"""Concurrency sanitizer: lock-order recorder + framework thread registry.

The runtime is a dozen cooperating background threads — serving dispatch
threads (``serving/engine.py``, ``serving/decode.py``), the disagg
session pumps and health tick (``serving/disagg/router.py``), the
async-pipeline stager (``fluid/async_pipeline.py``), heartbeat beaters
(``parallel/elastic.py``) — coordinating through a handful of framework
locks. A refactor that inverts two lock acquisitions, or parks a
blocking call under a lock, deadlocks (or convoys) only under load,
long after the diff landed. This module makes both hazard classes
observable *the first time the orders are exercised*, without needing
the unlucky interleaving:

- **Named locks** — framework locks are :class:`NamedLock` wrappers
  (``named_lock("serving.engine.admit")``). Lock names are per *lock
  class*, not per instance, so an order recorded on one engine applies
  to every engine (classic lockdep semantics).
- **Lock-order graph** — armed (``PADDLE_TPU_LOCK_SANITIZER=on`` or
  :func:`arm`), every acquisition while other named locks are held
  records a ``held -> acquiring`` edge with BOTH acquisition stacks.
  An edge whose reverse path already exists is a cycle: a
  ``potential-deadlock`` violation carrying the stacks of every edge on
  the cycle — the two threads' acquisition sites, attributed.
- **Blocking-under-lock** — instrumented blocking sites
  (:func:`note_blocking` at ``queue.get``, ``time.sleep``, device
  dispatch, FileStore directory scans) flag a ``blocking-under-lock``
  violation when the calling thread holds any named lock: the lock
  acquisition stack plus the blocking site stack.
- **Thread registry** — subsystems :func:`track_thread` their
  background threads under an owner token; ``stop()``/``close()`` call
  :func:`check_stopped`, which reports still-alive threads as
  ``thread-leak`` violations (and always returns their names, so tests
  can assert zero leaks even disarmed).

Off (the default), every hook is a single module-bool check —
``NamedLock`` delegates straight to the underlying ``threading``
primitive and ``note_blocking`` returns immediately — so the
instrumentation stays compiled into the hot paths permanently.

Metrics (armed): ``analysis.lock_graph_edges`` gauge,
``sanitizer.violations`` / ``threads.leaked`` counters, and
``lock_violation`` flight-recorder events (source ``sanitizer``).
Stdlib-only (+observability): importable from supervisor/crash paths
without accelerator init.
"""
import collections
import os
import threading
import traceback
import weakref

from .. import observability as obs

__all__ = [
    "LOCK_SANITIZER_ENV", "MAX_VIOLATIONS", "NamedLock", "arm",
    "armed", "check_stopped", "disarm", "dropped", "find_cycles",
    "held_locks", "live_threads", "lock_order_edges", "named_lock",
    "note_blocking", "owner_token", "report", "reset", "track_thread",
    "violations",
]

LOCK_SANITIZER_ENV = "PADDLE_TPU_LOCK_SANITIZER"

# the hot-path gate: every hook checks this single module bool
_on = os.environ.get(LOCK_SANITIZER_ENV, "").lower() in ("1", "on", "true")

MAX_VIOLATIONS = 256

_state = threading.Lock()   # guards everything below (never a NamedLock)
_edges = {}                 # (held_name, acq_name) -> edge record
_lock_names = set()         # every NamedLock name ever constructed
_violations = collections.deque(maxlen=MAX_VIOLATIONS)
_dropped = 0
_threads = {}               # owner token -> [weakref.ref(Thread)]
_tls = threading.local()    # .held = [(name, stack)] acquisition order


def armed():
    return _on


def arm():
    """Enable recording (tests / debugging sessions / CI lanes)."""
    global _on
    _on = True


def disarm():
    global _on
    _on = False


def reset():
    """Clear the lock-order graph, violations, and drop counter (keeps
    the thread registry and armed state — live threads stay tracked)."""
    global _dropped
    with _state:
        _edges.clear()
        _violations.clear()
        _dropped = 0


def _stack(skip=2, limit=9):
    """Compact acquisition/blocking-site stack: innermost frames last,
    the sanitizer's own frames stripped."""
    frames = traceback.extract_stack(limit=limit)
    if skip:
        frames = frames[:-skip]
    return ["%s:%d in %s" % (f.filename, f.lineno, f.name)
            for f in frames[-5:]]


def _held():
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def held_locks():
    """Names of named locks the CALLING thread currently holds, in
    acquisition order."""
    return [name for name, _stk in _held()]


def _record_violation(v):
    """Append one violation (bounded; overflow counts as dropped) and
    mirror it to the obs hub. Called with ``_state`` NOT held."""
    global _dropped
    with _state:
        if len(_violations) == _violations.maxlen:
            _dropped += 1
        _violations.append(v)
    obs.inc("sanitizer.violations")
    obs.event("lock_violation", source="sanitizer", check=v["check"],
              locks=",".join(v.get("locks", ())),
              threads=",".join(v.get("threads", ())))


def violations():
    """Snapshot of recorded violations (list of dicts, oldest first)."""
    with _state:
        return list(_violations)


def dropped():
    """Violations discarded because the bounded buffer overflowed."""
    with _state:
        return _dropped


# ---------------------------------------------------------------------------
# named locks + the lock-order graph
# ---------------------------------------------------------------------------

class NamedLock:
    """A ``threading.Lock``/``RLock`` with a lock-class name that
    registers acquisition order in the sanitizer's graph when armed.
    Supports the full context-manager / acquire / release protocol."""

    __slots__ = ("name", "recursive", "_lock")

    def __init__(self, name, recursive=False):
        self.name = str(name)
        self.recursive = bool(recursive)
        self._lock = threading.RLock() if recursive else threading.Lock()
        with _state:
            _lock_names.add(self.name)

    def acquire(self, blocking=True, timeout=-1):
        ok = self._lock.acquire(blocking, timeout)
        if ok and _on:
            self._note_acquire()
        return ok

    def release(self):
        if _on:
            self._note_release()
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # -- armed-mode bookkeeping (off the hot path) -----------------------
    def _note_acquire(self):
        held = _held()
        stack = _stack(skip=3)
        for held_name, held_stack in held:
            if held_name != self.name:  # RLock re-entry adds no edge
                _add_edge(held_name, self.name, held_stack, stack)
        held.append((self.name, stack))

    def _note_release(self):
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == self.name:
                del held[i]
                break

    def locked(self):
        if self.recursive:
            # RLock has no locked(); a non-blocking probe answers it
            if self._lock.acquire(blocking=False):
                self._lock.release()
                return False
            return True
        return self._lock.locked()

    def __repr__(self):
        return "NamedLock(%r%s)" % (
            self.name, ", recursive=True" if self.recursive else "")


def named_lock(name, recursive=False):
    """Build a :class:`NamedLock`. ``name`` is the lock *class*
    (e.g. ``"serving.engine.admit"``) shared by every instance of the
    owning component, so orders learned on one instance guard all."""
    return NamedLock(name, recursive=recursive)


def _add_edge(a, b, stack_a, stack_b):
    me = threading.current_thread().name
    with _state:
        if (a, b) in _edges:
            return
        _edges[(a, b)] = {
            "from": a, "to": b, "thread": me,
            "stacks": [list(stack_a), list(stack_b)],
        }
        n_edges = len(_edges)
        path = _path_between(b, a)  # reverse path => cycle through (a, b)
    obs.set_gauge("analysis.lock_graph_edges", n_edges)
    if path is None:
        return
    # cycle: a -> b (new edge, this thread) then b ->* a (recorded by
    # other threads). Attach every edge's acquisition stacks — for the
    # two-lock case that is exactly "both threads' stacks".
    cycle_names = [a, b] + [e["to"] for e in path if e["to"] != a]
    _record_violation({
        "check": "potential-deadlock",
        "locks": cycle_names,
        "threads": [me] + [e["thread"] for e in path],
        "stacks": [list(stack_a), list(stack_b)]
        + [s for e in path for s in e["stacks"]],
        "message": "lock-order cycle %s: this thread acquired %r while "
                   "holding %r, but the reverse order is already "
                   "recorded — two threads interleaving these paths "
                   "deadlock" % (" -> ".join(cycle_names + [a]), b, a),
    })


def _path_between(src, dst):
    """Edge records along some ``src ->* dst`` path in the recorded
    graph, or None. Called with ``_state`` held."""
    adj = collections.defaultdict(list)
    for (x, _y), rec in _edges.items():
        adj[x].append(rec)
    parent = {src: None}
    queue = collections.deque([src])
    while queue:
        node = queue.popleft()
        for rec in adj.get(node, ()):
            nxt = rec["to"]
            if nxt in parent:
                continue
            parent[nxt] = (node, rec)
            if nxt == dst:
                path = []
                cur = nxt
                while parent[cur] is not None:
                    prev, rec2 = parent[cur]
                    path.append(rec2)
                    cur = prev
                path.reverse()
                return path
            queue.append(nxt)
    return None


def lock_order_edges():
    """Snapshot of the recorded lock-order graph: list of edge dicts
    (``from``/``to``/``thread``/``stacks``), deterministic order."""
    with _state:
        return [dict(_edges[k]) for k in sorted(_edges)]


def find_cycles():
    """Every simple cycle in the recorded graph as a list of lock-name
    lists (each rotated to start at its smallest name, deduplicated)."""
    with _state:
        edges = list(_edges)
    adj = collections.defaultdict(list)
    for a, b in edges:
        adj[a].append(b)
    cycles = set()

    def walk(start, node, trail):
        for nxt in adj.get(node, ()):
            if nxt == start:
                cyc = trail[:]
                k = cyc.index(min(cyc))
                cycles.add(tuple(cyc[k:] + cyc[:k]))
            elif nxt not in trail:
                walk(start, nxt, trail + [nxt])

    for a in sorted(adj):
        walk(a, a, [a])
    return [list(c) for c in sorted(cycles)]


# ---------------------------------------------------------------------------
# blocking-call-while-holding-lock
# ---------------------------------------------------------------------------

def note_blocking(what):
    """Mark a blocking call site (``queue.get``, ``time.sleep``, device
    dispatch, directory scans). Armed + any named lock held => a
    ``blocking-under-lock`` violation with the lock acquisition stack
    and this call site's stack. Disarmed: one module-bool check."""
    if not _on:
        return
    held = _held()
    if not held:
        return
    lock_name, lock_stack = held[-1]
    _record_violation({
        "check": "blocking-under-lock",
        "what": str(what),
        "locks": [n for n, _s in held],
        "threads": [threading.current_thread().name],
        "stacks": [list(lock_stack), _stack(skip=2)],
        "message": "blocking call %r while holding lock(s) %s — every "
                   "other thread contending the lock convoys behind "
                   "this wait; move the blocking call outside the "
                   "critical section"
                   % (what, ", ".join(repr(n) for n, _s in held)),
    })


# ---------------------------------------------------------------------------
# framework thread registry
# ---------------------------------------------------------------------------

def owner_token(kind, name, instance=None):
    """Stable registry key for one component instance's threads, e.g.
    ``owner_token("serving-engine", self.name, self)``."""
    tok = "%s:%s" % (kind, name)
    if instance is not None:
        tok += ":%x" % id(instance)
    return tok


def track_thread(thread, owner):
    """Register a framework background thread under ``owner`` (an
    :func:`owner_token`). Always on — the registry is how
    ``stop()``/``close()`` prove zero leaked threads."""
    with _state:
        refs = _threads.setdefault(str(owner), [])
        refs[:] = [r for r in refs
                   if r() is not None and r().is_alive()]
        refs.append(weakref.ref(thread))


def live_threads(owner=None):
    """Still-alive registered threads (for ``owner``, or all)."""
    with _state:
        if owner is None:
            refs = [r for rs in _threads.values() for r in rs]
        else:
            refs = list(_threads.get(str(owner), ()))
    out = []
    for r in refs:
        t = r()
        if t is not None and t.is_alive():
            out.append(t)
    return out


def check_stopped(owner, grace=1.0):
    """Assert every thread registered under ``owner`` has exited —
    called at the END of ``stop()``/``close()``, after joins. Waits up
    to ``grace`` seconds for stragglers (joins already signalled them),
    then returns the leaked thread names; armed, each leak is also a
    ``thread-leak`` violation and a ``threads.leaked`` count."""
    deadline = None
    while True:
        alive = live_threads(owner)
        if not alive:
            break
        import time as _time
        now = _time.monotonic()
        if deadline is None:
            deadline = now + max(0.0, float(grace))
        if now >= deadline:
            break
        _time.sleep(0.01)
    with _state:
        if not alive:
            _threads.pop(str(owner), None)
        else:
            refs = _threads.get(str(owner))
            if refs is not None:
                refs[:] = [r for r in refs
                           if r() is not None and r().is_alive()]
    if not alive:
        return []
    names = [t.name for t in alive]
    obs.inc("threads.leaked", len(names))
    if _on:
        _record_violation({
            "check": "thread-leak",
            "owner": str(owner),
            "locks": [],
            "threads": names,
            "stacks": [_stack(skip=2)],
            "message": "stop()/close() of %s left %d thread(s) alive: "
                       "%s — the component's shutdown path does not "
                       "join every thread it spawned"
                       % (owner, len(names), ", ".join(names)),
        })
    return names


# ---------------------------------------------------------------------------
# report surface (CLI --concurrency, tests, lanes)
# ---------------------------------------------------------------------------

def report():
    """One dict over everything recorded: registered lock classes, the
    order graph, cycles, violations (+ drop count), live registered
    threads. Stable ordering — lanes can diff it."""
    with _state:
        locks = sorted(_lock_names)
        n_dropped = _dropped
    live = sorted(t.name for t in live_threads())
    return {
        "armed": _on,
        "locks": locks,
        "edges": lock_order_edges(),
        "cycles": find_cycles(),
        "violations": violations(),
        "violations_dropped": n_dropped,
        "live_threads": live,
    }
