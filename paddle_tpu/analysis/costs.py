"""Static cost model: per-op FLOPs, bytes moved, and a roofline
prediction of step time / MFU — computed BEFORE any XLA compile.

Like :mod:`.shapes`, this pass reuses the op lowering registry as the
single rule set: each op's lowering is traced with ``jax.make_jaxpr``
over the abstract shape env, and FLOPs are counted primitive by
primitive from the jaxpr (``dot_general``: 2·M·N·K,
``conv_general_dilated``: 2·out·k·Cin/g, elementwise: one per output
element, pure data movement: zero). Bytes per op are the op's input +
output footprints — the HBM traffic an unfused op would move, i.e. the
roofline's memory leg. The symbolic ``backward`` op is costed
analytically as 2x its forward region (the classic fwd:bwd ratio; the
vjp replay's duplicated forward is CSE'd by XLA, see lowering.run_ops).

The device table below is the ONE shared peak-FLOPs/HBM table —
``bench.py`` imports :func:`peak_flops` and
:func:`bert_train_flops_per_token` from here so the bench and the
analyzer can never drift. Env overrides (all optional) calibrate or
pin a profile where no table entry matches (CPU smoke lanes, tests):

- ``PADDLE_TPU_PEAK_FLOPS`` — peak FLOPs/s
- ``PADDLE_TPU_HBM_BYTES``  — memory capacity in bytes
- ``PADDLE_TPU_HBM_BW``     — memory bandwidth in bytes/s
- ``PADDLE_TPU_ICI_BW``     — per-chip interconnect bandwidth in
  bytes/s (the gradient-allreduce leg; see
  :func:`ring_allreduce_seconds`)
- ``PADDLE_TPU_DCN_BW``     — per-chip CROSS-SLICE bandwidth in
  bytes/s (the data-center network leg a multi-slice allreduce rides)
- ``PADDLE_TPU_SLICE_CHIPS`` — chips one ICI slice can reach; groups
  wider than this pay the DCN wire (see :func:`allreduce_bandwidth`)
"""
import os

__all__ = [
    "DeviceProfile", "DEVICE_TABLE", "device_profile", "peak_flops",
    "bert_train_flops_per_token", "OpCost", "op_costs", "jaxpr_flops",
    "CostReport", "analyze_cost", "predict_program",
    "ring_allreduce_seconds", "allreduce_bandwidth",
    "pipeline_bubble_fraction", "dp_grad_bytes", "ICI_BW_ENV",
    "DCN_BW_ENV", "SLICE_CHIPS_ENV", "CALIBRATION_ENV",
    "load_calibration",
]

PEAK_FLOPS_ENV = "PADDLE_TPU_PEAK_FLOPS"
HBM_BYTES_ENV = "PADDLE_TPU_HBM_BYTES"
HBM_BW_ENV = "PADDLE_TPU_HBM_BW"
ICI_BW_ENV = "PADDLE_TPU_ICI_BW"
DCN_BW_ENV = "PADDLE_TPU_DCN_BW"
SLICE_CHIPS_ENV = "PADDLE_TPU_SLICE_CHIPS"
# path to a calibration JSON written by DeviceProfile.calibrated_from;
# device_profile() layers it OVER the table match and UNDER the env
# overrides (operator pins always win)
CALIBRATION_ENV = "PADDLE_TPU_CALIBRATION_FILE"


class DeviceProfile:
    """Roofline constants of one accelerator: bf16 peak FLOPs/s, HBM
    capacity (bytes), HBM bandwidth (bytes/s), per-chip ICI
    (inter-chip interconnect) bandwidth (bytes/s — all links combined,
    the figure a ring allreduce rides), per-chip DCN bandwidth
    (bytes/s — what a collective pays once it crosses a slice
    boundary), and the chip count one ICI slice tops out at. Any field
    may be None (unknown) — consumers skip the corresponding
    check/prediction."""

    __slots__ = ("name", "peak_flops", "hbm_bytes", "hbm_bw", "ici_bw",
                 "dcn_bw", "slice_chips")

    def __init__(self, name, peak_flops=None, hbm_bytes=None, hbm_bw=None,
                 ici_bw=None, dcn_bw=None, slice_chips=None):
        self.name = name
        self.peak_flops = peak_flops
        self.hbm_bytes = hbm_bytes
        self.hbm_bw = hbm_bw
        self.ici_bw = ici_bw
        self.dcn_bw = dcn_bw
        self.slice_chips = slice_chips

    def copy(self):
        return DeviceProfile(self.name, self.peak_flops, self.hbm_bytes,
                             self.hbm_bw, self.ici_bw, self.dcn_bw,
                             self.slice_chips)

    def to_dict(self):
        return {"name": self.name, "peak_flops": self.peak_flops,
                "hbm_bytes": self.hbm_bytes, "hbm_bw": self.hbm_bw,
                "ici_bw": self.ici_bw, "dcn_bw": self.dcn_bw,
                "slice_chips": self.slice_chips}

    def __repr__(self):
        return ("DeviceProfile(%r, peak_flops=%r, hbm_bytes=%r, "
                "hbm_bw=%r, ici_bw=%r, dcn_bw=%r, slice_chips=%r)"
                % (self.name, self.peak_flops, self.hbm_bytes,
                   self.hbm_bw, self.ici_bw, self.dcn_bw,
                   self.slice_chips))

    @classmethod
    def calibrated_from(cls, ledger, measured_steps=None, path=None):
        """Fit *effective* peak-FLOPs / HBM-BW from measured step
        times in an executable ledger (the live
        ``observability.ExecutableLedger``, its ``snapshot()`` dict,
        or a bare entry list). ``measured_steps`` ({fingerprint:
        seconds}) augments/overrides the per-entry
        ``measured_step_seconds``.

        Two fit rungs, best first:

        - **ratio**: entries carrying both a prediction made under a
          known profile (``predicted["device"]``) and a measurement
          scale that profile's peak_flops/hbm_bw by the median
          ``predicted_step / measured_step``. The roofline's per-op
          ``max(compute leg, memory leg)`` sum scales inversely with
          a common factor on both constants, so the re-prediction
          under the calibrated profile lands on the measurement
          exactly (modulo run-to-run noise).
        - **rate** (fallback, no usable prediction): effective
          FLOPs/s and bytes/s as the median ``flops / measured`` and
          ``bytes / measured`` over entries (XLA's ``cost_analysis``
          figures when present, else the analyzer totals). An upper
          bound per leg — the per-op max-sum may over-predict up to
          2x — but it turns "no profile" into a usable one.

        With ``path`` the fit is also written as a calibration JSON
        that :func:`device_profile` layers under the env overrides
        (point ``PADDLE_TPU_CALIBRATION_FILE`` at it). Returns the
        calibrated profile, or None when no entry had a usable
        measurement."""
        entries, extra_measured = _ledger_entries(ledger)
        measured = dict(extra_measured)
        measured.update(measured_steps or {})
        ratio, peaks, bws, hbm_caps = [], [], [], []
        rate_flops, rate_bytes = [], []
        n_used = 0
        for e in entries:
            if not isinstance(e, dict):
                continue
            fp = e.get("fingerprint")
            t = measured.get(fp) or e.get("measured_step_seconds")
            if not t or t <= 0:
                continue
            n_used += 1
            pred = e.get("predicted") or {}
            dev = pred.get("device") or {}
            ps = pred.get("predicted_step_seconds")
            if ps and ps > 0 and (dev.get("peak_flops")
                                  or dev.get("hbm_bw")):
                r = float(ps) / float(t)
                ratio.append(r)
                if dev.get("peak_flops"):
                    peaks.append(float(dev["peak_flops"]) * r)
                if dev.get("hbm_bw"):
                    bws.append(float(dev["hbm_bw"]) * r)
                if dev.get("hbm_bytes"):
                    hbm_caps.append(float(dev["hbm_bytes"]))
            xla = e.get("xla") or {}
            f = xla.get("flops") or pred.get("total_flops")
            b = xla.get("bytes_accessed") or pred.get("total_bytes")
            if f and f > 0:
                rate_flops.append(float(f) / float(t))
            if b and b > 0:
                rate_bytes.append(float(b) / float(t))
        if peaks or bws:
            method = "ratio"
            peak = _median(peaks)
            bw = _median(bws)
        elif rate_flops or rate_bytes:
            method = "rate"
            peak = _median(rate_flops)
            bw = _median(rate_bytes)
        else:
            return None
        prof = cls("calibrated", peak_flops=peak, hbm_bw=bw,
                   hbm_bytes=_median(hbm_caps))
        if path:
            doc = prof.to_dict()
            doc["fit"] = {
                "method": method,
                "entries_used": n_used,
                "ratio_median": round(_median(ratio), 6)
                if ratio else None,
            }
            import json

            tmp = "%s.tmp-%d" % (path, os.getpid())
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1)
            os.replace(tmp, path)
        return prof


# Public per-chip figures, matched by device_kind substring — the
# LONGEST matching key wins ("v5p" beats "v5" regardless of row order,
# so adding rows can never shadow existing ones). bf16 peak FLOPs/s,
# HBM bytes, HBM bytes/s, ICI bytes/s (all links per chip), DCN
# bytes/s per chip, max chips per ICI slice.
DEVICE_TABLE = [
    ("v6", DeviceProfile("v6e", 918e12, 32e9, 1640e9, 448e9,
                         25e9, 256)),
    ("v5p", DeviceProfile("v5p", 459e12, 95e9, 2765e9, 600e9,
                          25e9, 8960)),
    ("v5e", DeviceProfile("v5e", 197e12, 16e9, 819e9, 200e9,
                          12.5e9, 256)),
    ("v5", DeviceProfile("v5e", 197e12, 16e9, 819e9, 200e9,
                         12.5e9, 256)),
    ("v4", DeviceProfile("v4", 275e12, 32e9, 1228e9, 300e9,
                         12.5e9, 4096)),
    ("v3", DeviceProfile("v3", 123e12, 32e9, 900e9, 82e9,
                         6.25e9, 1024)),
    ("v2", DeviceProfile("v2", 45e12, 16e9, 700e9, 62e9,
                         6.25e9, 512)),
]


def _env_float(name):
    v = os.environ.get(name)
    if not v:
        return None
    try:
        return float(v)
    except ValueError:
        return None


# one-slot mtime cache: the calibration file is read once per mtime,
# not once per device_profile() call (executors resolve profiles on
# every compile)
_cal_cache = {"path": None, "mtime": None, "doc": None}


def load_calibration(path=None):
    """The calibration JSON written by
    :meth:`DeviceProfile.calibrated_from`, as a dict of profile fields
    (or None). ``path`` defaults to ``$PADDLE_TPU_CALIBRATION_FILE``.
    A torn/corrupt file (truncated mid-write, non-JSON bytes, wrong
    schema, bool/NaN/inf constants) warns once per mtime and resolves
    to None — the profile falls back to the table; a stale or mangled
    calibration must never crash a serving process."""
    path = path or os.environ.get(CALIBRATION_ENV)
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return None
    if _cal_cache["path"] == path and _cal_cache["mtime"] == mtime:
        return _cal_cache["doc"]
    doc = None
    why = None
    try:
        import json
        import math

        with open(path, "r", encoding="utf-8") as f:
            raw = json.load(f)
        if isinstance(raw, dict):
            doc = {}
            for k in ("name", "peak_flops", "hbm_bytes", "hbm_bw",
                      "ici_bw", "dcn_bw", "slice_chips"):
                v = raw.get(k)
                if k == "name":
                    if isinstance(v, str):
                        doc[k] = v
                elif (isinstance(v, (int, float))
                      and not isinstance(v, bool)
                      and math.isfinite(v) and v > 0):
                    doc[k] = float(v)
            if not any(k != "name" for k in doc):
                doc = None
                why = "no usable numeric field"
        else:
            why = "top-level %s, want an object" % type(raw).__name__
    except Exception as e:  # noqa: BLE001 — torn write, bad bytes, ...
        doc = None
        why = "%s: %s" % (type(e).__name__, str(e)[:120])
    if doc is None and why is not None:
        # once per mtime: the cache short-circuits until the file
        # changes again, so a bad file cannot spam a serving loop
        import warnings

        warnings.warn(
            "ignoring corrupt calibration file %s (%s); falling back "
            "to the device table" % (path, why), RuntimeWarning,
            stacklevel=2)
    _cal_cache.update(path=path, mtime=mtime, doc=doc)
    return doc


def _median(xs):
    xs = sorted(x for x in xs if x is not None)
    if not xs:
        return None
    n = len(xs)
    mid = xs[n // 2]
    return mid if n % 2 else (xs[n // 2 - 1] + mid) / 2.0


def _ledger_entries(ledger):
    """(entries, measured) from an ExecutableLedger, its snapshot()
    dict, or a bare entry list."""
    if ledger is None:
        return [], {}
    snap = getattr(ledger, "snapshot", None)
    if callable(snap):
        ledger = snap()
    if isinstance(ledger, dict):
        return (list(ledger.get("entries") or ()),
                dict(ledger.get("measured") or {}))
    return list(ledger), {}


def device_profile(device_kind=None):
    """Resolve a :class:`DeviceProfile` for a jax ``device_kind`` string
    (substring match against the table; when several keys match, the
    LONGEST — most specific — wins, so the result is independent of
    table row order), then layer the calibration file
    (``$PADDLE_TPU_CALIBRATION_FILE``, measured effective constants)
    and finally the env overrides (operator pins always win). Returns
    None when neither the table, the calibration, nor any override
    knows the device — callers must treat that as "no prediction
    possible"."""
    prof = None
    dk = (device_kind or "").lower()
    best_key = None
    for key, p in DEVICE_TABLE:
        if key in dk and (best_key is None or len(key) > len(best_key)):
            best_key = key
            prof = p.copy()
    cal = load_calibration()
    over = {
        "peak_flops": _env_float(PEAK_FLOPS_ENV),
        "hbm_bytes": _env_float(HBM_BYTES_ENV),
        "hbm_bw": _env_float(HBM_BW_ENV),
        "ici_bw": _env_float(ICI_BW_ENV),
        "dcn_bw": _env_float(DCN_BW_ENV),
        "slice_chips": _env_float(SLICE_CHIPS_ENV),
    }
    if (prof is None and cal is None
            and not any(v is not None for v in over.values())):
        return None
    if prof is None:
        prof = DeviceProfile(device_kind or "env")
    if cal is not None:
        for k, v in cal.items():
            if k != "name":
                setattr(prof, k, v)
        prof.name = "%s+cal" % prof.name
    for k, v in over.items():
        if v is not None:
            setattr(prof, k, v)
    return prof


def peak_flops(device_kind):
    """bf16 peak FLOPs/s for a device_kind, or None (bench.py's
    ``_peak_flops``, now table-backed here)."""
    p = device_profile(device_kind)
    return p.peak_flops if p is not None else None


def bert_train_flops_per_token(cfg, seq):
    """Analytic matmul FLOPs per trained token (fwd + bwd ~= 3x fwd) —
    bench.py's ``_flops_per_token_train``, shared so the bench MFU and
    the analyzer's roofline use one formula."""
    d, L, V = cfg.hidden, cfg.num_layers, cfg.vocab_size
    per_layer = 12 * d * d          # qkv (3d^2) + proj (d^2) + mlp (8d^2)
    attn = 4 * seq * d              # QK^T and AV rows for one token
    fwd = 2 * (L * (per_layer + attn) + d * V)
    return 3 * fwd


def ring_allreduce_seconds(n_bytes, n_shards, ici_bw):
    """Bandwidth term of one (ring or two-shot) allreduce of
    ``n_bytes`` over ``n_shards`` chips at ``ici_bw`` bytes/s per chip:
    every chip sends and receives ``2 (n-1)/n * n_bytes`` concurrently,
    so the wall time is that divided by the per-chip bandwidth. 0.0
    when there is nothing to reduce across (n < 2) or the bandwidth is
    unknown."""
    n = max(1, int(n_shards))
    if n < 2 or not ici_bw:
        return 0.0
    return 2.0 * (n - 1) / n * float(n_bytes) / float(ici_bw)


def allreduce_bandwidth(profile, group_size):
    """(bytes/s, wire) the allreduce over ``group_size`` chips rides:
    the ICI figure while the group fits one slice, the per-chip DCN
    figure once it spills over ``profile.slice_chips``. Falls back to
    ICI when the DCN figure is unknown (single-slice optimism is better
    than no prediction)."""
    if profile is None:
        return None, "ici"
    n = max(1, int(group_size))
    cap = profile.slice_chips
    if cap and n > int(cap) and profile.dcn_bw:
        return profile.dcn_bw, "dcn"
    return profile.ici_bw, "ici"


def pipeline_bubble_fraction(pp, microbatches):
    """GPipe fill/drain overhead as a fraction of useful compute:
    (pp-1)/microbatches. 0.0 for a single stage; with one microbatch a
    pp-stage schedule is fully serial (fraction pp-1)."""
    pp = max(1, int(pp))
    m = max(1, int(microbatches or 1))
    return float(pp - 1) / float(m)


def dp_grad_bytes(program, env=None):
    """fp32 bytes one data-parallel step must allreduce: the backward
    op's gradient footprint when the program trains, else the
    trainable-parameter footprint (inference dumps of a training model
    — what an equivalent training step would sync). Deterministic, so
    the comm prediction below and parallel/comms' live wire accounting
    agree on what counts."""
    import numpy as np

    gb = program.global_block()
    total = 0.0
    for op in gb.ops:
        if op.type != "backward":
            continue
        for g in op.output("Grads"):
            if env is not None and g in env:
                total += _spec_nbytes(env[g])
    if total:
        return total
    for p in gb.all_parameters():
        if not getattr(p, "trainable", True):
            continue
        shape = tuple(getattr(p, "shape", ()) or ())
        if not shape or not all(isinstance(d, int) and d > 0
                                for d in shape):
            continue
        total += float(np.prod(shape)) * 4.0
    return total


# -- per-primitive FLOP counting over a jaxpr -------------------------------

# primitives that move/reshape data without arithmetic
_ZERO_FLOP_PRIMS = frozenset({
    "reshape", "broadcast_in_dim", "transpose", "convert_element_type",
    "bitcast_convert_type", "slice", "dynamic_slice",
    "dynamic_update_slice", "concatenate", "pad", "squeeze", "rev",
    "iota", "copy", "device_put", "stop_gradient", "split",
    "gather", "expand_dims", "real", "imag", "empty",
})


def _aval_size(aval):
    n = 1
    for d in getattr(aval, "shape", ()) or ():
        n *= int(d)
    return n


def _sub_jaxprs(params):
    subs = []
    for v in params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for u in vs:
            if hasattr(u, "jaxpr"):          # ClosedJaxpr
                subs.append(u.jaxpr)
            elif hasattr(u, "eqns"):         # Jaxpr
                subs.append(u)
    return subs


def jaxpr_flops(jaxpr):
    """Deterministic FLOP count of a jaxpr: exact for matmul/conv, one
    per output element for everything arithmetic, zero for pure data
    movement. ``scan`` bodies multiply by trip count; ``while`` bodies
    count one trip (trip count is value-dependent); ``cond`` takes the
    most expensive branch."""
    total = 0.0
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        subs = _sub_jaxprs(eqn.params)
        if subs:
            inner = [jaxpr_flops(s) for s in subs]
            if prim == "scan":
                total += float(eqn.params.get("length", 1)) * sum(inner)
            elif prim == "cond":
                total += max(inner)
            else:  # pjit / while / remat / custom_* wrappers
                total += sum(inner)
            continue
        total += _prim_flops(eqn, prim)
    return total


def _prim_flops(eqn, prim):
    out_size = max((_aval_size(v.aval) for v in eqn.outvars), default=0)
    if prim == "dot_general":
        (lhs_c, _rhs_c), _ = eqn.params["dimension_numbers"]
        lhs_shape = eqn.invars[0].aval.shape
        k = 1
        for d in lhs_c:
            k *= int(lhs_shape[d])
        return 2.0 * out_size * k
    if prim == "conv_general_dilated":
        dn = eqn.params["dimension_numbers"]
        rhs = eqn.invars[1].aval
        out_chan = int(rhs.shape[dn.rhs_spec[0]])
        # per output element: 2 * (kernel spatial x in-chan-per-group)
        return 2.0 * out_size * _aval_size(rhs) / max(out_chan, 1)
    if prim in _ZERO_FLOP_PRIMS or prim.startswith("scatter"):
        return 0.0
    if prim.startswith("reduce") or prim.startswith("arg") \
            or prim == "cumsum" or prim.startswith("cum"):
        # one op per INPUT element: reductions shrink the output
        return float(max((_aval_size(v.aval) for v in eqn.invars
                          if hasattr(v, "aval")), default=out_size))
    return float(out_size)


# -- per-op costing over a Program ------------------------------------------

class OpCost:
    """FLOPs + bytes of one global-block op."""

    __slots__ = ("op_index", "op_type", "flops", "bytes", "op")

    def __init__(self, op_index, op_type, flops, bytes_, op=None):
        self.op_index = op_index
        self.op_type = op_type
        self.flops = flops
        self.bytes = bytes_
        self.op = op

    @property
    def intensity(self):
        """Arithmetic intensity (flops per HBM byte)."""
        if not self.bytes:
            return None
        return self.flops / self.bytes

    def to_dict(self):
        d = {"op_index": self.op_index, "op_type": self.op_type,
             "flops": round(self.flops, 1), "bytes": round(self.bytes, 1)}
        if self.intensity is not None:
            d["intensity"] = round(self.intensity, 3)
        return d


def op_costs(program, env, is_test=False, platform="cpu"):
    """Per-op FLOPs/bytes for the global block by tracing each op's
    lowering with ``jax.make_jaxpr`` over the abstract env from
    :func:`.shapes.propagate`. Ops whose inputs never resolved (or
    whose lowering cannot trace) are skipped. The ``backward`` op is
    costed analytically: 2x the FLOPs/bytes of its forward region."""
    import jax

    from ..fluid import lowering
    from ..ops.registry import LowerContext
    from . import walker

    gb = program.global_block()
    var_lookup = lowering._make_var_lookup(gb)
    rng = jax.random.PRNGKey(0)
    out = []
    fwd_flops = 0.0   # running non-backward totals (the backward region)
    fwd_bytes = 0.0
    for i, op in enumerate(gb.ops):
        if op.type == "backward":
            grads = op.output("Grads")
            grad_bytes = sum(
                _spec_nbytes(env[g]) for g in grads if g in env)
            out.append(OpCost(i, op.type, 2.0 * fwd_flops,
                              2.0 * fwd_bytes + grad_bytes, op=op))
            continue
        reads = walker._op_reads(program, op)
        if any(n not in env for n in reads):
            continue
        sub_env = {n: env[n] for n in sorted(reads)}

        def f(e, _op=op, _i=i):
            ctx = LowerContext(rng=rng, is_test=is_test, program=program,
                               platform=platform)
            ctx.run_ops = lowering.run_ops
            e = lowering.apply_op(_op, dict(e), ctx, var_lookup, op_tag=_i)
            return {n: e[n] for ns in _op.outputs.values()
                    for n in ns if n in e}

        try:
            closed = jax.make_jaxpr(f)(sub_env)
        except Exception:  # noqa: BLE001 — shapes.propagate reports these
            continue
        flops = jaxpr_flops(closed.jaxpr)
        nbytes = (sum(_spec_nbytes(env[n]) for n in reads)
                  + sum(_spec_nbytes(env[n])
                        for ns in op.outputs.values() for n in ns
                        if n in env))
        out.append(OpCost(i, op.type, flops, float(nbytes), op=op))
        fwd_flops += flops
        fwd_bytes += float(nbytes)
    return out


def _spec_nbytes(spec):
    import numpy as np

    n = 1
    for d in getattr(spec, "shape", ()) or ():
        n *= int(d)
    return n * np.dtype(spec.dtype).itemsize


# -- report -----------------------------------------------------------------

class CostReport:
    """Per-op and per-program FLOPs/bytes + roofline prediction against
    one :class:`DeviceProfile`, plus the liveness peak-HBM estimate and
    (when ``dp_shards > 1``) the interconnect leg: predicted gradient
    allreduce seconds and data-parallel scaling efficiency."""

    def __init__(self, per_op, memory=None, profile=None, dp_shards=1,
                 grad_bytes=0.0, comm_overlap_ratio=0.0):
        self.per_op = list(per_op)
        self.memory = memory            # analysis.memory.MemoryReport
        self.profile = profile          # DeviceProfile or None
        self.dp_shards = max(1, int(dp_shards))
        self.grad_bytes = float(grad_bytes)
        self.comm_overlap_ratio = min(1.0, max(0.0,
                                               float(comm_overlap_ratio)))
        self.total_flops = float(sum(c.flops for c in self.per_op))
        self.total_bytes = float(sum(c.bytes for c in self.per_op))

    @property
    def intensity(self):
        if not self.total_bytes:
            return None
        return self.total_flops / self.total_bytes

    @property
    def predicted_step_seconds(self):
        """Roofline: each op pays max(compute leg, memory leg); the
        step is their sum (sequential dependency chain)."""
        p = self.profile
        if p is None or (not p.peak_flops and not p.hbm_bw):
            return None
        t = 0.0
        for c in self.per_op:
            legs = []
            if p.peak_flops:
                legs.append(c.flops / p.peak_flops)
            if p.hbm_bw:
                legs.append(c.bytes / p.hbm_bw)
            t += max(legs)
        return t

    @property
    def predicted_mfu(self):
        p = self.profile
        t = self.predicted_step_seconds
        if not t or p is None or not p.peak_flops:
            return None
        return self.total_flops / (t * p.peak_flops)

    @property
    def bound(self):
        """Whether the program as a whole is compute- or memory-bound
        under the profile (None when unpredictable)."""
        p = self.profile
        if p is None or not p.peak_flops or not p.hbm_bw:
            return None
        return ("compute"
                if self.total_flops / p.peak_flops
                >= self.total_bytes / p.hbm_bw else "memory")

    @property
    def comm_wire(self):
        """Which wire the gradient allreduce rides: "ici" while the dp
        group fits one slice, "dcn" once it spills past the profile's
        slice_chips."""
        _, wire = allreduce_bandwidth(self.profile, self.dp_shards)
        return wire

    @property
    def predicted_comm_seconds(self):
        """Gradient-allreduce wall seconds per step over the profile's
        interconnect — ICI while the dp group fits one slice, DCN when
        it crosses slices. None when there is no dp group, no gradient
        footprint, or the bandwidth is unknown."""
        bw, _ = allreduce_bandwidth(self.profile, self.dp_shards)
        if self.dp_shards < 2 or not self.grad_bytes or not bw:
            return None
        return ring_allreduce_seconds(self.grad_bytes, self.dp_shards, bw)

    @property
    def scaling_efficiency(self):
        """Predicted dp scaling efficiency: compute time over compute
        plus the EXPOSED comm leg (comm scaled by what bucketed overlap
        cannot hide). 1.0 means free scaling; None when either leg is
        unpredictable."""
        t = self.predicted_step_seconds
        c = self.predicted_comm_seconds
        if not t or c is None:
            return None
        exposed = c * (1.0 - self.comm_overlap_ratio)
        return t / (t + exposed)

    def hottest(self, k=5):
        """Top-k ops by FLOPs, descending (stable: ties break on op
        index)."""
        return sorted(self.per_op,
                      key=lambda c: (-c.flops, c.op_index))[:k]

    def to_dict(self, top=16):
        d = {
            "n_ops_costed": len(self.per_op),
            "total_flops": round(self.total_flops, 1),
            "total_bytes": round(self.total_bytes, 1),
        }
        if self.intensity is not None:
            d["intensity"] = round(self.intensity, 3)
        if self.profile is not None:
            d["device"] = self.profile.to_dict()
        t = self.predicted_step_seconds
        if t is not None:
            d["predicted_step_seconds"] = float("%.6g" % t)
        mfu = self.predicted_mfu
        if mfu is not None:
            d["predicted_mfu"] = round(mfu, 4)
        if self.bound is not None:
            d["bound"] = self.bound
        if self.memory is not None:
            d["memory"] = self.memory.to_dict()
        if self.dp_shards > 1 and self.grad_bytes:
            comm = {
                "dp_shards": self.dp_shards,
                "grad_bytes": round(self.grad_bytes, 1),
                "overlap_ratio": round(self.comm_overlap_ratio, 4),
                "wire": self.comm_wire,
            }
            c = self.predicted_comm_seconds
            if c is not None:
                comm["predicted_allreduce_seconds"] = float("%.6g" % c)
            eff = self.scaling_efficiency
            if eff is not None:
                comm["scaling_efficiency"] = round(eff, 4)
            d["comm"] = comm
        d["hottest_ops"] = [c.to_dict() for c in self.hottest(top)]
        return d


def analyze_cost(program, env=None, feed_specs=None, state_specs=None,
                 feed_names=None, fetch_names=(), state_names=None,
                 is_test=False, platform="cpu", default_dim=None,
                 device_kind=None, param_shards=1, act_shards=1,
                 dp_shards=1, comm_overlap_ratio=0.0):
    """One-stop cost + memory analysis: propagate shapes (unless an
    ``env`` is supplied), cost every op, run the liveness peak-HBM
    estimate, and bind the device profile. With ``dp_shards > 1`` the
    report also carries the interconnect leg (gradient bytes, predicted
    allreduce seconds against the profile's ICI bandwidth, and dp
    scaling efficiency; ``comm_overlap_ratio`` is the fraction the
    bucketed backward-overlap scheduler hides — see
    parallel/comms/bucketing.py). Returns a :class:`CostReport`."""
    from . import memory, shapes

    if env is None:
        if feed_specs is None and feed_names:
            feed_specs = shapes.feed_specs_from_program(
                program, feed_names=list(feed_names),
                default_dim=default_dim)
        env, _ = shapes.propagate(
            program, feed_specs=feed_specs, state_specs=state_specs,
            is_test=is_test, platform=platform, default_dim=default_dim,
            check_declared=False)
    per_op = op_costs(program, env, is_test=is_test, platform=platform)
    mem = memory.estimate(
        program, env=env, feed_specs=feed_specs, state_specs=state_specs,
        fetch_names=fetch_names, state_names=state_names,
        default_dim=default_dim, param_shards=param_shards,
        act_shards=act_shards)
    grad_bytes = dp_grad_bytes(program, env) if int(dp_shards) > 1 else 0.0
    return CostReport(per_op, memory=mem,
                      profile=device_profile(device_kind),
                      dp_shards=dp_shards, grad_bytes=grad_bytes,
                      comm_overlap_ratio=comm_overlap_ratio)


def predict_program(program, feed_specs=None, fetch_names=(),
                    state_specs=None, device_kind=None, is_test=False,
                    default_dim=None):
    """Bench-friendly wrapper: :func:`analyze_cost` flattened to a plain
    dict (``predicted_step_seconds``, ``predicted_mfu``, ``total_flops``,
    ``total_bytes``, ``predicted_peak_hbm_bytes``)."""
    rep = analyze_cost(
        program, feed_specs=feed_specs, state_specs=state_specs,
        fetch_names=fetch_names, is_test=is_test,
        default_dim=default_dim, device_kind=device_kind)
    out = {
        "total_flops": rep.total_flops,
        "total_bytes": rep.total_bytes,
        "predicted_step_seconds": rep.predicted_step_seconds,
        "predicted_mfu": rep.predicted_mfu,
        "bound": rep.bound,
    }
    if rep.memory is not None:
        out["predicted_peak_hbm_bytes"] = rep.memory.peak_bytes
    # the profile the prediction was made under — what
    # DeviceProfile.calibrated_from's ratio fit rescales
    out["device"] = (rep.profile.to_dict()
                     if rep.profile is not None else None)
    return out
