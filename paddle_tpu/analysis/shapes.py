"""Static shape/dtype propagation.

Instead of hand-writing hundreds of per-op inference rules, this pass
reuses the op lowering registry (``fluid/lowering.py`` +
``ops/registry.py``) exactly as the executor does — but under
``jax.eval_shape``, which runs each lowering on abstract
``ShapeDtypeStruct`` values: full shape/dtype semantics, zero FLOPs,
zero XLA compiles. Every lowering is already jit-trace-safe (that is
how the executor runs it), so tracing it abstractly per op is faithful
by construction: anything this pass rejects, ``jax.jit`` would reject
later with a far worse error; anything it infers, XLA would compute.

Failures surface as ``shape-infer-failed`` errors carrying the op's
recorded Python callstack — the mismatch is attributed to the line of
user code that built the op, BEFORE any XLA compile is attempted.

Dims declared ``-1`` (feed-time batch/sequence dims) resolve to
``default_dim`` in standalone mode; the executor passes the real feed
shapes instead.
"""
import numpy as np

from ..fluid import lowering
from ..fluid import core
from ..ops.registry import LowerContext
from .diagnostics import ERROR, WARNING, AnalysisReport

__all__ = ["propagate", "feed_specs_from_program", "canonical_dtype"]

DEFAULT_DIM = 8  # placeholder for -1 dims in standalone analysis


def canonical_dtype(dtype):
    """The dtype jax will actually materialize for a declared dtype:
    without x64, int64/float64 silently become int32/float32 — declared
    dtypes must be canonicalized before comparing against inferred ones
    or every int64 label var would be a false mismatch."""
    import jax

    return np.dtype(jax.dtypes.canonicalize_dtype(core.np_dtype(dtype)))


def _spec(shape, dtype, default_dim):
    import jax

    shape = tuple(default_dim if (s is None or s < 0) else int(s)
                  for s in (shape or ()))
    return jax.ShapeDtypeStruct(shape, canonical_dtype(dtype))


def feed_specs_from_program(program, feed_names=None, default_dim=None):
    """Abstract feed specs from declared var metadata (standalone mode):
    every -1 dim becomes ``default_dim``; ``@SEQ_LEN`` companions are
    added the way ``Executor._prepare_feeds`` would."""
    default_dim = DEFAULT_DIM if default_dim is None else default_dim
    gb = program.global_block()
    if feed_names is None:
        feed_names = [n for n, v in gb.vars.items() if v.is_data]
    specs = {}
    for n in feed_names:
        if not gb.has_var(n):
            continue
        v = gb.var(n)
        specs[n] = _spec(v.shape, v.dtype or "float32", default_dim)
        seq = n + "@SEQ_LEN"
        if gb.has_var(seq) and seq not in feed_names:
            specs[seq] = _spec((specs[n].shape[0],), "int32", default_dim)
    return specs


def _state_specs_from_program(program, default_dim):
    specs = {}
    for name, v in program.global_block().vars.items():
        if v.persistable and v.shape is not None:
            specs[name] = _spec(v.shape, v.dtype or "float32", default_dim)
    return specs


def propagate(program, feed_specs=None, state_specs=None, is_test=False,
              platform="cpu", default_dim=None, check_declared=True):
    """Propagate shapes/dtypes through the global block op by op.

    ``feed_specs`` / ``state_specs``: name -> ``jax.ShapeDtypeStruct``
    (or anything with .shape/.dtype, e.g. real arrays). ``None`` derives
    them from declared var metadata. Returns ``(env, report)`` where
    ``env`` maps every resolved name to its inferred spec.
    """
    import jax

    report = AnalysisReport(checks=["shapes"])
    default_dim = DEFAULT_DIM if default_dim is None else default_dim
    gb = program.global_block()

    if feed_specs is None:
        feed_specs = feed_specs_from_program(
            program, default_dim=default_dim)
    if state_specs is None:
        state_specs = _state_specs_from_program(program, default_dim)

    env = {}
    for src in (state_specs, feed_specs):
        for n, v in src.items():
            env[n] = jax.ShapeDtypeStruct(tuple(v.shape),
                                          np.dtype(v.dtype))

    var_lookup = lowering._make_var_lookup(gb)
    rng = jax.random.PRNGKey(0)
    unknown = set()  # names whose spec is unknowable after a failure

    for i, op in enumerate(gb.ops):
        out_names = [n for ns in op.outputs.values() for n in ns]
        in_names = [n for ns in op.inputs.values() for n in ns]
        if any(n in unknown or n not in env for n in in_names):
            # upstream failure (or verifier-reported missing input):
            # poison downstream silently instead of cascading reports
            unknown.update(out_names)
            continue

        if op.type == "backward":
            # exact by vjp semantics: a cotangent has the shape/dtype of
            # its primal — no replay needed
            targets = list(op.attrs.get("targets") or [])
            grads = op.output("Grads")
            ok = True
            for t, g in zip(targets, grads):
                if t in env and t not in unknown:
                    env[g] = env[t]
                else:
                    unknown.add(g)
                    ok = False
            if ok:
                _check_outputs(gb, op, i, env, report, check_declared)
            continue

        def f(e, _op=op, _i=i):
            ctx = LowerContext(rng=rng, is_test=is_test, program=program,
                               platform=platform)
            ctx.run_ops = lowering.run_ops
            e = dict(e)
            e = lowering.apply_op(_op, e, ctx, var_lookup, op_tag=_i)
            return {n: e[n] for ns in _op.outputs.values()
                    for n in ns if n in e}

        try:
            outs = jax.eval_shape(f, env)
        except Exception as e:  # noqa: BLE001 — each failure is a finding
            msg = str(e)
            if len(msg) > 600:
                msg = msg[:600] + " ..."
            report.add(
                ERROR, "shape-infer-failed",
                "abstract evaluation of op '%s' failed (%s): %s"
                % (op.type, type(e).__name__, msg),
                block_idx=0, op_index=i, op=op)
            unknown.update(out_names)
            continue
        for n, v in outs.items():
            env[n] = jax.ShapeDtypeStruct(tuple(v.shape), np.dtype(v.dtype))
        _check_outputs(gb, op, i, env, report, check_declared)

    report.meta["n_resolved"] = len(env)
    return env, report


def _check_outputs(gb, op, i, env, report, check_declared):
    """Compare inferred specs against declared Variable metadata."""
    if not check_declared:
        return
    for ns in op.outputs.values():
        for n in ns:
            if n not in env or not gb.has_var(n):
                continue
            var = gb.var(n)
            got = env[n]
            if var.dtype is not None:
                want = canonical_dtype(var.dtype)
                if np.dtype(got.dtype) != want:
                    report.add(
                        WARNING, "dtype-mismatch",
                        "var '%s' is declared %s (canonically %s) but the "
                        "op produces %s" % (n, var.dtype, want.name,
                                            np.dtype(got.dtype).name),
                        block_idx=0, op_index=i, op=op, var=n)
            decl = var.shape
            if decl is None or len(decl) != len(got.shape):
                continue  # rank drift in declared metadata is common
            for ax, (d, g) in enumerate(zip(decl, got.shape)):
                if d is not None and d >= 0 and int(d) != int(g):
                    report.add(
                        WARNING, "shape-mismatch",
                        "var '%s' axis %d is declared %d but the op "
                        "produces %d (inferred shape %s, declared %s)"
                        % (n, ax, d, g, tuple(got.shape), tuple(decl)),
                        block_idx=0, op_index=i, op=op, var=n)
                    break
