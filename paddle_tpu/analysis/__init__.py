"""paddle_tpu.analysis — static program analysis gating every compile.

Three passes over the symbolic Program IR plus one runtime guard:

- :mod:`.verifier` — structural verification (use-before-def, dangling
  vars, uninitialized persistables, fetch reachability, dead code,
  control-flow sub-block sanity). Pure-python walk; the executor and
  predictor run it on every first compile (``PADDLE_TPU_ANALYSIS``,
  default ``verify``).
- :mod:`.shapes` — static shape/dtype propagation by running each op's
  lowering under ``jax.eval_shape`` (the lowering registry IS the
  inference rule set); mismatches report the op's recorded python
  callstack before XLA ever runs.
- :mod:`.tpu_lint` — TPU-shape hazards: unpadded matmul/conv lanes,
  float64 creep, donated-buffer-also-fetched, host syncs inside scan
  bodies, collectives without deadlines, shape-vocabulary blowups.
- :mod:`.sanitizer` — opt-in cross-thread Scope mutation detector
  (``PADDLE_TPU_SCOPE_SANITIZER=on``).
- :mod:`.concurrency` — named-lock lock-order recorder (cycle =
  potential deadlock, with both acquisition stacks), blocking-call-
  while-holding-lock detection, and the framework thread registry
  behind zero-leak ``stop()``/``close()`` checks
  (``PADDLE_TPU_LOCK_SANITIZER=on``).
- :mod:`.dataflow` — def-use/donation dataflow over the Program IR:
  use-after-donate and double-donate proven (errors at ``full``
  level), plus cross-program donated-alias checks, static and runtime.
- :mod:`.costs` / :mod:`.memory` — the quantitative layer: per-op
  FLOPs/bytes from the same lowering registry (traced with
  ``jax.make_jaxpr``), a roofline step-time/MFU prediction against the
  shared device table, and def-use liveness peak-HBM estimation that
  gates compile and serving admission with a predicted-OOM error.

Entry points: :func:`analyze` (all passes), :func:`verify` (structural
only), the ``python -m paddle_tpu.analysis <model_dir>`` CLI, and the
wired-in gates in ``Executor``/``Predictor``/``GuardedExecutor``.

Submodules load lazily (PEP 562): importing ``paddle_tpu.analysis``
costs nothing until a pass is actually used, and the stdlib-only
:mod:`.sanitizer` stays importable without jax.
"""

__all__ = [
    "analyze", "verify", "mode", "ANALYSIS_ENV",
    "AnalysisReport", "Diagnostic", "ProgramVerifyError",
    "analyze_cost", "CostReport", "device_profile",
    "analyzer", "verifier", "shapes", "tpu_lint", "walker",
    "diagnostics", "sanitizer", "cli", "costs", "memory",
    "concurrency", "dataflow",
]

_LAZY_ATTRS = {
    "analyze": ("analyzer", "analyze"),
    "mode": ("analyzer", "mode"),
    "ANALYSIS_ENV": ("analyzer", "ANALYSIS_ENV"),
    "verify": ("verifier", "verify"),
    "AnalysisReport": ("diagnostics", "AnalysisReport"),
    "Diagnostic": ("diagnostics", "Diagnostic"),
    "ProgramVerifyError": ("diagnostics", "ProgramVerifyError"),
    "analyze_cost": ("costs", "analyze_cost"),
    "CostReport": ("costs", "CostReport"),
    "device_profile": ("costs", "device_profile"),
}

_SUBMODULES = ("analyzer", "verifier", "shapes", "tpu_lint", "walker",
               "diagnostics", "sanitizer", "cli", "costs", "memory",
               "concurrency", "dataflow")


def __getattr__(name):
    import importlib

    if name in _LAZY_ATTRS:
        mod_name, attr = _LAZY_ATTRS[name]
        mod = importlib.import_module("." + mod_name, __name__)
        return getattr(mod, attr)
    if name in _SUBMODULES:
        return importlib.import_module("." + name, __name__)
    raise AttributeError("module %r has no attribute %r" % (__name__, name))


def __dir__():
    return sorted(__all__)
