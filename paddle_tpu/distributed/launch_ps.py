"""paddle.distributed.launch_ps (ref: the pserver-mode process
launcher spawning N servers + M trainers)."""
import sys

__all__ = ["launch"]

_MSG = (
    "launch_ps starts parameter-server processes; there are none on "
    "TPU (tables live sharded in HBM). Launch workers with "
    "`python -m paddle_tpu.distributed.launch script.py` (jax."
    "distributed multi-host) and train through fleet.parameter_server."
    "pslib or the collective fleet."
)


def launch():
    raise NotImplementedError(_MSG)


if __name__ == "__main__":
    sys.stderr.write(_MSG + "\n")
    sys.exit(1)
