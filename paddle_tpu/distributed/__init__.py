"""paddle_tpu.distributed (ref: python/paddle/distributed/)."""
from . import launch  # noqa: F401
