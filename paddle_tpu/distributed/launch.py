"""Multi-host launcher (ref: python/paddle/distributed/launch.py).

The reference forks one process per GPU and wires NCCL env vars. On TPU
pods, each *host* runs one process that owns its local chips and joins the
ICI mesh via jax.distributed — so the launcher initializes jax.distributed
from the standard env (COORDINATOR_ADDRESS, NUM_PROCESSES, PROCESS_ID) and
execs the training script in-process.
"""
import argparse
import os
import runpy
import sys

__all__ = ["launch", "main"]


def launch(training_script, coordinator=None, num_processes=None,
           process_id=None, script_args=()):
    import jax

    coordinator = coordinator or os.environ.get("COORDINATOR_ADDRESS")
    num_processes = num_processes or os.environ.get("NUM_PROCESSES")
    process_id = process_id or os.environ.get("PROCESS_ID")
    if coordinator and num_processes:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(num_processes),
            process_id=int(process_id or 0),
        )
    sys.argv = [training_script] + list(script_args)
    runpy.run_path(training_script, run_name="__main__")


def main():
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--coordinator", default=None)
    parser.add_argument("--num_processes", default=None)
    parser.add_argument("--process_id", default=None)
    parser.add_argument("training_script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args()
    launch(
        args.training_script,
        args.coordinator,
        args.num_processes,
        args.process_id,
        args.script_args,
    )


if __name__ == "__main__":
    main()
