"""Linear-chain CRF ops: linear_chain_crf, crf_decoding, chunk_eval
(ref: paddle/fluid/operators/{linear_chain_crf_op.h, crf_decoding_op.h,
chunk_eval_op.h}).

TPU-native design notes:
  * the reference computes the forward algorithm per sequence in exp
    domain with per-step L1 renormalisation on the CPU; here the whole
    batch runs one log-domain lax.scan over time (logsumexp is the
    stable equivalent of the reference's normalise-and-log accounting),
    so XLA can tile the (B, D, D) transition broadcasts on the MXU;
  * viterbi decoding is a scan storing a (T, B, D) backpointer table and
    a reverse scan to walk it — no per-sequence host loops;
  * chunk_eval's tag state machine (ChunkBegin/ChunkEnd branch ladders in
    the reference) is precomputed into dense (L, L) boolean lookup
    tables over (prev_label, label) pairs at trace time, so the T-step
    evaluation is pure gathers + a tiny matching scan.

Transition layout matches the reference: row 0 = start weights, row 1 =
end weights, rows 2.. = tag->tag transition scores, shape (D+2, D).
LogLikelihood output is the per-sequence NEGATIVE log likelihood
(a cost to minimise), exactly as the reference returns it.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single

NEG = -1e30


def _crf_inputs(ins):
    x = ins["Emission"][0]
    w = ins["Transition"][0]
    squeeze = x.ndim == 2
    if squeeze:
        x = x[None]
    b, t, d = x.shape
    if ins.get("Length"):
        lens = ins["Length"][0].astype(jnp.int32).reshape(-1)
    else:
        lens = jnp.full((b,), t, jnp.int32)
    return x, w, lens, squeeze


@register_op("linear_chain_crf")
def _linear_chain_crf(ctx, ins, attrs):
    """Negative log likelihood of a linear-chain CRF
    (ref linear_chain_crf_op.h ForwardOneSequence)."""
    x, w, lens, _ = _crf_inputs(ins)
    b, t, d = x.shape
    label = ins["Label"][0].astype(jnp.int32).reshape(b, t)
    start_w, end_w, trans = w[0], w[1], w[2:]

    # ---- log partition via batched forward scan
    a0 = start_w[None, :] + x[:, 0, :]                       # (B, D)

    def fwd(carry, k):
        a = carry
        nxt = jax.nn.logsumexp(
            a[:, :, None] + trans[None, :, :], axis=1
        ) + x[:, k, :]
        a = jnp.where((k < lens)[:, None], nxt, a)
        return a, a

    a_last, a_hist = lax.scan(fwd, a0, jnp.arange(1, t))
    log_z = jax.nn.logsumexp(a_last + end_w[None, :], axis=-1)   # (B,)

    # ---- gold path score
    pos = jnp.arange(t)[None, :]
    valid = pos < lens[:, None]
    emit = jnp.take_along_axis(x, label[:, :, None], axis=2)[:, :, 0]
    emit_sum = jnp.sum(jnp.where(valid, emit, 0.0), axis=1)
    trans_sc = trans[label[:, :-1], label[:, 1:]]                # (B, T-1)
    trans_sum = jnp.sum(
        jnp.where(valid[:, 1:], trans_sc, 0.0), axis=1
    )
    last_idx = jnp.maximum(lens - 1, 0)
    last_lab = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    gold = (
        start_w[label[:, 0]] + emit_sum + trans_sum + end_w[last_lab]
    )
    nll = jnp.where(lens > 0, log_z - gold, 0.0)

    alpha = jnp.concatenate([a0[:, None, :], a_hist.transpose(1, 0, 2)],
                            axis=1)
    return {
        "LogLikelihood": [nll[:, None]],
        "Alpha": [alpha],
        "EmissionExps": [jnp.exp(x - jnp.max(x, -1, keepdims=True))],
        "TransitionExps": [jnp.exp(w)],
    }


@register_op("crf_decoding")
def _crf_decoding(ctx, ins, attrs):
    """Viterbi decode (ref crf_decoding_op.h Decode). Output: (B, T) int64
    best path, zero past each length; with a Label input the output is a
    per-token correctness indicator instead (ref behavior)."""
    x, w, lens, squeeze = _crf_inputs(ins)
    b, t, d = x.shape
    start_w, end_w, trans = w[0], w[1], w[2:]

    a0 = start_w[None, :] + x[:, 0, :]

    def fwd(carry, k):
        a = carry
        scores = a[:, :, None] + trans[None, :, :]       # (B, Dprev, D)
        best = jnp.max(scores, axis=1) + x[:, k, :]
        track = jnp.argmax(scores, axis=1).astype(jnp.int32)
        live = (k < lens)[:, None]
        a = jnp.where(live, best, a)
        track = jnp.where(live, track, jnp.arange(d)[None, :])
        return a, track

    a_last, tracks = lax.scan(fwd, a0, jnp.arange(1, t))  # tracks (T-1,B,D)
    last_tag = jnp.argmax(a_last + end_w[None, :], axis=-1).astype(jnp.int32)

    def back(carry, track_k):
        tag = carry
        prev = jnp.take_along_axis(track_k, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first, path_rev = lax.scan(back, last_tag, tracks, reverse=True)
    # ys[i] is the carry before consuming tracks[i] = the tag at position
    # i+1; the final carry is the tag at position 0. Steps past each
    # sequence's length used identity tracks, so the walk passes through
    # them unchanged and the sub-length positions decode correctly.
    path = jnp.concatenate([first[:, None], path_rev.transpose(1, 0)],
                           axis=1)
    pos = jnp.arange(t)[None, :]
    valid = pos < lens[:, None]
    path = jnp.where(valid, path, 0).astype(jnp.int64)
    if ins.get("Label"):
        label = ins["Label"][0].astype(jnp.int64).reshape(b, t)
        path = jnp.where(valid, (label == path).astype(jnp.int64), 0)
    out = path[0] if squeeze else path
    return {"ViterbiPath": [out]}


# ---------------------------------------------------------------------------
# chunk_eval
# ---------------------------------------------------------------------------
_SCHEMES = {
    # scheme -> (num_tag_types, begin, inside, end, single) tag roles
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_tables(scheme, num_chunk_types):
    """Dense (L, L) begin/end tables over (prev_label, label) pairs,
    mirroring the reference's ChunkBegin/ChunkEnd predicates. L includes
    the 'other' (O) label = num_chunk_types * num_tag_types."""
    ntag, t_beg, t_in, t_end, t_sin = _SCHEMES[scheme]
    other = num_chunk_types
    n_labels = num_chunk_types * ntag + 1

    def tag_type(lab):
        return lab % ntag, lab // ntag

    def chunk_begin(prev, cur):
        ptag, ptype = tag_type(prev)
        tag, typ = tag_type(cur)
        if ptype == other:
            return typ != other
        if typ == other:
            return False
        if typ != ptype:
            return True
        if tag == t_beg:
            return True
        if tag == t_in:
            return ptag in (t_end, t_sin)
        if tag == t_end:
            return ptag in (t_end, t_sin)
        if tag == t_sin:
            return True
        return False

    def chunk_end(prev, cur):
        ptag, ptype = tag_type(prev)
        tag, typ = tag_type(cur)
        if ptype == other:
            return False
        if typ == other:
            return True
        if typ != ptype:
            return True
        if ptag == t_beg or ptag == t_in:
            return tag in (t_beg, t_sin)
        if ptag in (t_end, t_sin):
            return True
        return False

    beg = np.zeros((n_labels, n_labels), np.bool_)
    end = np.zeros((n_labels, n_labels), np.bool_)
    for p in range(n_labels):
        for c in range(n_labels):
            beg[p, c] = chunk_begin(p, c)
            end[p, c] = chunk_end(p, c)
    return beg, end, other, ntag, n_labels


def _chunk_masks(labels, lens, beg_t, end_t, other, ntag, n_labels):
    """Per-position begin/end booleans + chunk type, vectorised over
    (B, T) via the lookup tables. Out-of-range labels are clamped to O."""
    b, t = labels.shape
    lab = jnp.clip(labels, 0, n_labels - 1)
    pos = jnp.arange(t)[None, :]
    valid = pos < lens[:, None]
    lab = jnp.where(valid, lab, n_labels - 1)          # pads act as O
    o_col = jnp.full((b, 1), n_labels - 1, lab.dtype)
    prev = jnp.concatenate([o_col, lab[:, :-1]], axis=1)
    nxt = jnp.concatenate([lab[:, 1:], o_col], axis=1)
    beg = jnp.asarray(beg_t)[prev, lab] & valid
    end = jnp.asarray(end_t)[lab, nxt] & valid
    typ = lab // ntag
    return beg, end, typ, valid


@register_op("chunk_eval")
def _chunk_eval(ctx, ins, attrs):
    """Chunk-level precision/recall/F1 (ref chunk_eval_op.h). Inference and
    Label: (B, T) int labels, padded; SeqLength: (B,) int."""
    inf = ins["Inference"][0].astype(jnp.int32)
    lab = ins["Label"][0].astype(jnp.int32)
    if inf.ndim == 3:
        inf = inf[:, :, 0]
    if lab.ndim == 3:
        lab = lab[:, :, 0]
    if inf.ndim == 1:
        inf, lab = inf[None], lab[None]
    b, t = inf.shape
    if ins.get("SeqLength"):
        lens = ins["SeqLength"][0].astype(jnp.int32).reshape(-1)
    else:
        lens = jnp.full((b,), t, jnp.int32)

    scheme = attrs.get("chunk_scheme", "IOB")
    nct = int(attrs["num_chunk_types"])
    excluded = list(attrs.get("excluded_chunk_types") or [])
    beg_t, end_t, other, ntag, n_labels = _chunk_tables(scheme, nct)

    ib, ie, ityp, valid = _chunk_masks(
        inf, lens, beg_t, end_t, other, ntag, n_labels
    )
    lb, le, ltyp, _ = _chunk_masks(
        lab, lens, beg_t, end_t, other, ntag, n_labels
    )
    include_i = jnp.ones_like(ityp, jnp.bool_)
    include_l = jnp.ones_like(ltyp, jnp.bool_)
    for e in excluded:
        include_i &= ityp != e
        include_l &= ltyp != e

    n_infer = jnp.sum((ib & include_i).astype(jnp.int64))
    n_label = jnp.sum((lb & include_l).astype(jnp.int64))

    # matching scan: a candidate match is alive from a shared begin (same
    # type, not excluded) until any end; counted when both end together
    def match(carry, k):
        alive, cnt = carry
        start = lb[:, k] & ib[:, k] & (ltyp[:, k] == ityp[:, k]) \
            & include_l[:, k]
        alive = start | (alive & ~lb[:, k] & ~ib[:, k])
        both_end = le[:, k] & ie[:, k]
        cnt = cnt + (alive & both_end).astype(jnp.int64)
        alive = alive & ~le[:, k] & ~ie[:, k]
        return (alive, cnt), None

    (_, cnt), _ = lax.scan(
        match,
        (jnp.zeros((b,), jnp.bool_), jnp.zeros((b,), jnp.int64)),
        jnp.arange(t),
    )
    n_correct = jnp.sum(cnt)

    prec = jnp.where(
        n_infer > 0, n_correct / jnp.maximum(n_infer, 1), 0.0
    ).astype(jnp.float32)
    rec = jnp.where(
        n_label > 0, n_correct / jnp.maximum(n_label, 1), 0.0
    ).astype(jnp.float32)
    f1 = jnp.where(
        n_correct > 0, 2 * prec * rec / jnp.maximum(prec + rec, 1e-12), 0.0
    ).astype(jnp.float32)
    return {
        "Precision": [prec[None]],
        "Recall": [rec[None]],
        "F1-Score": [f1[None]],
        "NumInferChunks": [n_infer[None]],
        "NumLabelChunks": [n_label[None]],
        "NumCorrectChunks": [n_correct[None]],
    }


@register_op("ctc_greedy_decoder")
def _ctc_greedy_decoder(ctx, ins, attrs):
    """Greedy CTC decode (ref ctc_align_op / layers ctc_greedy_decoder):
    argmax per frame, merge repeats, drop blanks. Padded mode: Input
    (B, T, C) + optional InputLength; outputs (B, T) tokens padded with
    `padding_value` and OutLength (B, 1)."""
    x = ins["Input"][0]
    blank = int(attrs["blank"])
    pad_val = attrs.get("padding_value", 0)
    squeeze = x.ndim == 2
    if squeeze:
        x = x[None]
    b, t, c = x.shape
    if ins.get("InputLength"):
        lens = ins["InputLength"][0].astype(jnp.int32).reshape(-1)
    else:
        lens = jnp.full((b,), t, jnp.int32)
    tok = jnp.argmax(x, axis=-1).astype(jnp.int32)         # (B, T)
    pos = jnp.arange(t)[None, :]
    valid = pos < lens[:, None]
    prev = jnp.concatenate(
        [jnp.full((b, 1), -1, jnp.int32), tok[:, :-1]], axis=1
    )
    keep = valid & (tok != blank) & (tok != prev)
    # stable left-compaction: sort positions by (dropped, position)
    key = jnp.where(keep, pos, t + pos)
    order = jnp.argsort(key, axis=1)
    gathered = jnp.take_along_axis(tok, order, axis=1)
    n_keep = jnp.sum(keep.astype(jnp.int32), axis=1)
    out = jnp.where(
        pos < n_keep[:, None], gathered, jnp.asarray(pad_val, jnp.int32)
    ).astype(jnp.int64)
    if squeeze:
        return {"Out": [out[0]], "OutLength": [n_keep[:, None]]}
    return {"Out": [out], "OutLength": [n_keep[:, None]]}
