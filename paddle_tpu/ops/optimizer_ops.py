"""Optimizer update op lowerings.

Replaces sgd_op, momentum_op, adagrad_op, adam_op, adamax_op, rmsprop_op,
adadelta_op, ftrl_op, lamb_op, lars_momentum_op, decayed_adagrad_op,
dpsgd_op (ref: paddle/fluid/operators/optimizers/*). These are ordinary ops
in the Program, so the whole update fuses into the one jitted train step and
parameters update in-place in HBM via buffer donation.
"""
import jax
import jax.numpy as jnp

from .registry import register_op, single


@register_op("sgd")
def _sgd(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    return {"ParamOut": [p - lr.astype(p.dtype) * g.astype(p.dtype)]}


@register_op("momentum")
def _momentum(ctx, ins, attrs):
    p, g, v, lr = (
        ins["Param"][0],
        ins["Grad"][0],
        ins["Velocity"][0],
        ins["LearningRate"][0],
    )
    mu = attrs.get("mu", 0.9)
    use_nesterov = attrs.get("use_nesterov", False)
    g = g.astype(p.dtype)
    lr = lr.astype(p.dtype)
    v_new = mu * v + g
    if use_nesterov:
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    return {"ParamOut": [p_new], "VelocityOut": [v_new]}


@register_op("dgc_momentum")
def _dgc_momentum(ctx, ins, attrs):
    """Deep Gradient Compression momentum (ref operators/optimizers/
    dgc_momentum_op.h + dgc_op): before rampup_begin_step this is plain
    momentum; after, the momentum-corrected gradient accumulates locally
    and only the top-(1-sparsity) magnitudes update the parameter this
    step (the rest stay banked in V). Sparsity threshold via quantile so
    the rampup schedule can stay a traced value."""
    p = ins["Param"][0]
    g = ins["Grad"][0].astype(p.dtype)
    u = ins["U"][0]
    v = ins["V"][0]
    step = ins["CurrentStep"][0].reshape(())
    lr = ins["LearningRate"][0].astype(p.dtype)
    mu = attrs.get("mu", 0.9)
    begin = attrs.get("rampup_begin_step", 0)
    rampup = max(attrs.get("rampup_step", 1), 1)
    sparsity = jnp.asarray(
        attrs.get("sparsity", [0.999]), jnp.float32
    )
    clip_norm = attrs.get("local_grad_clip_norm", -1.0)
    if clip_norm and clip_norm > 0:
        gn = jnp.sqrt(jnp.sum(g * g))
        g = g * jnp.minimum(1.0, clip_norm / jnp.maximum(gn, 1e-12))

    # plain momentum branch (pre-rampup)
    vel = mu * u + g
    p_plain = p - lr * vel

    # DGC branch
    u_new = mu * u + g
    v_new = v + u_new
    seg = jnp.clip(
        ((step - begin) * len(attrs.get("sparsity", [0.999])) // rampup)
        .astype(jnp.int32),
        0, len(attrs.get("sparsity", [0.999])) - 1,
    )
    s = sparsity[seg]
    absv = jnp.abs(v_new)
    thr = jnp.quantile(absv.reshape(-1).astype(jnp.float32), s)
    mask = (absv >= thr.astype(p.dtype)).astype(p.dtype)
    transmitted = v_new * mask
    p_dgc = p - lr * transmitted
    v_keep = v_new * (1.0 - mask)
    u_keep = u_new * (1.0 - mask)

    use_dgc = step >= begin
    p_out = jnp.where(use_dgc, p_dgc, p_plain)
    u_out = jnp.where(use_dgc, u_keep, vel)
    v_out = jnp.where(use_dgc, v_keep, v)
    return {
        "ParamOut": [p_out],
        "UOut": [u_out],
        "VOut": [v_out],
        "StepOut": [(step + 1).reshape(1)],
    }


@register_op("lars_momentum")
def _lars_momentum(ctx, ins, attrs):
    p, g, v, lr = (
        ins["Param"][0],
        ins["Grad"][0],
        ins["Velocity"][0],
        ins["LearningRate"][0],
    )
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    decay = attrs.get("lars_weight_decay", 0.0005)
    pn = jnp.sqrt(jnp.sum(p * p))
    gn = jnp.sqrt(jnp.sum(g * g))
    local_lr = jnp.where(
        (pn > 0) & (gn > 0),
        lr * coeff * pn / (gn + decay * pn + 1e-12),
        lr,
    )
    v_new = mu * v + local_lr * (g + decay * p)
    return {"ParamOut": [p - v_new], "VelocityOut": [v_new]}


@register_op("adagrad")
def _adagrad(ctx, ins, attrs):
    p, g, m, lr = (
        ins["Param"][0],
        ins["Grad"][0],
        ins["Moment"][0],
        ins["LearningRate"][0],
    )
    eps = attrs.get("epsilon", 1e-6)
    m_new = m + g * g
    p_new = p - lr * g / (jnp.sqrt(m_new) + eps)
    return {"ParamOut": [p_new], "MomentOut": [m_new]}


@register_op("decayed_adagrad")
def _decayed_adagrad(ctx, ins, attrs):
    p, g, m, lr = (
        ins["Param"][0],
        ins["Grad"][0],
        ins["Moment"][0],
        ins["LearningRate"][0],
    )
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * g * g
    return {
        "ParamOut": [p - lr * g / (jnp.sqrt(m_new) + eps)],
        "MomentOut": [m_new],
    }


@register_op("adadelta")
def _adadelta(ctx, ins, attrs):
    p, g = ins["Param"][0], ins["Grad"][0]
    avg_sq_grad = ins["AvgSquaredGrad"][0]
    avg_sq_upd = ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g2 = rho * avg_sq_grad + (1 - rho) * g * g
    upd = -jnp.sqrt((avg_sq_upd + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_upd + (1 - rho) * upd * upd
    return {
        "ParamOut": [p + upd],
        "AvgSquaredGradOut": [g2],
        "AvgSquaredUpdateOut": [u2],
    }


def _adam_core(p, g, m, v, beta1_pow, beta2_pow, lr, beta1, beta2, eps):
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * g * g
    lr_t = lr * jnp.sqrt(1 - beta2_pow) / (1 - beta1_pow)
    p_new = p - lr_t * m_new / (jnp.sqrt(v_new) + eps)
    return p_new, m_new, v_new


@register_op("adam")
def _adam(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    m, v = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    cdtype = jnp.float32
    pf = p.astype(cdtype)
    p_new, m_new, v_new = _adam_core(
        pf, g.astype(cdtype), m, v, b1p, b2p, lr, beta1, beta2, eps
    )
    return {
        "ParamOut": [p_new.astype(p.dtype)],
        "Moment1Out": [m_new],
        "Moment2Out": [v_new],
        "Beta1PowOut": [b1p * beta1],
        "Beta2PowOut": [b2p * beta2],
    }


@register_op("adamax")
def _adamax(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    m, inf_norm = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0]
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_new = beta1 * m + (1 - beta1) * g
    inf_new = jnp.maximum(beta2 * inf_norm, jnp.abs(g))
    p_new = p - (lr / (1 - b1p)) * m_new / (inf_new + eps)
    return {
        "ParamOut": [p_new],
        "MomentOut": [m_new],
        "InfNormOut": [inf_new],
    }


@register_op("rmsprop")
def _rmsprop(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    ms = ins["MeanSquare"][0]
    mom = ins["Moment"][0]
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    ms_new = rho * ms + (1 - rho) * g * g
    if centered:
        mg = ins["MeanGrad"][0]
        mg_new = rho * mg + (1 - rho) * g
        denom = ms_new - mg_new * mg_new + eps
    else:
        mg_new = None
        denom = ms_new + eps
    mom_new = momentum * mom + lr * g / jnp.sqrt(denom)
    out = {
        "ParamOut": [p - mom_new],
        "MeanSquareOut": [ms_new],
        "MomentOut": [mom_new],
    }
    if centered:
        out["MeanGradOut"] = [mg_new]
    return out


@register_op("ftrl")
def _ftrl(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    sq, lin = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    new_sq = sq + g * g
    sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    quad = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre_shrink = (l1 * jnp.sign(new_lin) - new_lin) / quad
    p_new = jnp.where(jnp.abs(new_lin) > l1, pre_shrink, 0.0)
    return {
        "ParamOut": [p_new],
        "SquaredAccumOut": [new_sq],
        "LinearAccumOut": [new_lin],
    }


@register_op("lamb")
def _lamb(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    m, v = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    beta1 = attrs.get("beta1", 0.9)
    beta2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    m_new = beta1 * m + (1 - beta1) * g
    v_new = beta2 * v + (1 - beta2) * g * g
    m_hat = m_new / (1 - b1p)
    v_hat = v_new / (1 - b2p)
    r = m_hat / (jnp.sqrt(v_hat) + eps) + wd * p
    p_norm = jnp.sqrt(jnp.sum(p * p))
    r_norm = jnp.sqrt(jnp.sum(r * r))
    trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
    return {
        "ParamOut": [p - lr * trust * r],
        "Moment1Out": [m_new],
        "Moment2Out": [v_new],
        "Beta1PowOut": [b1p * beta1],
        "Beta2PowOut": [b2p * beta2],
    }


@register_op("dpsgd")
def _dpsgd(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    clip = attrs.get("clip", 10.0)
    batch_size = attrs.get("batch_size", 16.0)
    sigma = attrs.get("sigma", 1.0)
    gn = jnp.sqrt(jnp.sum(g * g))
    g = g * jnp.minimum(1.0, clip / jnp.maximum(gn, 1e-12))
    noise = sigma * clip * jax.random.normal(ctx.next_rng(), g.shape)
    return {"ParamOut": [p - lr * (g + noise / batch_size)]}
