"""Fused LayerNorm as pallas TPU kernels.

TPU-native fused form of the reference's layer_norm op (ref:
paddle/fluid/operators/layer_norm_op.cc / .cu — a dedicated fused CUDA
kernel there too). One VMEM pass computes mean/var/normalize/affine per row
block; the backward kernel re-normalizes from saved (mean, rstd) and emits
per-block partial sums for d(scale)/d(bias) that the wrapper reduces — the
cross-row reduction is the only part XLA sees, so it fuses into neighbours.

Used by the layer_norm lowering when PADDLE_TPU_PALLAS_LN=1 on TPU
(default off: XLA's own LN fusion is already strong; flip after profiling
shows a win for your shape mix). Exact parity with the jnp lowering is
covered by tests in interpret mode.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_layer_norm"]


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                 # (bm, H)
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    xhat = xc * rstd
    y = xhat * g_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean[:, 0]
    rstd_ref[...] = rstd[:, 0]


def _bwd_kernel(x_ref, g_ref, mean_ref, rstd_ref, dy_ref, dx_ref, dg_ref,
                db_ref):
    x = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    mean = mean_ref[...][:, None]
    rstd = rstd_ref[...][:, None]
    xhat = (x - mean) * rstd
    wdy = dy * g
    c1 = jnp.mean(wdy, axis=1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=1, keepdims=True)
    dx = (wdy - c1 - xhat * c2) * rstd
    dx_ref[...] = dx.astype(dx_ref.dtype)
    # per-block partials; wrapper sums over the grid axis
    dg_ref[...] = jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_ref[...] = jnp.sum(dy, axis=0, keepdims=True)


def _row_block(n):
    for b in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if n % b == 0:
            return b
    return 1


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln(x, gamma, beta, eps, interpret):
    """Returns (y, mean, rstd); mean/rstd are diagnostics — their
    cotangents are ignored in the backward (like the reference op's
    Mean/Variance outputs, which carry no gradient)."""
    return _ln_fwd(x, gamma, beta, eps, interpret)[0]


def _ln_fwd(x, gamma, beta, eps, interpret):
    n, h = x.shape
    bm = _row_block(n)
    grid = (n // bm,)
    y, mean, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=(
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
            pl.BlockSpec((bm,), lambda i: (i,)),
            pl.BlockSpec((bm,), lambda i: (i,)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, h), x.dtype),
            jax.ShapeDtypeStruct((n,), jnp.float32),
            jax.ShapeDtypeStruct((n,), jnp.float32),
        ),
        interpret=interpret,
    )(x, gamma, beta)
    return (y, mean, rstd), (x, gamma, mean, rstd)


def _ln_bwd(eps, interpret, res, dys):
    dy = dys[0]  # stats cotangents (dys[1:]) are ignored by design
    x, gamma, mean, rstd = res
    n, h = x.shape
    bm = _row_block(n)
    grid = (n // bm,)
    dx, dg_part, db_part = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((bm,), lambda i: (i,)),
            pl.BlockSpec((bm,), lambda i: (i,)),
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((bm, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n, h), x.dtype),
            jax.ShapeDtypeStruct((grid[0], h), jnp.float32),
            jax.ShapeDtypeStruct((grid[0], h), jnp.float32),
        ),
        interpret=interpret,
    )(x, gamma, mean, rstd, dy)
    dg = jnp.sum(dg_part, axis=0).astype(gamma.dtype)
    db = jnp.sum(db_part, axis=0).astype(gamma.dtype)
    return dx, dg, db


_ln.defvjp(_ln_fwd, _ln_bwd)


def fused_layer_norm(x, gamma=None, beta=None, eps=1e-5, interpret=False,
                     return_stats=False):
    """LayerNorm over the last axis of a 2D-reshapeable x.

    x: (..., H); gamma/beta: (H,) or None. With return_stats=True also
    returns (mean, rstd) shaped like x's leading axes — the kernel computed
    them anyway; callers must not recompute (that would double the memory
    passes this kernel exists to avoid).
    """
    shape = x.shape
    h = shape[-1]
    xf = x.reshape(-1, h)
    if gamma is None:
        gamma = jnp.ones((h,), jnp.float32)
    if beta is None:
        beta = jnp.zeros((h,), jnp.float32)
    y, mean, rstd = _ln(
        xf, gamma.reshape(h), beta.reshape(h), float(eps), interpret
    )
    if return_stats:
        return (
            y.reshape(shape),
            mean.reshape(shape[:-1]),
            rstd.reshape(shape[:-1]),
        )
    return y.reshape(shape)
