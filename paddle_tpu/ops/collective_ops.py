"""Placeholder — filled in as the subsystem lands."""
