"""Collective op lowerings.

Replaces the reference's NCCL collective kernels
(ref: paddle/fluid/operators/collective/c_allreduce_op.h, c_allgather_op.cc,
c_broadcast_op.cc, c_reducescatter_op.cc, c_comm_init_op.cc) with jax.lax
collectives. Inside shard_map over a Mesh these lower to XLA all-reduce /
all-gather / reduce-scatter riding the ICI; outside any mesh axis they are
identities (single participant), which matches NCCL world-size-1 semantics.

The main data/tensor-parallel path does NOT use these ops — pjit + GSPMD
sharding inserts collectives automatically (see parallel/sharding.py). These
exist for API parity and for explicit shard_map programs.
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


def axis_size(ax):
    """Static mapped-axis size; ``lax.axis_size`` is newer than some
    supported jax builds (psum of the literal 1 folds statically)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(ax)
    return lax.psum(1, ax)


def _axis(ctx, attrs):
    """Resolve the mesh axis for a collective ring id; None = no axis bound
    (single-device execution)."""
    ring = attrs.get("ring_id", 0)
    return ctx.mesh_axes.get(ring) or ctx.mesh_axes.get("collective")


def collective_guard(what, site="collective"):
    """Host-side health gate, hit at trace/dispatch time — before the
    collective is handed to XLA. Counts a fault-spec check at `site`
    and enforces the thread's armed collective deadline (elastic
    training arms one per step), so a fleet that already lost a peer
    raises CollectiveTimeoutError here instead of wedging on the chip.
    World-size-1 paths are guarded too: the entry point is the unit of
    accounting, not the payload — the telemetry dispatch counters below
    count lowerings the same way. Imported lazily — ops must stay
    importable before the fluid package finishes initialising.

    Public: parallel/comms routes every quantized/bucketed gradient
    sync launch through here too, so FleetGuard deadlines and fault
    drills cover the new lowerings exactly like the c_* op lowerings.
    """
    from .. import observability as obs
    from ..fluid.resilience import collective_check

    obs.inc("collective.dispatch")
    obs.inc("collective.dispatch.%s" % what)
    collective_check(what, site=site)


_guard = collective_guard


def _allreduce(name, reducer):
    def lower(ctx, ins, attrs):
        x = ins["X"][0]
        _guard(name)
        ax = _axis(ctx, attrs)
        if ax is None:
            return single(x)
        return single(reducer(x, axis_name=ax))

    return lower


register_op("c_allreduce_sum")(_allreduce("c_allreduce_sum", lax.psum))
register_op("c_allreduce_max")(_allreduce("c_allreduce_max", lax.pmax))
register_op("c_allreduce_min")(_allreduce("c_allreduce_min", lax.pmin))


@register_op("c_allreduce_prod")
def _c_allreduce_prod(ctx, ins, attrs):
    x = ins["X"][0]
    _guard("c_allreduce_prod")
    ax = _axis(ctx, attrs)
    if ax is None:
        return single(x)
    # XLA has no native product all-reduce: gather the axis then reduce
    # (exact, including zeros/signs, unlike a log-space psum)
    gathered = lax.all_gather(x, axis_name=ax)
    return single(jnp.prod(gathered, axis=0))


@register_op("c_allgather")
def _c_allgather(ctx, ins, attrs):
    x = ins["X"][0]
    _guard("c_allgather")
    ax = _axis(ctx, attrs)
    if ax is None:
        return single(x)
    out = lax.all_gather(x, axis_name=ax)
    # paddle concatenates along dim 0
    return single(out.reshape((-1,) + x.shape[1:]))


@register_op("c_broadcast")
def _c_broadcast(ctx, ins, attrs):
    x = ins["X"][0]
    _guard("c_broadcast")
    ax = _axis(ctx, attrs)
    if ax is None:
        return single(x)
    root = attrs.get("root", 0)
    # select root's value on every participant
    src = lax.all_gather(x, axis_name=ax)
    return single(src[root])


@register_op("c_reducescatter")
def _c_reducescatter(ctx, ins, attrs):
    x = ins["X"][0]
    _guard("c_reducescatter")
    ax = _axis(ctx, attrs)
    if ax is None:
        return single(x)
    return single(lax.psum_scatter(x, axis_name=ax, tiled=True))


@register_op("c_concat")
def _c_concat(ctx, ins, attrs):
    return _c_allgather(ctx, ins, attrs)


@register_op("c_identity")
def _c_identity(ctx, ins, attrs):
    return single(ins["X"][0])


@register_op("c_split")
def _c_split(ctx, ins, attrs):
    x = ins["X"][0]
    ax = _axis(ctx, attrs)
    if ax is None:
        return single(x)
    idx = lax.axis_index(ax)
    n = axis_size(ax)
    per = x.shape[0] // n
    return single(lax.dynamic_slice_in_dim(x, idx * per, per, axis=0))


@register_op("c_sync_calc_stream")
def _c_sync_calc_stream(ctx, ins, attrs):
    # XLA's dataflow order replaces stream synchronisation
    return single(ins["X"][0])


@register_op("c_sync_comm_stream")
def _c_sync_comm_stream(ctx, ins, attrs):
    return single(ins["X"][0])


@register_op("c_comm_init")
def _c_comm_init(ctx, ins, attrs):
    # communicator setup is implicit in the mesh; no-op for parity
    return {}


@register_op("c_comm_init_all")
def _c_comm_init_all(ctx, ins, attrs):
    return {}


@register_op("c_gen_nccl_id")
def _c_gen_nccl_id(ctx, ins, attrs):
    return {}


@register_op("barrier")
def _barrier(ctx, ins, attrs):
    _guard("barrier", site="barrier")
    ax = _axis(ctx, attrs)
    if ins.get("X"):
        x = ins["X"][0]
        if ax is not None:
            # data-dependent no-op forces a rendezvous
            x = x + 0 * lax.psum(jnp.zeros((), x.dtype), axis_name=ax)
        return single(x)
    return {}


@register_op("ppermute")
def _ppermute(ctx, ins, attrs):
    """Ring permute — building block for ring attention / pipeline."""
    x = ins["X"][0]
    _guard("ppermute")
    ax = _axis(ctx, attrs)
    if ax is None:
        return single(x)
    n = axis_size(ax)
    shift = attrs.get("shift", 1)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return single(lax.ppermute(x, axis_name=ax, perm=perm))


@register_op("all_to_all")
def _all_to_all(ctx, ins, attrs):
    x = ins["X"][0]
    _guard("all_to_all")
    ax = _axis(ctx, attrs)
    if ax is None:
        return single(x)
    split_axis = attrs.get("split_axis", 0)
    concat_axis = attrs.get("concat_axis", 0)
    return single(
        lax.all_to_all(x, ax, split_axis=split_axis, concat_axis=concat_axis,
                       tiled=True)
    )


@register_op("c_allreduce_quant")
def _c_allreduce_quant(ctx, ins, attrs):
    """Block-scaled quantized mean-allreduce (parallel/comms): quantize
    -> reduce-scatter -> dequant-accumulate -> all-gather. Explicit-op
    twin of the grad-sync path for shard_map programs that script their
    own collectives. attrs: ``block_size`` (default 256), ``wire_dtype``
    ('int8' | 'fp8_e4m3'), ``op`` ('mean' default | 'sum'). Inputs that
    can't block-quantize (non-float, scalar) fall back to the exact
    reduce, like pmean_int8."""
    x = ins["X"][0]
    _guard("c_allreduce_quant")
    ax = _axis(ctx, attrs)
    if ax is None:
        return single(x)
    from ..parallel.comms import allreduce as _ar
    from ..parallel.comms import quantize as _qz

    mean = attrs.get("op", "mean") != "sum"
    block = int(attrs.get("block_size", _qz.DEFAULT_BLOCK))
    wire = attrs.get("wire_dtype", "int8")
    n = _ar.axis_size(ax)
    if not jnp.issubdtype(x.dtype, jnp.floating) or x.ndim == 0:
        red = lax.psum(x, ax)
        return single(red / n if mean else red)
    flat, orig = _qz.pad_flat(x.astype(jnp.float32).reshape(-1),
                              n * block)
    reduced, _ = _ar.quantized_allreduce_flat(flat, ax, block, wire,
                                              mean=mean)
    return single(reduced[:orig].reshape(x.shape).astype(x.dtype))
