"""Control-flow op lowerings.

Replaces the reference's C++ control-flow operators
(ref: paddle/fluid/operators/controlflow/while_op.cc,
conditional_block_op.cc) with lax.while_loop / lax.cond over sub-block
lowering — compiler-friendly control flow with static carried shapes, as
XLA requires.
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


def _sub_block(ctx, idx):
    return ctx.program.block(idx)


def _run_block_ops(ctx, block, env):
    # recurse through the same machinery the top-level lowerer uses
    return ctx.run_ops(block, block.ops, env, ctx)


@register_op("while")
def _while(ctx, ins, attrs):
    """Loop a sub-block until its condition var goes False.
    inputs: Condition=[cond_name value], X=[carried values]
    attrs: sub_block (idx), carried_names (order matches X),
           cond_name, outer_env (bound by the lowerer via ctx)."""
    block = _sub_block(ctx, attrs["sub_block"])
    carried_names = attrs["carried_names"]
    cond_name = attrs["cond_name"]
    outer_env = dict(ctx.current_env)
    init = {n: v for n, v in zip(carried_names, ins["X"])}
    init[cond_name] = ins["Condition"][0]
    init["__iter__"] = jnp.zeros((), jnp.int32)

    def cond_fn(carry):
        return jnp.reshape(carry[cond_name], ()).astype(bool)

    def body_fn(carry):
        env = dict(outer_env)
        env.update(carry)
        env.pop("__iter__")
        # per-iteration PRNG token: random ops inside the loop draw fresh
        # keys each iteration instead of a baked trace-time constant
        prev_token = ctx._iter_token
        ctx._iter_token = carry["__iter__"]
        try:
            env = _run_block_ops(ctx, block, env)
        finally:
            ctx._iter_token = prev_token
        out = {n: env[n] for n in carried_names}
        out[cond_name] = env[cond_name]
        out["__iter__"] = carry["__iter__"] + 1
        return out

    final = lax.while_loop(cond_fn, body_fn, init)
    return {"Out": [final[n] for n in carried_names]}


@register_op("conditional_block")
def _conditional_block(ctx, ins, attrs):
    """Run a sub-block iff cond; assigned vars escape (must pre-exist so the
    false branch has values)."""
    block = _sub_block(ctx, attrs["sub_block"])
    written = attrs["written_names"]
    outer_env = dict(ctx.current_env)
    cond = jnp.reshape(ins["Cond"][0], ()).astype(bool)
    prev_vals = ins["X"]  # current values of written vars

    def true_fn(vals):
        env = dict(outer_env)
        env.update(zip(written, vals))
        env = _run_block_ops(ctx, block, env)
        return tuple(env[n] for n in written)

    def false_fn(vals):
        return tuple(vals)

    outs = lax.cond(cond, true_fn, false_fn, tuple(prev_vals))
    return {"Out": list(outs)}


@register_op("cond")
def _cond(ctx, ins, attrs):
    """layers.cond(pred, true_fn, false_fn): both branches are sub-blocks;
    outputs are the paired return vars."""
    tb = _sub_block(ctx, attrs["true_block"])
    fb = _sub_block(ctx, attrs["false_block"])
    t_names = attrs["true_out_names"]
    f_names = attrs["false_out_names"]
    outer_env = dict(ctx.current_env)
    pred = jnp.reshape(ins["Cond"][0], ()).astype(bool)

    def t_fn(_):
        env = _run_block_ops(ctx, tb, dict(outer_env))
        return tuple(env[n] for n in t_names)

    def f_fn(_):
        env = _run_block_ops(ctx, fb, dict(outer_env))
        return tuple(env[n] for n in f_names)

    outs = lax.cond(pred, t_fn, f_fn, 0)
    return {"Out": list(outs)}


@register_op("static_rnn")
def _static_rnn(ctx, ins, attrs):
    """StaticRNN: lax.scan of the step sub-block over the time axis.
    step inputs (T, ...) sliced per step; memories carried."""
    block = _sub_block(ctx, attrs["sub_block"])
    mem_names = attrs["mem_names"]          # in-block memory var names
    mem_updated = attrs["mem_updated"]      # names holding new memory value
    x_names = attrs["x_names"]              # in-block step-input names
    out_names = attrs["out_names"]          # step outputs collected
    outer_env = dict(ctx.current_env)
    mems = ins["Mem"]
    xs = ins["X"]  # each (T, ...)

    tsteps = xs[0].shape[0] if xs else 1

    def step(carry, inp):
        t, xt = inp
        env = dict(outer_env)
        env.update(zip(mem_names, carry))
        env.update(zip(x_names, xt))
        prev_token = ctx._iter_token
        ctx._iter_token = t
        try:
            env = _run_block_ops(ctx, block, env)
        finally:
            ctx._iter_token = prev_token
        new_carry = tuple(env[n] for n in mem_updated)
        outs = tuple(env[n] for n in out_names)
        return new_carry, outs

    _, stacked = lax.scan(
        step, tuple(mems), (jnp.arange(tsteps), tuple(xs))
    )
    return {"Out": list(stacked)}


@register_op("dynamic_rnn")
def _dynamic_rnn(ctx, ins, attrs):
    """DynamicRNN (ref control_flow.py DynamicRNN / C++ rnn_memory_helper):
    lax.scan over the padded time axis of batch-major (B, T, ...) step
    inputs. Per-sequence lengths mask the memory carry (finished sequences
    freeze) and the stacked outputs (padding emits zeros) — equivalent to
    the reference's batch-shrinking without dynamic shapes."""
    block = _sub_block(ctx, attrs["sub_block"])
    mem_names = attrs["mem_names"]
    mem_updated = attrs["mem_updated"]
    x_names = attrs["x_names"]
    out_names = attrs["out_names"]
    outer_env = dict(ctx.current_env)
    mems = ins["Mem"]
    xs = [jnp.moveaxis(x, 1, 0) for x in ins["X"]]   # (T, B, ...)
    tsteps = xs[0].shape[0]
    batch = xs[0].shape[1]
    if ins.get("SeqLen"):
        seq_len = ins["SeqLen"][0].astype(jnp.int32)
    else:
        seq_len = jnp.full((batch,), tsteps, jnp.int32)

    def _mask_to(alive, val):
        m = alive.astype(val.dtype).reshape(
            (batch,) + (1,) * (val.ndim - 1)
        )
        return m

    def step(carry, inp):
        t, xt = inp
        env = dict(outer_env)
        env.update(zip(mem_names, carry))
        env.update(zip(x_names, xt))
        prev_token = ctx._iter_token
        ctx._iter_token = t
        try:
            env = _run_block_ops(ctx, block, env)
        finally:
            ctx._iter_token = prev_token
        alive = t < seq_len                          # (B,)
        new_carry = tuple(
            jnp.where(_mask_to(alive, env[n]) > 0, env[n], old)
            for n, old in zip(mem_updated, carry)
        )
        outs = tuple(
            env[n] * _mask_to(alive, env[n]) for n in out_names
        )
        return new_carry, outs

    _, stacked = lax.scan(
        step, tuple(mems), (jnp.arange(tsteps), tuple(xs))
    )
    # (T, B, ...) -> (B, T, ...)
    return {"Out": [jnp.moveaxis(s, 0, 1) for s in stacked]}


@register_op("gather_tree")
def _gather_tree(ctx, ins, attrs):
    """Beam-search backtrace (ref operators/gather_tree_op): ids/parents
    are (T, B, W); walk parent pointers from the last step backwards."""
    ids = ins["Ids"][0]
    parents = ins["Parents"][0].astype(jnp.int32)
    tsteps, batch, beam = ids.shape
    bidx = jnp.arange(batch)[:, None]

    def step(par, inp):
        id_t, par_t = inp                            # (B, W) each
        out_t = id_t[bidx, par]                      # follow current pointer
        par = par_t[bidx, par]
        return par, out_t

    init = jnp.tile(jnp.arange(beam)[None, :], (batch, 1))
    # last step emits its own ids; earlier steps follow the pointer chain
    par, _ = step(init, (ids[-1], parents[-1]))
    rev = (jnp.flip(ids[:-1], 0), jnp.flip(parents[:-1], 0))
    _, rows = lax.scan(step, par, rev)
    out = jnp.concatenate([jnp.flip(rows, 0), ids[-1:]], axis=0)
    return {"Out": [out]}


@register_op("is_empty")
def _is_empty(ctx, ins, attrs):
    x = ins["X"][0]
    return single(jnp.array(x.size == 0))


@register_op("select_input")
def _select_input(ctx, ins, attrs):
    xs = ins["X"]
    mask = jnp.reshape(ins["Mask"][0], ()).astype(jnp.int32)
    stacked = jnp.stack(xs)
    return single(stacked[mask])


@register_op("select_output")
def _select_output(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}
