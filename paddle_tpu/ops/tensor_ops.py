"""Tensor manipulation op lowerings.

Replaces cast_op, concat_op, reshape_op, transpose_op, slice_op, split_op,
gather/scatter ops, fill_constant, assign, one_hot, expand, stack, etc.
(ref: paddle/fluid/operators/{cast,concat,reshape,transpose,slice,gather,
scatter,fill_constant,assign,one_hot,expand,stack}_op.*).
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..fluid import core
from .registry import register_op, single


@register_op("cast")
def _cast(ctx, ins, attrs):
    x = ins["X"][0]
    dtype = core.np_dtype(core.convert_dtype(attrs["out_dtype"]))
    return single(x.astype(dtype))


@register_op("concat")
def _concat(ctx, ins, attrs):
    axis = ins["AxisTensor"][0] if ins.get("AxisTensor") else attrs.get("axis", 0)
    return single(jnp.concatenate(ins["X"], axis=int(axis)))


@register_op("split")
def _split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections[:-1])
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("reshape2")
def _reshape(ctx, ins, attrs):
    x = ins["X"][0]
    if ins.get("ShapeTensor"):
        shape = [int(s) for s in ins["ShapeTensor"]]
    else:
        shape = list(attrs["shape"])
    # paddle: 0 means copy dim from input
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return {
        "Out": [x.reshape(shape)],
        "XShape": [jnp.zeros((0,) + x.shape, x.dtype)],
    }


@register_op("transpose2")
def _transpose(ctx, ins, attrs):
    x = ins["X"][0]
    return {
        "Out": [jnp.transpose(x, attrs["axis"])],
        "XShape": [jnp.zeros((0,) + x.shape, x.dtype)],
    }


@register_op("squeeze2")
def _squeeze(ctx, ins, attrs):
    x = ins["X"][0]
    axes = attrs.get("axes", [])
    if axes:
        axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
        out = jnp.squeeze(x, axis=axes) if axes else x
    else:
        out = jnp.squeeze(x)
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register_op("unsqueeze2")
def _unsqueeze(ctx, ins, attrs):
    x = ins["X"][0]
    out = x
    for a in sorted(attrs["axes"]):
        out = jnp.expand_dims(out, a)
    return {"Out": [out], "XShape": [jnp.zeros((0,) + x.shape, x.dtype)]}


@register_op("flatten2")
def _flatten(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = 1
    for s in x.shape[:axis]:
        lead *= s
    return {
        "Out": [x.reshape((lead, -1))],
        "XShape": [jnp.zeros((0,) + x.shape, x.dtype)],
    }


@register_op("slice")
def _slice(ctx, ins, attrs):
    x = ins["Input"][0]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        dim = x.shape[ax]
        st = max(st + dim, 0) if st < 0 else min(st, dim)
        en = max(en + dim, 0) if en < 0 else min(en, dim)
        idx[ax] = slice(st, en)
    return single(x[tuple(idx)])


@register_op("strided_slice")
def _strided_slice(ctx, ins, attrs):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for ax, st, en, sd in zip(
        attrs["axes"], attrs["starts"], attrs["ends"], attrs["strides"]
    ):
        idx[ax] = slice(st, en, sd)
    return single(x[tuple(idx)])


@register_op("fill_constant")
def _fill_constant(ctx, ins, attrs):
    shape = attrs.get("shape", [])
    if ins.get("ShapeTensor"):
        shape = [int(v) for v in ins["ShapeTensor"]]
    dtype = core.np_dtype(core.convert_dtype(attrs["dtype"]))
    value = attrs.get("value", 0.0)
    if ins.get("ValueTensor"):
        value = ins["ValueTensor"][0]
    return single(jnp.full(tuple(int(s) for s in shape), value, dtype=dtype))


@register_op("fill_constant_batch_size_like")
def _fill_constant_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = core.np_dtype(core.convert_dtype(attrs["dtype"]))
    return single(jnp.full(tuple(shape), attrs.get("value", 0.0), dtype=dtype))


@register_op("fill_zeros_like")
def _fill_zeros_like(ctx, ins, attrs):
    return single(jnp.zeros_like(ins["X"][0]))


@register_op("assign")
def _assign(ctx, ins, attrs):
    return single(ins["X"][0])


@register_op("assign_value")
def _assign_value(ctx, ins, attrs):
    dtype = core.np_dtype(core.convert_dtype(attrs["dtype"]))
    values = np.array(attrs["values"], dtype=dtype).reshape(attrs["shape"])
    return single(jnp.asarray(values))


@register_op("shape")
def _shape(ctx, ins, attrs):
    x = ins["Input"][0]
    return single(jnp.array(x.shape, dtype=jnp.int32))


@register_op("size")
def _size(ctx, ins, attrs):
    x = ins["Input"][0]
    return single(jnp.array(x.size, dtype=jnp.int64))


@register_op("gather")
def _gather(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    if idx.ndim == 2 and idx.shape[1] == 1:
        idx = idx[:, 0]
    return single(jnp.take(x, idx, axis=0))


@register_op("gather_nd")
def _gather_nd(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    # idx shape (..., k): index into first k dims of x
    k = idx.shape[-1]
    out = x[tuple(jnp.moveaxis(idx, -1, 0))]
    return single(out)


@register_op("scatter")
def _scatter(ctx, ins, attrs):
    x, idx, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    if idx.ndim == 2 and idx.shape[1] == 1:
        idx = idx[:, 0]
    if attrs.get("overwrite", True):
        return single(x.at[idx].set(upd))
    return single(x.at[idx].set(0).at[idx].add(upd))


@register_op("scatter_nd_add")
def _scatter_nd_add(ctx, ins, attrs):
    x, idx, upd = ins["X"][0], ins["Index"][0], ins["Updates"][0]
    return single(x.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd))


@register_op("one_hot")
def _one_hot(ctx, ins, attrs):
    x = ins["X"][0]
    depth = attrs["depth"]
    # v1 convention collapses a trailing (n, 1) ids dim; the v2 API
    # (fluid.input.one_hot) appends depth to the shape as-is
    if x.ndim >= 2 and x.shape[-1] == 1 and attrs.get("_squeeze", True):
        x = x[..., 0]
    out = jax.nn.one_hot(x, depth, dtype=jnp.float32)
    return single(out)


@register_op("expand")
def _expand(ctx, ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return single(jnp.tile(x, times))


@register_op("expand_as")
def _expand_as(ctx, ins, attrs):
    x, tgt = ins["X"][0], ins["target_tensor"][0]
    times = [t // s for t, s in zip(tgt.shape, x.shape)]
    return single(jnp.tile(x, times))


@register_op("stack")
def _stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack")
def _unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    num = x.shape[axis]
    outs = [jnp.squeeze(a, axis) for a in jnp.split(x, num, axis=axis)]
    return {"Y": outs}


@register_op("tile")
def _tile(ctx, ins, attrs):
    return single(jnp.tile(ins["X"][0], attrs["repeat_times"]))


@register_op("pad")
def _pad(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return single(jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0)))


@register_op("pad2d")
def _pad2d(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    fmt = attrs.get("data_format", "NCHW")
    if fmt == "NCHW":
        pads = [(0, 0), (0, 0), (p[0], p[1]), (p[2], p[3])]
    else:
        pads = [(0, 0), (p[0], p[1]), (p[2], p[3]), (0, 0)]
    if mode == "constant":
        return single(
            jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))
        )
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return single(jnp.pad(x, pads, mode=jmode))


@register_op("pad_constant_like")
def _pad_constant_like(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return single(jnp.pad(y, pads, constant_values=attrs.get("pad_value", 0.0)))


@register_op("arg_max")
def _arg_max(ctx, ins, attrs):
    return single(
        jnp.argmax(ins["X"][0], axis=attrs.get("axis", -1)).astype(jnp.int64)
    )


@register_op("arg_min")
def _arg_min(ctx, ins, attrs):
    return single(
        jnp.argmin(ins["X"][0], axis=attrs.get("axis", -1)).astype(jnp.int64)
    )


@register_op("argsort")
def _argsort(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    desc = attrs.get("descending", False)
    idx = jnp.argsort(-x if desc else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx.astype(jnp.int64)]}


@register_op("top_k")
def _top_k(ctx, ins, attrs):
    x = ins["X"][0]
    k = int(ins["K"][0]) if ins.get("K") else attrs["k"]
    vals, idx = lax.top_k(x, k)
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


@register_op("where_index")
def _where_index(ctx, ins, attrs):
    # nonzero has data-dependent shape; provide host-side only (documented)
    x = np.asarray(ins["Condition"][0])
    return single(jnp.asarray(np.stack(np.nonzero(x), axis=1).astype(np.int64)))


@register_op("where")
def _where(ctx, ins, attrs):
    return single(
        jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])
    )


@register_op("multiplex")
def _multiplex(ctx, ins, attrs):
    xs = jnp.stack(ins["X"], axis=0)  # (n, batch, d)
    idx = ins["Ids"][0].reshape(-1).astype(jnp.int32)
    batch = jnp.arange(idx.shape[0])
    return single(xs[idx, batch])


@register_op("range")
def _range(ctx, ins, attrs):
    start = ins["Start"][0] if ins.get("Start") else attrs["start"]
    end = ins["End"][0] if ins.get("End") else attrs["end"]
    step = ins["Step"][0] if ins.get("Step") else attrs["step"]
    return single(jnp.arange(float(start), float(end), float(step)).astype(
        core.np_dtype(core.convert_dtype(attrs.get("dtype", "float32")))
    ))


@register_op("linspace")
def _linspace(ctx, ins, attrs):
    start = float(ins["Start"][0]) if ins.get("Start") else attrs["start"]
    stop = float(ins["Stop"][0]) if ins.get("Stop") else attrs["stop"]
    num = int(ins["Num"][0]) if ins.get("Num") else attrs["num"]
    return single(jnp.linspace(start, stop, num))


@register_op("increment")
def _increment(ctx, ins, attrs):
    return single(ins["X"][0] + attrs.get("step", 1.0))


@register_op("eye")
def _eye(ctx, ins, attrs):
    dtype = core.np_dtype(core.convert_dtype(attrs.get("dtype", "float32")))
    return single(
        jnp.eye(attrs["num_rows"], attrs.get("num_columns") or attrs["num_rows"], dtype=dtype)
    )


@register_op("diag")
def _diag(ctx, ins, attrs):
    return single(jnp.diag(ins["Diagonal"][0]))


@register_op("reverse")
def _reverse(ctx, ins, attrs):
    x = ins["X"][0]
    return single(jnp.flip(x, axis=tuple(attrs["axis"])))


@register_op("roll")
def _roll(ctx, ins, attrs):
    return single(
        jnp.roll(ins["X"][0], attrs["shifts"], axis=tuple(attrs.get("axis", ())) or None)
    )


@register_op("flip")
def _flip(ctx, ins, attrs):
    return single(jnp.flip(ins["X"][0], axis=tuple(attrs["axis"])))


@register_op("crop")
def _crop(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = attrs.get("offsets")
    shape = attrs.get("shape")
    if ins.get("Y") is not None and ins.get("Y"):
        shape = ins["Y"][0].shape
    idx = tuple(
        slice(o, o + s) for o, s in zip(offsets, shape)
    )
    return single(x[idx])


@register_op("label_smooth")
def _label_smooth(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.1)
    if ins.get("PriorDist"):
        prior = ins["PriorDist"][0]
        out = (1 - eps) * x + eps * prior
    else:
        out = (1 - eps) * x + eps / x.shape[-1]
    return single(out)


@register_op("share_data")
def _share_data(ctx, ins, attrs):
    return single(ins["X"][0])


@register_op("print")
def _print(ctx, ins, attrs):
    x = ins["In"][0]
    import jax as _jax

    _jax.debug.print(attrs.get("message", "") + "{x}", x=x)
    return single(x)


@register_op("decode_cache_write")
def _decode_cache_write(ctx, ins, attrs):
    """TPU-native incremental-decode KV-cache write: Out = Cache with
    the (B, 1, H) step Value written at time index Pos along axis 1.

    Contract: the decode position is UNIFORM across the batch (row 0's
    value is used) — true for the KV-cache decoders here, where every
    row advances one token per scan step. Lowers to
    lax.dynamic_update_slice, an O(B·H) write, replacing the one-hot
    masked rewrite (mul+mul+add over the whole (B, T, H) cache) that
    re-reads and re-writes the entire cache every step — the decode
    equivalent of the reference's in-place beam-search cache kernels
    (ref: paddle/fluid/operators/math/beam_search.cc writes rows in
    place rather than rebuilding the tensor)."""
    cache, val, pos = ins["Cache"][0], ins["Value"][0], ins["Pos"][0]
    if attrs.get("per_row"):
        # continuous-batching slot semantics: every row is its OWN
        # sequence at its own position (freed slots restart at 0 while
        # neighbours keep decoding), so the write index varies per row.
        # vmap the row write — still O(B·H), no one-hot rewrite.
        import jax as _jax

        starts = pos.reshape(-1).astype(jnp.int32)

        def _row(c, v, s):
            return lax.dynamic_update_slice(
                c, v.astype(c.dtype), (s, jnp.int32(0)))

        return single(_jax.vmap(_row)(cache, val, starts))
    start = pos.reshape(-1)[0].astype(jnp.int32)
    zero = jnp.int32(0)
    return single(lax.dynamic_update_slice(
        cache, val.astype(cache.dtype), (zero, start, zero)))
