"""Sequence op lowerings.

Replaces the reference's LoD-walking sequence kernels
(ref: paddle/fluid/operators/sequence_ops/*) with masked/segment math on
dense-padded (B, T, ...) tensors + a SeqLen vector — static shapes that XLA
tiles on the MXU, no ragged host-side offset walking.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


def _mask(x, lens):
    """(B, T) bool validity mask broadcastable over x (B, T, ...)."""
    t = x.shape[1]
    m = jnp.arange(t)[None, :] < lens[:, None]
    return m.reshape(m.shape + (1,) * (x.ndim - 2))


def _lens(ins, x):
    if ins.get("SeqLen"):
        return ins["SeqLen"][0].astype(jnp.int32)
    return jnp.full((x.shape[0],), x.shape[1], jnp.int32)


@register_op("sequence_pool")
def _sequence_pool(ctx, ins, attrs):
    x = ins["X"][0]
    lens = _lens(ins, x)
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    m = _mask(x, lens)
    xm = jnp.where(m, x, 0.0)
    cnt = jnp.maximum(lens, 1).astype(x.dtype)
    cnt = cnt.reshape((-1,) + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(xm, axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(xm, axis=1) / cnt
    elif ptype == "SQRT":
        out = jnp.sum(xm, axis=1) / jnp.sqrt(cnt)
    elif ptype == "MAX":
        out = jnp.max(jnp.where(m, x, -jnp.inf), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        )[:, 0]
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise NotImplementedError("sequence_pool type %s" % ptype)
    return {"Out": [out], "MaxIndex": [jnp.zeros(out.shape, jnp.int32)]}


@register_op("sequence_softmax")
def _sequence_softmax(ctx, ins, attrs):
    x = ins["X"][0]  # (B, T) or (B, T, 1)
    lens = _lens(ins, x)
    m = _mask(x, lens)
    logits = jnp.where(m, x, -1e30)
    out = jax.nn.softmax(logits, axis=1)
    return single(jnp.where(m, out, 0.0))


@register_op("sequence_reverse")
def _sequence_reverse(ctx, ins, attrs):
    x = ins["X"][0]
    lens = _lens(ins, x)
    t = x.shape[1]
    # index i -> len-1-i inside each sequence, identity in padding
    idx = jnp.arange(t)[None, :]
    src = jnp.where(idx < lens[:, None], lens[:, None] - 1 - idx, idx)
    return {"Y": [jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
        axis=1,
    )]}


@register_op("sequence_expand")
def _sequence_expand(ctx, ins, attrs):
    """Repeat each sequence i of X ylens[i] times along a new ragged axis —
    dense form: X (B, T, ...) -> (B, Ty, T, ...) masked. The common use
    (X is per-sequence vector, ref_level=0) maps to broadcast."""
    x = ins["X"][0]
    y = ins["Y"][0]
    # dense padded: tile x rows to y's time dim
    if x.ndim == 2 and y.ndim >= 2:
        out = jnp.broadcast_to(
            x[:, None, :], (x.shape[0], y.shape[1], x.shape[1])
        )
        return single(out)
    raise NotImplementedError(
        "sequence_expand with %d-D X is a ragged repeat the dense-padded "
        "representation cannot express; restructure with broadcasting or "
        "gather over explicit indices" % x.ndim
    )


@register_op("sequence_expand_as")
def _sequence_expand_as(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    if x.ndim == 2 and y.ndim >= 2:
        return single(
            jnp.broadcast_to(x[:, None, :],
                             (x.shape[0], y.shape[1], x.shape[1]))
        )
    return single(jnp.broadcast_to(x, y.shape))


@register_op("sequence_concat")
def _sequence_concat(ctx, ins, attrs):
    """Concatenate along time; lens add. Dense: place second after first's
    length per row."""
    xs = ins["X"]
    if len(xs) == 1:
        return single(xs[0])
    lens_list = ins.get("SeqLen", [])
    if len(lens_list) != len(xs):
        return single(jnp.concatenate(xs, axis=1))
    total_t = sum(x.shape[1] for x in xs)
    b = xs[0].shape[0]
    out = jnp.zeros((b, total_t) + xs[0].shape[2:], xs[0].dtype)
    offs = jnp.zeros((b,), jnp.int32)
    for x, l in zip(xs, lens_list):
        t = x.shape[1]
        pos = offs[:, None] + jnp.arange(t)[None, :]
        valid = jnp.arange(t)[None, :] < l[:, None]
        bidx = jnp.arange(b)[:, None]
        out = out.at[bidx, jnp.where(valid, pos, total_t - 1)].add(
            jnp.where(valid.reshape(valid.shape + (1,) * (x.ndim - 2)), x, 0)
        )
        offs = offs + l.astype(jnp.int32)
    return single(out)


@register_op("sequence_conv")
def _sequence_conv(ctx, ins, attrs):
    """Context-window conv over time (ref sequence_conv_op.cc): for window k
    centered at contextStart, out_t = sum_j x[t+j] @ W_j."""
    x = ins["X"][0]        # (B, T, D)
    w = ins["Filter"][0]   # (k*D, F)
    lens = _lens(ins, x)
    k = attrs.get("contextLength", 3)
    start = attrs.get("contextStart", -(k // 2))
    d = x.shape[-1]
    m = _mask(x, lens)
    xm = jnp.where(m, x, 0.0)
    pieces = []
    for j in range(k):
        shift = start + j
        if shift < 0:
            shifted = jnp.pad(xm, ((0, 0), (-shift, 0), (0, 0)))[:, : x.shape[1]]
        elif shift > 0:
            shifted = jnp.pad(xm, ((0, 0), (0, shift), (0, 0)))[:, shift:]
        else:
            shifted = xm
        pieces.append(shifted)
    ctx_feat = jnp.concatenate(pieces, axis=-1)  # (B, T, k*D)
    out = jnp.einsum("btd,df->btf", ctx_feat, w)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return single(jnp.where(m, out, 0.0))


@register_op("sequence_mask")
def _sequence_mask(ctx, ins, attrs):
    x = ins["X"][0]  # lengths (B,) or (B,1)
    maxlen = attrs.get("maxlen", -1)
    if ins.get("MaxLenTensor"):
        try:
            maxlen = int(ins["MaxLenTensor"][0])
        except (TypeError, jax.errors.TracerIntegerConversionError,
                jax.errors.ConcretizationTypeError):
            raise NotImplementedError(
                "sequence_mask needs a static (python int) maxlen on TPU — "
                "a traced MaxLenTensor is a data-dependent output shape XLA "
                "cannot compile"
            )
    lens = x.reshape(-1).astype(jnp.int32)
    if maxlen is None or maxlen < 0:
        raise NotImplementedError(
            "sequence_mask needs static maxlen on TPU (data-dependent "
            "shapes can't be compiled); pass maxlen explicitly"
        )
    out = (jnp.arange(maxlen)[None, :] < lens[:, None])
    from ..fluid import core as _core

    dt = attrs.get("out_dtype", "int64")
    return {"Y": [out.astype(_core.np_dtype(_core.convert_dtype(dt)))]}


@register_op("sequence_pad")
def _sequence_pad(ctx, ins, attrs):
    # dense representation is already padded: re-pad to padded_length
    x = ins["X"][0]
    lens = _lens(ins, x)
    plen = attrs.get("padded_length", -1)
    pad_value = ins["PadValue"][0] if ins.get("PadValue") else 0.0
    t = x.shape[1]
    if plen is None or plen < 0:
        plen = t
    m = _mask(x, lens)
    out = jnp.where(m, x, pad_value)
    if plen > t:
        pads = [(0, 0), (0, plen - t)] + [(0, 0)] * (x.ndim - 2)
        out = jnp.pad(out, pads, constant_values=pad_value)
    else:
        out = out[:, :plen]
    return {"Out": [out], "Length": [lens.astype(jnp.int64)]}


@register_op("sequence_unpad")
def _sequence_unpad(ctx, ins, attrs):
    x = ins["X"][0]
    lens = ins["Length"][0].astype(jnp.int32)
    m = _mask(x, lens)
    return single(jnp.where(m, x, 0.0))


@register_op("sequence_enumerate")
def _sequence_enumerate(ctx, ins, attrs):
    x = ins["X"][0]  # (B, T)
    win = attrs["win_size"]
    pad = attrs.get("pad_value", 0)
    t = x.shape[1]
    cols = []
    for j in range(win):
        if j == 0:
            cols.append(x)
        else:
            cols.append(
                jnp.pad(x, ((0, 0), (0, j)), constant_values=pad)[:, j:]
            )
    return single(jnp.stack(cols, axis=-1))


@register_op("sequence_slice")
def _sequence_slice(ctx, ins, attrs):
    x = ins["X"][0]
    offset = ins["Offset"][0].reshape(-1).astype(jnp.int32)
    length = ins["Length"][0].reshape(-1).astype(jnp.int32)
    t = x.shape[1]
    idx = jnp.arange(t)[None, :]
    src = offset[:, None] + idx
    valid = idx < length[:, None]
    src = jnp.where(valid, jnp.minimum(src, t - 1), 0)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1
    )
    return single(jnp.where(
        valid.reshape(valid.shape + (1,) * (x.ndim - 2)), out, 0.0
    ))


@register_op("sequence_erase")
def _sequence_erase(ctx, ins, attrs):
    raise NotImplementedError(
        "sequence_erase produces data-dependent lengths; filter host-side "
        "before feeding (TPU requires static shapes)"
    )


@register_op("lod_reset")
def _lod_reset(ctx, ins, attrs):
    x = ins["X"][0]
    return single(x)


@register_op("sequence_reshape")
def _sequence_reshape(ctx, ins, attrs):
    x = ins["X"][0]
    dim = attrs["new_dim"]
    b, t = x.shape[0], x.shape[1]
    d = x.shape[2] if x.ndim > 2 else 1
    return single(x.reshape(b, t * d // dim, dim))


@register_op("sequence_scatter")
def _sequence_scatter(ctx, ins, attrs):
    x = ins["X"][0]
    ids = ins["Ids"][0].astype(jnp.int32)
    upd = ins["Updates"][0]
    b = x.shape[0]
    bidx = jnp.arange(b)[:, None]
    return single(x.at[bidx, ids].add(upd))


@register_op("edit_distance")
def _edit_distance(ctx, ins, attrs):
    """Levenshtein distance between padded hyp/ref token sequences
    (ref edit_distance_op.cc) via a lax.scan DP."""
    hyp = ins["Hyps"][0].astype(jnp.int32)     # (B, Th)
    ref = ins["Refs"][0].astype(jnp.int32)     # (B, Tr)
    hyp_lens = (
        ins["HypsLength"][0].astype(jnp.int32).reshape(-1)
        if ins.get("HypsLength")
        else jnp.full((hyp.shape[0],), hyp.shape[1], jnp.int32)
    )
    ref_lens = (
        ins["RefsLength"][0].astype(jnp.int32).reshape(-1)
        if ins.get("RefsLength")
        else jnp.full((ref.shape[0],), ref.shape[1], jnp.int32)
    )
    normalized = attrs.get("normalized", False)
    b, th = hyp.shape
    tr = ref.shape[1]

    def per_batch(h, r, hl, rl):
        row0 = jnp.arange(tr + 1, dtype=jnp.float32)

        def step(row, i):
            # computing DP row i+1 (hyp position i)
            def inner(carry, j):
                prev_diag, new_row = carry
                cost = jnp.where(h[i] == r[j], 0.0, 1.0)
                val = jnp.minimum(
                    jnp.minimum(new_row[j] + 1.0, row[j + 1] + 1.0),
                    prev_diag + cost,
                )
                new_row = new_row.at[j + 1].set(val)
                return (row[j + 1], new_row), None

            new_row = jnp.zeros_like(row).at[0].set(i + 1.0)
            (_, new_row), _ = lax.scan(
                inner, (row[0], new_row), jnp.arange(tr)
            )
            # only advance while i < hl
            return jnp.where(i < hl, new_row, row), None

        row, _ = lax.scan(step, row0, jnp.arange(th))
        d = row[jnp.minimum(rl, tr)]
        return jnp.where(normalized, d / jnp.maximum(rl, 1), d)

    out = jax.vmap(per_batch)(hyp, ref, hyp_lens, ref_lens)
    return {
        "Out": [out[:, None]],
        "SequenceNum": [jnp.array(b, jnp.int64)],
    }
