"""NN op lowerings: activations, softmax, conv, pool, norms, dropout,
embedding, interpolation.

Replaces activation_op.*, softmax_op, conv_op/conv_cudnn_op, pool_op,
batch_norm_op, layer_norm_op, group_norm_op, instance_norm_op, dropout_op,
lookup_table_op, interpolate_op (ref: paddle/fluid/operators/...). Convs and
matmuls lower to lax.conv_general_dilated / dot_general so XLA tiles them on
the MXU; norms/activations are elementwise chains XLA fuses around them.
"""
import math
import os

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


# ---------------------------------------------------------------------------
# activations (ref: paddle/fluid/operators/activation_op.cc)
# ---------------------------------------------------------------------------
def _act(fn):
    def lower(ctx, ins, attrs):
        return single(fn(ins["X"][0], attrs))

    return lower


register_op("relu")(_act(lambda x, a: jax.nn.relu(x)))
register_op("sigmoid")(_act(lambda x, a: jax.nn.sigmoid(x)))
register_op("tanh")(_act(lambda x, a: jnp.tanh(x)))
register_op("exp")(_act(lambda x, a: jnp.exp(x)))
register_op("log")(_act(lambda x, a: jnp.log(x)))
register_op("sqrt")(_act(lambda x, a: jnp.sqrt(x)))
register_op("rsqrt")(_act(lambda x, a: lax.rsqrt(x)))
register_op("square")(_act(lambda x, a: x * x))
register_op("reciprocal")(_act(lambda x, a: 1.0 / x))
register_op("floor")(_act(lambda x, a: jnp.floor(x)))
register_op("ceil")(_act(lambda x, a: jnp.ceil(x)))
register_op("round")(_act(lambda x, a: jnp.round(x)))
register_op("sin")(_act(lambda x, a: jnp.sin(x)))
register_op("cos")(_act(lambda x, a: jnp.cos(x)))
register_op("tan")(_act(lambda x, a: jnp.tan(x)))
register_op("asin")(_act(lambda x, a: jnp.arcsin(x)))
register_op("acos")(_act(lambda x, a: jnp.arccos(x)))
register_op("atan")(_act(lambda x, a: jnp.arctan(x)))
register_op("sinh")(_act(lambda x, a: jnp.sinh(x)))
register_op("cosh")(_act(lambda x, a: jnp.cosh(x)))
register_op("erf")(_act(lambda x, a: jax.scipy.special.erf(x)))
register_op("gelu")(
    _act(lambda x, a: jax.nn.gelu(x, approximate=a.get("approximate", False)))
)
register_op("logsigmoid")(_act(lambda x, a: jax.nn.log_sigmoid(x)))
register_op("softplus")(_act(lambda x, a: jax.nn.softplus(x)))
register_op("softsign")(_act(lambda x, a: jax.nn.soft_sign(x)))
register_op("softshrink")(
    _act(
        lambda x, a: jnp.where(
            x > a.get("lambda", 0.5),
            x - a.get("lambda", 0.5),
            jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0),
        )
    )
)
register_op("hard_shrink")(
    _act(
        lambda x, a: jnp.where(
            jnp.abs(x) > a.get("threshold", 0.5), x, 0.0
        )
    )
)
register_op("tanh_shrink")(_act(lambda x, a: x - jnp.tanh(x)))
register_op("hard_sigmoid")(
    _act(
        lambda x, a: jnp.clip(
            a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0
        )
    )
)
register_op("hard_swish")(
    _act(
        lambda x, a: x
        * jnp.clip(x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0))
        / a.get("scale", 6.0)
    )
)
register_op("relu6")(
    _act(lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)))
)
register_op("brelu")(
    _act(lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)))
)
register_op("leaky_relu")(
    _act(lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 0.02) * x))
)
register_op("elu")(
    _act(
        lambda x, a: jnp.where(
            x >= 0, x, a.get("alpha", 1.0) * (jnp.exp(jnp.minimum(x, 0.0)) - 1)
        )
    )
)
register_op("selu")(
    _act(
        lambda x, a: a.get("scale", 1.0507009873554805)
        * jnp.where(
            x >= 0,
            x,
            a.get("alpha", 1.6732632423543772)
            * (jnp.exp(jnp.minimum(x, 0.0)) - 1),
        )
    )
)
register_op("swish")(
    _act(lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x))
)
register_op("stanh")(
    _act(
        lambda x, a: a.get("scale_b", 1.7159)
        * jnp.tanh(a.get("scale_a", 0.67) * x)
    )
)
register_op("soft_relu")(
    _act(
        lambda x, a: jnp.log(
            1 + jnp.exp(jnp.clip(x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))
        )
    )
)
register_op("thresholded_relu")(
    _act(lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0))
)
register_op("maxout")(
    _act(
        lambda x, a: jnp.max(
            x.reshape(
                (x.shape[0], a["groups"], x.shape[1] // a["groups"])
                + x.shape[2:]
            ),
            axis=1,
        )
    )
)


@register_op("prelu")
def _prelu(ctx, ins, attrs):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "all":
        alpha = alpha.reshape(())
    return single(jnp.where(x >= 0, x, alpha * x))


@register_op("softmax")
def _softmax(ctx, ins, attrs):
    return single(jax.nn.softmax(ins["X"][0], axis=attrs.get("axis", -1)))


@register_op("log_softmax")
def _log_softmax(ctx, ins, attrs):
    return single(jax.nn.log_softmax(ins["X"][0], axis=attrs.get("axis", -1)))


# ---------------------------------------------------------------------------
# dropout (ref: paddle/fluid/operators/dropout_op.cc)
# ---------------------------------------------------------------------------
def _dropout_keep_mask(ctx, p, shape, allow_quantized=True):
    """Bernoulli keep-mask for dropout; returns ``(mask, keep_prob)``
    where keep_prob is the EXACT probability the mask was drawn with.

    Default path rides XLA's native RngBitGenerator (rbg): threefry
    mask generation measured ~31% of a BERT-base train step on TPU v5e
    (82ms -> 40ms with dropout ablated); rbg recovers nearly all of it.
    PADDLE_TPU_DROPOUT_BITS=8 opts into quantized masks (only honored
    when ``allow_quantized``, i.e. the upscale_in_train caller): 8
    random bits per element, keep threshold quantized to t/256 (e.g.
    p=0.1 -> 230/256) with the RETURNED keep_prob that exact value so
    upscaling stays unbiased. Measured on v5e it is NOT the default:
    despite 4x fewer random bits it ties at T=128 and loses 4-6% at
    T=512 (bench_experiments/dropout_bits_ab.json) — the separate
    bits/bitcast/compare chain denies XLA the bernoulli-into-consumer
    fusion and the bool mask round-trips HBM. The rbg key derives from
    the same deterministic per-(op, draw) step key, so masks stay
    reproducible and identical between the forward pass and its vjp
    replay. PADDLE_TPU_DROPOUT_RBG=0 restores threefry."""
    key = ctx.next_rng()
    keep_prob = 1.0 - p
    if os.environ.get("PADDLE_TPU_DROPOUT_RBG", "1") != "0":
        kd = jax.random.key_data(key).astype(jnp.uint32).reshape(-1)
        if kd.size < 4:
            kd = jnp.concatenate([kd, kd])
        key = jax.random.wrap_key_data(kd[:4], impl="rbg")
        t = int(round(keep_prob * 256.0))
        # quantization gate: only take the 8-bit path when the implied
        # DROP rate (1 - t/256) is within 5% relative of the requested
        # p — tiny rates like p=0.002 would otherwise silently double
        # their regularization strength (quantum is 1/256)
        quantize_ok = (
            allow_quantized and 0 < t < 256 and p > 0
            and abs((1.0 - t / 256.0) - p) <= 0.05 * p
        )
        if quantize_ok and os.environ.get(
                "PADDLE_TPU_DROPOUT_BITS", "32") == "8":
            n = math.prod(shape)
            bits32 = jax.random.bits(key, ((n + 3) // 4,),
                                     dtype=jnp.uint32)
            bits8 = jax.lax.bitcast_convert_type(bits32, jnp.uint8)
            keep = (bits8.reshape(-1)[:n] < jnp.uint8(t)).reshape(shape)
            return keep, t / 256.0
    return jax.random.bernoulli(key, keep_prob, shape), keep_prob


@register_op("dropout")
def _dropout(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.is_test
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        if impl == "downgrade_in_infer":
            out = x * (1.0 - p)
        else:
            out = x
        return {"Out": [out], "Mask": [jnp.ones_like(x)]}
    # downgrade_in_infer scales by (1-p) at INFER time, so its train
    # mask must be drawn at exactly 1-p (no quantized threshold);
    # upscale_in_train rescales by whatever exact prob the mask used
    keep, keep_prob = _dropout_keep_mask(
        ctx, p, x.shape, allow_quantized=(impl == "upscale_in_train"))
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / max(keep_prob, 1e-8), 0.0)
    else:
        out = jnp.where(keep, x, 0.0)
    return {"Out": [out.astype(x.dtype)], "Mask": [keep.astype(x.dtype)]}


# ---------------------------------------------------------------------------
# embedding (ref: paddle/fluid/operators/lookup_table_op.cc)
# ---------------------------------------------------------------------------
@register_op("lookup_table_v2")
def _lookup_table(ctx, ins, attrs):
    w, ids = ins["W"][0], ins["Ids"][0]
    padding_idx = attrs.get("padding_idx", -1)
    squeeze_last = False
    if ids.ndim >= 2 and ids.shape[-1] == 1 and attrs.get("_squeeze", True):
        ids = ids[..., 0]
    if os.environ.get("PADDLE_TPU_EMBED_ONEHOT", "0") not in ("", "0"):
        # one-hot matmul path: the VJP is a dense (V, N)@(N, D) matmul on
        # the MXU instead of a scatter-add, which XLA serializes on TPU.
        # Worth it when N·V·D matmul time < scatter time (large batches).
        oh = jax.nn.one_hot(ids.astype(jnp.int32), w.shape[0], dtype=w.dtype)
        out = oh @ w
    else:
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    return single(out)


# ---------------------------------------------------------------------------
# conv / pool (ref: conv_op.cc, pool_op.cc — cuDNN path replaced by
# lax.conv_general_dilated which XLA maps onto the MXU)
# ---------------------------------------------------------------------------
def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


@register_op("conv2d")
def _conv2d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1) or 1
    pad_alg = attrs.get("padding_algorithm", "EXPLICIT")
    if pad_alg == "SAME":
        padding = "SAME"
    elif pad_alg == "VALID":
        padding = "VALID"
    else:
        if len(pads) == 4:
            padding = [(pads[0], pads[1]), (pads[2], pads[3])]
        else:
            padding = [(pads[0], pads[0]), (pads[1], pads[1])]
    # no preferred_element_type here: the TPU MXU accumulates bf16 convs
    # in f32 internally already, and jax's conv transpose (grad) rule
    # does not thread the widened output dtype — the f32 cotangent then
    # meets the bf16 lhs and conv_general_dilated rejects the mix (the
    # bf16 ResNet AMP path failed exactly there)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=padding,
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return {"Output": [out]}


@register_op("depthwise_conv2d")
def _depthwise_conv2d(ctx, ins, attrs):
    return _conv2d(ctx, ins, attrs)


@register_op("conv3d")
def _conv3d(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1, 1]), 3)
    pads = _pair(attrs.get("paddings", [0, 0, 0]), 3)
    dilations = _pair(attrs.get("dilations", [1, 1, 1]), 3)
    groups = attrs.get("groups", 1) or 1
    padding = [(p, p) for p in pads]
    out = lax.conv_general_dilated(
        x, w, strides, padding, rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )
    return {"Output": [out]}


@register_op("conv2d_transpose")
def _conv2d_transpose(ctx, ins, attrs):
    x, w = ins["Input"][0], ins["Filter"][0]
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1) or 1
    if (attrs.get("groups", 1) or 1) != 1:
        raise NotImplementedError(
            "conv2d_transpose groups>1: lax.conv_transpose has no grouped "
            "mode — split channels and concat results, or use groups=1"
        )
    # gradient of conv2d == transposed conv (ref conv2d_transpose_op.cc).
    # Paddle filter layout is (C_in, C_out, kh, kw); with
    # transpose_kernel=True the spec names the FORWARD-conv roles, so the
    # C_in axis sits in the 'O' slot (verified vs torch conv_transpose2d).
    # output_padding (from the layer's output_size) extends the bottom/right
    # edge by shrinking the high-side implicit crop, like the reference.
    opad = _pair(attrs.get("output_padding", [0, 0]))
    out = lax.conv_transpose(
        x,
        w,
        strides=strides,
        padding=[(p, p - o) for p, o in zip(pads, opad)],
        rhs_dilation=dilations,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        transpose_kernel=True,
    )
    return {"Output": [out]}


@register_op("conv3d_transpose")
def _conv3d_transpose(ctx, ins, attrs):
    """3-D transposed conv (ref conv3d_transpose_op.cc) — the gradient of
    conv3d, via lax.conv_transpose over NCDHW."""
    x, w = ins["Input"][0], ins["Filter"][0]
    if (attrs.get("groups", 1) or 1) != 1:
        raise NotImplementedError(
            "conv3d_transpose groups>1: lax.conv_transpose has no grouped "
            "mode — split channels and concat results, or use groups=1"
        )
    strides = _pair(attrs.get("strides", [1, 1, 1]), 3)
    pads = _pair(attrs.get("paddings", [0, 0, 0]), 3)
    dilations = _pair(attrs.get("dilations", [1, 1, 1]), 3)
    opad = _pair(attrs.get("output_padding", [0, 0, 0]), 3)
    out = lax.conv_transpose(
        x,
        w,
        strides=strides,
        padding=[(p, p - o) for p, o in zip(pads, opad)],
        rhs_dilation=dilations,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        transpose_kernel=True,
    )
    return {"Output": [out]}


def _pool(x, ksize, strides, pads, ptype, ceil_mode, exclusive, global_pool,
          adaptive=False):
    if global_pool:
        ksize = x.shape[2:]
        strides = ksize
        pads = (0,) * len(ksize)
    if adaptive:
        # adaptive: output size = ksize; use reduce_window with computed strides
        out_hw = ksize
        in_hw = x.shape[2:]
        strides = tuple(i // o for i, o in zip(in_hw, out_hw))
        ksize = tuple(i - (o - 1) * s for i, o, s in zip(in_hw, out_hw, strides))
        pads = (0,) * len(out_hw)
    window = (1, 1) + tuple(ksize)
    strides_full = (1, 1) + tuple(strides)
    pad_full = ((0, 0), (0, 0)) + tuple((p, p) for p in pads)
    if ceil_mode:
        # add extra right/bottom padding so ceil division is covered
        extra = []
        for i, (k, s, p) in enumerate(zip(ksize, strides, pads)):
            dim = x.shape[2 + i]
            out_ceil = -(-(dim + 2 * p - k) // s) + 1
            needed = (out_ceil - 1) * s + k - dim - 2 * p
            extra.append((p, p + max(0, needed)))
        pad_full = ((0, 0), (0, 0)) + tuple(extra)
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = lax.reduce_window(
            x, init, lax.max, window, strides_full, pad_full
        )
    else:
        summed = lax.reduce_window(
            x, 0.0, lax.add, window, strides_full, pad_full
        )
        if exclusive:
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(
                ones, 0.0, lax.add, window, strides_full, pad_full
            )
            out = summed / counts
        else:
            out = summed / float(np.prod(ksize))
    return out


@register_op("pool2d")
def _pool2d(ctx, ins, attrs):
    x = ins["X"][0]
    out = _pool(
        x,
        _pair(attrs.get("ksize", [2, 2])),
        _pair(attrs.get("strides", [1, 1])),
        _pair(attrs.get("paddings", [0, 0])),
        attrs.get("pooling_type", "max"),
        attrs.get("ceil_mode", False),
        attrs.get("exclusive", True),
        attrs.get("global_pooling", False),
        attrs.get("adaptive", False),
    )
    return single(out)


@register_op("pool3d")
def _pool3d(ctx, ins, attrs):
    x = ins["X"][0]
    out = _pool(
        x,
        _pair(attrs.get("ksize", [2, 2, 2]), 3),
        _pair(attrs.get("strides", [1, 1, 1]), 3),
        _pair(attrs.get("paddings", [0, 0, 0]), 3),
        attrs.get("pooling_type", "max"),
        attrs.get("ceil_mode", False),
        attrs.get("exclusive", True),
        attrs.get("global_pooling", False),
        attrs.get("adaptive", False),
    )
    return single(out)


# ---------------------------------------------------------------------------
# normalization (ref: batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc,
# instance_norm_op.cc). batch_norm keeps running stats as persistable state
# updated functionally in the one jitted step.
# ---------------------------------------------------------------------------
@register_op("batch_norm")
def _batch_norm(ctx, ins, attrs):
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or ctx.is_test
    use_global = attrs.get("use_global_stats", False) or is_test
    layout = attrs.get("data_layout", "NCHW")
    ch_axis = 1 if layout == "NCHW" else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]

    if use_global:
        use_mean, use_var = mean, var
        new_mean, new_var = mean, var
        saved_mean = jnp.zeros_like(mean)
        saved_var = jnp.ones_like(var)
    else:
        xf = x.astype(jnp.float32)
        bm = jnp.mean(xf, axis=axes)
        bv = jnp.var(xf, axis=axes)
        use_mean, use_var = bm, bv
        if os.environ.get("PADDLE_TPU_BN_FREEZE_STATS"):
            # experiment knob (bench_experiments/resnet_gap.py):
            # isolate the moving-stat update's cost; NOT for training
            new_mean, new_var = mean, var
        else:
            new_mean = momentum * mean + (1 - momentum) * bm
            new_var = momentum * var + (1 - momentum) * bv
        saved_mean = bm
        saved_var = 1.0 / jnp.sqrt(bv + eps)
    inv = lax.rsqrt(use_var.astype(jnp.float32) + eps)
    if os.environ.get("PADDLE_TPU_BN_BF16_APPLY") and \
            x.dtype == jnp.bfloat16:
        # experiment knob: per-channel scalars stay f32, the elementwise
        # normalize runs in the activation dtype (halves the fused
        # loop's working set on bf16 activations)
        g16 = (inv * scale.astype(jnp.float32)).astype(x.dtype)
        out = (x - use_mean.astype(x.dtype).reshape(bshape)) \
            * g16.reshape(bshape) \
            + bias.astype(x.dtype).reshape(bshape)
    else:
        out = (x.astype(jnp.float32) - use_mean.reshape(bshape)) * (
            inv * scale.astype(jnp.float32)
        ).reshape(bshape) + bias.astype(jnp.float32).reshape(bshape)
        out = out.astype(x.dtype)
    return {
        "Y": [out],
        "MeanOut": [new_mean.astype(mean.dtype)],
        "VarianceOut": [new_var.astype(var.dtype)],
        "SavedMean": [saved_mean],
        "SavedVariance": [saved_var],
    }


@register_op("layer_norm")
def _layer_norm(ctx, ins, attrs):
    import os

    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    begin = attrs.get("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    platform = ctx.platform or jax.default_backend()
    if (
        os.environ.get("PADDLE_TPU_PALLAS_LN")
        and platform == "tpu"
        and not ctx.mesh_axes
        and begin == x.ndim - 1
    ):
        # fused pallas kernel (opt-in; see ops/pallas_layernorm.py). The
        # kernel's own mean/rstd become Mean/Variance (no extra passes),
        # squeezed exactly like the default path squeezes its keepdims stats
        from .pallas_layernorm import fused_layer_norm

        scale = ins["Scale"][0] if ins.get("Scale") else None
        bias = ins["Bias"][0] if ins.get("Bias") else None
        out, mean, rstd = fused_layer_norm(x, scale, bias, eps,
                                           return_stats=True)
        var = 1.0 / (rstd * rstd) - eps
        lead = x.shape[:begin]
        mean_kd = mean.reshape(lead + (1,) * (x.ndim - begin))
        var_kd = var.reshape(lead + (1,) * (x.ndim - begin))
        return {
            "Y": [out],
            "Mean": [jnp.squeeze(mean_kd)],
            "Variance": [jnp.squeeze(var_kd)],
        }
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps)
    norm_shape = x.shape[begin:]
    if ins.get("Scale"):
        out = out * ins["Scale"][0].reshape(norm_shape).astype(jnp.float32)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(norm_shape).astype(jnp.float32)
    return {
        "Y": [out.astype(x.dtype)],
        "Mean": [jnp.squeeze(mean)],
        "Variance": [jnp.squeeze(var)],
    }


@register_op("group_norm")
def _group_norm(ctx, ins, attrs):
    x = ins["X"][0]
    g = attrs["groups"]
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        out = out * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(bshape)
    return {"Y": [out], "Mean": [jnp.squeeze(mean)], "Variance": [jnp.squeeze(var)]}


@register_op("instance_norm")
def _instance_norm(ctx, ins, attrs):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        out = out * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(bshape)
    return {
        "Y": [out],
        "SavedMean": [jnp.squeeze(mean)],
        "SavedVariance": [jnp.squeeze(var)],
    }


@register_op("data_norm")
def _data_norm(ctx, ins, attrs):
    x = ins["X"][0]
    size = ins["BatchSize"][0]
    bsum = ins["BatchSum"][0]
    bsquare = ins["BatchSquareSum"][0]
    mean = bsum / size
    scale = lax.rsqrt(bsquare / size - mean * mean + 1e-4)
    out = (x - mean) * scale
    return {"Y": [out], "Means": [mean], "Scales": [scale]}


@register_op("norm")
def _norm(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True) + eps)
    return {"Out": [x / norm], "Norm": [norm]}


def _wn_norm(v, dim):
    """||v|| over all axes except `dim` (dim=-1 → over everything)."""
    if dim is None or dim < 0:
        return jnp.sqrt(jnp.sum(v * v)).reshape((1,))
    axes = tuple(a for a in range(v.ndim) if a != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes))


@register_op("norm_except_dim")
def _norm_except_dim(ctx, ins, attrs):
    """g0 = ||v|| keeping axis `dim` (ref layer_helper_base.py
    __norm_except_dim); used by startup to seed weight-norm g so the
    initial effective weight equals the initialised v."""
    return single(_wn_norm(ins["V"][0], attrs.get("dim", -1)))


@register_op("weight_norm_reparam")
def _weight_norm_reparam(ctx, ins, attrs):
    """w = g * v / ||v|| (ref layer_helper_base.py:88 create_parameter
    weight-norm path). Differentiable in g and v via the jax vjp."""
    v = ins["V"][0]
    g = ins["G"][0]
    dim = attrs.get("dim", -1)
    norm = _wn_norm(v, dim)
    if dim is None or dim < 0:
        return single(v * (g[0] / norm[0]))
    bshape = [1] * v.ndim
    bshape[dim] = v.shape[dim]
    return single(v * (g / norm).reshape(bshape))


@register_op("lrn")
def _lrn(ctx, ins, attrs):
    x = ins["X"][0]
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    sq = x * x
    # sum over channel window: pad channels and reduce
    half = n // 2
    sq_pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    win = sum(
        sq_pad[:, i : i + x.shape[1]] for i in range(n)
    )
    mid = jnp.power(k + alpha * win, beta)
    return {"Out": [x / mid], "MidOut": [mid]}


@register_op("spectral_norm")
def _spectral_norm(ctx, ins, attrs):
    w = ins["Weight"][0]
    u = ins["U"][0]
    v = ins["V"][0]
    dim = attrs.get("dim", 0)
    power_iters = attrs.get("power_iters", 1)
    eps = attrs.get("eps", 1e-12)
    w2 = jnp.moveaxis(w, dim, 0).reshape((w.shape[dim], -1))
    for _ in range(power_iters):
        v = w2.T @ u
        v = v / jnp.maximum(jnp.linalg.norm(v), eps)
        u = w2 @ v
        u = u / jnp.maximum(jnp.linalg.norm(u), eps)
    sigma = u @ (w2 @ v)
    return single(w / sigma)


# ---------------------------------------------------------------------------
# interpolation / image (ref: interpolate_op.cc)
# ---------------------------------------------------------------------------
def _interp(ctx, ins, attrs, method):
    x = ins["X"][0]
    out_h = attrs.get("out_h", -1)
    out_w = attrs.get("out_w", -1)
    scale = attrs.get("scale", 0.0)
    if ins.get("OutSize"):
        sz = ins["OutSize"][0]
        out_h, out_w = int(sz[0]), int(sz[1])
    elif scale and scale > 0:
        out_h = int(x.shape[2] * scale)
        out_w = int(x.shape[3] * scale)
    out = jax.image.resize(
        x, (x.shape[0], x.shape[1], out_h, out_w), method=method
    )
    return single(out.astype(x.dtype))


@register_op("bilinear_interp")
def _bilinear_interp(ctx, ins, attrs):
    return _interp(ctx, ins, attrs, "bilinear")


@register_op("nearest_interp")
def _nearest_interp(ctx, ins, attrs):
    return _interp(ctx, ins, attrs, "nearest")


@register_op("trilinear_interp")
def _trilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]
    out_d = attrs.get("out_d", -1)
    out_h = attrs.get("out_h", -1)
    out_w = attrs.get("out_w", -1)
    out = jax.image.resize(
        x, (x.shape[0], x.shape[1], out_d, out_h, out_w), method="trilinear"
    )
    return single(out)


@register_op("grid_sampler")
def _grid_sampler(ctx, ins, attrs):
    x, grid = ins["X"][0], ins["Grid"][0]
    n, c, h, w = x.shape
    gx = (grid[..., 0] + 1) * (w - 1) / 2
    gy = (grid[..., 1] + 1) * (h - 1) / 2
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    x1, y1 = x0 + 1, y0 + 1
    wx = gx - x0
    wy = gy - y0

    def sample(xi, yi):
        xi = jnp.clip(xi, 0, w - 1)
        yi = jnp.clip(yi, 0, h - 1)
        bidx = jnp.arange(n)[:, None, None]
        return x[bidx, :, yi, xi]  # (n, oh, ow, c)

    v00 = sample(x0, y0)
    v01 = sample(x1, y0)
    v10 = sample(x0, y1)
    v11 = sample(x1, y1)
    wx_ = wx[..., None]
    wy_ = wy[..., None]
    out = (
        v00 * (1 - wx_) * (1 - wy_)
        + v01 * wx_ * (1 - wy_)
        + v10 * (1 - wx_) * wy_
        + v11 * wx_ * wy_
    )
    return {"Output": [jnp.moveaxis(out, -1, 1)]}


@register_op("affine_grid")
def _affine_grid(ctx, ins, attrs):
    theta = ins["Theta"][0]
    if ins.get("OutputShape"):
        oshape = [int(v) for v in np.asarray(ins["OutputShape"][0])]
    else:
        oshape = attrs["output_shape"]
    n, _, h, w = oshape
    ys = jnp.linspace(-1, 1, h)
    xs = jnp.linspace(-1, 1, w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1)  # (h, w, 3)
    out = jnp.einsum("hwk,njk->nhwj", base, theta)
    return {"Output": [out]}


@register_op("pixel_shuffle")
def _pixel_shuffle(ctx, ins, attrs):
    x = ins["X"][0]
    r = attrs["upscale_factor"]
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return single(x.reshape(n, c // (r * r), h * r, w * r))


@register_op("temporal_shift")
def _temporal_shift(ctx, ins, attrs):
    x = ins["X"][0]
    seg = attrs["seg_num"]
    ratio = attrs.get("shift_ratio", 0.25)
    nt, c, h, w = x.shape
    x = x.reshape(nt // seg, seg, c, h, w)
    c1 = int(c * ratio)
    fwd = jnp.pad(x[:, 1:, :c1], ((0, 0), (0, 1), (0, 0), (0, 0), (0, 0)))
    back = jnp.pad(x[:, :-1, c1 : 2 * c1], ((0, 0), (1, 0), (0, 0), (0, 0), (0, 0)))
    rest = x[:, :, 2 * c1 :]
    out = jnp.concatenate([fwd, back, rest], axis=2)
    return single(out.reshape(nt, c, h, w))


@register_op("add_position_encoding")
def _add_position_encoding(ctx, ins, attrs):
    x = ins["X"][0]
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    b, t, d = x.shape
    pos = jnp.arange(t)[:, None]
    i = jnp.arange(d // 2)[None, :]
    angle = pos / jnp.power(10000.0, 2 * i / d)
    pe = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=-1)
    return single(alpha * x + beta * pe[None, :, :])
