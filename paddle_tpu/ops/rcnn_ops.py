"""RCNN / RetinaNet detection-op lowerings.

TPU-native redesigns of the reference kernels under
paddle/fluid/operators/detection/ (anchor_generator_op.h,
rpn_target_assign_op.cc, generate_proposals_op.cc, sigmoid_focal_loss_op.h,
target_assign_op.h, detection_map_op.h, polygon_box_transform_op.cc,
box_decoder_and_assign_op.h).

Design deltas vs the reference (documented per op):
  * LoD-batched variable-length inputs/outputs become dense padded tensors
    with validity masks — static shapes so XLA can tile everything.
  * Target-assign ops return FULL per-anchor target/weight tensors instead
    of gathered index subsets; downstream losses apply the weights. This is
    mathematically the same objective and removes every dynamic gather.
  * Sampling (rpn_batch_size_per_im) is deterministic in anchor-index order
    (the reference's use_random=False path) — reproducible on TPU.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .detection_ops import _iou_matrix
from .registry import register_op, single


@register_op("anchor_generator")
def _anchor_generator(ctx, ins, attrs):
    """Faster-RCNN anchors (ref detection/anchor_generator_op.h): per cell,
    aspect_ratios loop outer, anchor_sizes loop inner; base w/h rounded from
    the stride area before scaling."""
    feat = ins["Input"][0]  # (N, C, H, W)
    sizes = attrs["anchor_sizes"]
    ratios = attrs["aspect_ratios"]
    stride = attrs["stride"]
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    offset = attrs.get("offset", 0.5)
    h, w = feat.shape[2], feat.shape[3]
    sw, sh = float(stride[0]), float(stride[1])
    # per-anchor width/height (python — all static)
    whs = []
    for ar in ratios:
        base_w = round(np.sqrt(sw * sh / ar))
        base_h = round(base_w * ar)
        for s in sizes:
            whs.append((s / sw * base_w, s / sh * base_h))
    aw = jnp.asarray([p[0] for p in whs], jnp.float32)  # (A,)
    ah = jnp.asarray([p[1] for p in whs], jnp.float32)
    x_ctr = jnp.arange(w, dtype=jnp.float32) * sw + offset * (sw - 1)
    y_ctr = jnp.arange(h, dtype=jnp.float32) * sh + offset * (sh - 1)
    xg, yg = jnp.meshgrid(x_ctr, y_ctr)          # (H, W)
    xg = xg[..., None]
    yg = yg[..., None]
    anchors = jnp.stack(
        [
            xg - 0.5 * (aw - 1), yg - 0.5 * (ah - 1),
            xg + 0.5 * (aw - 1), yg + 0.5 * (ah - 1),
        ],
        axis=-1,
    )                                            # (H, W, A, 4)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32), anchors.shape)
    return {"Anchors": [anchors], "Variances": [var]}


@register_op("sigmoid_focal_loss")
def _sigmoid_focal_loss(ctx, ins, attrs):
    """Elementwise focal loss (ref detection/sigmoid_focal_loss_op.h):
    labels are 1-indexed classes (0 = background contributes only negative
    terms, -1 = ignore), normalized by max(fg_num, 1). Grad comes free from
    jax autodiff over this forward."""
    x = ins["X"][0]                    # (R, C) logits
    label = ins["Label"][0].reshape(-1).astype(jnp.int32)   # (R,)
    fg_num = ins["FgNum"][0].reshape(-1)[0].astype(x.dtype)
    gamma = attrs.get("gamma", 2.0)
    alpha = attrs.get("alpha", 0.25)
    c = x.shape[1]
    d = jnp.arange(c)[None, :]
    g = label[:, None]
    c_pos = (g == d + 1).astype(x.dtype)
    c_neg = ((g != -1) & (g != d + 1)).astype(x.dtype)
    fg = jnp.maximum(fg_num, 1.0)
    p = jax.nn.sigmoid(x)
    # log(p) / log(1-p) in the numerically-stable softplus forms
    log_p = -jax.nn.softplus(-x)
    log_1mp = -jax.nn.softplus(x)
    term_pos = jnp.power(1.0 - p, gamma) * log_p
    term_neg = jnp.power(p, gamma) * log_1mp
    out = -c_pos * term_pos * (alpha / fg) - c_neg * term_neg * (
        (1.0 - alpha) / fg
    )
    return single(out)


@register_op("polygon_box_transform")
def _polygon_box_transform(ctx, ins, attrs):
    """EAST quad-geometry offsets -> absolute coords on a 4x-downsampled
    grid (ref detection/polygon_box_transform_op.cc): even channels are x
    (4*w_idx - v), odd channels y (4*h_idx - v)."""
    x = ins["Input"][0]  # (N, geo, H, W)
    n, g, h, w = x.shape
    wi = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
    hi = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
    even = (jnp.arange(g) % 2 == 0)[None, :, None, None]
    out = jnp.where(even, 4.0 * wi - x, 4.0 * hi - x)
    return {"Output": [out]}


@register_op("box_decoder_and_assign")
def _box_decoder_and_assign(ctx, ins, attrs):
    """Per-class decode + argmax-class assign (ref
    detection/box_decoder_and_assign_op.h): +1 width convention, dw/dh
    clipped at box_clip, background (class 0) keeps the prior box."""
    prior = ins["PriorBox"][0]           # (R, 4)
    pvar = ins["PriorBoxVar"][0]         # (4,)
    target = ins["TargetBox"][0]         # (R, 4*C)
    score = ins["BoxScore"][0]           # (R, C)
    clip = attrs.get("box_clip", 4.135)
    r = prior.shape[0]
    cnum = score.shape[1]
    t = target.reshape(r, cnum, 4)
    pw = prior[:, 2] - prior[:, 0] + 1.0
    ph = prior[:, 3] - prior[:, 1] + 1.0
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    dw = jnp.minimum(pvar[2] * t[..., 2], clip)
    dh = jnp.minimum(pvar[3] * t[..., 3], clip)
    cx = pvar[0] * t[..., 0] * pw[:, None] + pcx[:, None]
    cy = pvar[1] * t[..., 1] * ph[:, None] + pcy[:, None]
    bw = jnp.exp(dw) * pw[:, None]
    bh = jnp.exp(dh) * ph[:, None]
    decoded = jnp.stack(
        [cx - bw / 2, cy - bh / 2, cx + bw / 2 - 1, cy + bh / 2 - 1],
        axis=-1,
    )                                    # (R, C, 4)
    fg_score = score.at[:, 0].set(-jnp.inf) if cnum > 0 else score
    best = jnp.argmax(fg_score, axis=1)  # (R,)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, -1), axis=1
    )[:, 0]
    assigned = jnp.where((best > 0)[:, None], assigned, prior)
    return {
        "DecodeBox": [decoded.reshape(r, cnum * 4)],
        "OutputAssignBox": [assigned],
    }


@register_op("target_assign")
def _target_assign(ctx, ins, attrs):
    """Dense target assign (ref detection/target_assign_op.h). Input gt is
    the padded per-image tensor (N, G, K) (LoD rows -> batch dim); out[i,j]
    = gt[i, match[i,j]] where matched, else mismatch_value with weight 0;
    negative indices (N, P) mask sets weight 1 where its entry >= 0."""
    x = ins["X"][0]                      # (N, G, K)
    match = ins["MatchIndices"][0].astype(jnp.int32)  # (N, P)
    mismatch = attrs.get("mismatch_value", 0.0)
    idx = jnp.maximum(match, 0)
    out = jnp.take_along_axis(x, idx[:, :, None], axis=1)
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, out, jnp.asarray(mismatch, x.dtype))
    weight = matched.astype(jnp.float32)
    if ins.get("NegIndices"):
        neg = ins["NegIndices"][0]       # (N, P) >=0 marks a negative slot
        weight = jnp.maximum(weight, (neg >= 0)[:, :, None].astype(weight.dtype))
    return {"Out": [out], "OutWeight": [weight]}


def _encode_boxes(anchors, gts, var=None):
    """Center-size encode of gts (…,4 x1y1x2y2) against anchors (…,4)."""
    aw = anchors[..., 2] - anchors[..., 0] + 1.0
    ah = anchors[..., 3] - anchors[..., 1] + 1.0
    acx = anchors[..., 0] + aw / 2
    acy = anchors[..., 1] + ah / 2
    gw = jnp.maximum(gts[..., 2] - gts[..., 0] + 1.0, 1.0)
    gh = jnp.maximum(gts[..., 3] - gts[..., 1] + 1.0, 1.0)
    gcx = gts[..., 0] + gw / 2
    gcy = gts[..., 1] + gh / 2
    t = jnp.stack(
        [(gcx - acx) / aw, (gcy - acy) / ah, jnp.log(gw / aw),
         jnp.log(gh / ah)],
        axis=-1,
    )
    if var is not None:
        t = t / var
    return t


def _iou_xyxy(a, b):
    """IoU with the +1 pixel convention used by the RCNN family."""
    area_a = (a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1)
    area_b = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + 1, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def _assign_one_image(anchors, gt, crowd, im_info, pos_ov, neg_ov,
                      straddle, var):
    """Shared fg/bg analysis for rpn/retinanet target assign. Returns
    (fg_mask, bg_mask, argmax_gt, loc_target) — all per-anchor dense."""
    m = anchors.shape[0]
    valid_gt = ((gt[:, 2] - gt[:, 0]) > 0) & ((gt[:, 3] - gt[:, 1]) > 0)
    valid_gt &= ~(crowd > 0)
    iou = _iou_xyxy(anchors, gt)                       # (M, G)
    iou = jnp.where(valid_gt[None, :], iou, -1.0)
    a2g_max = jnp.max(iou, axis=1)                     # (M,)
    a2g_arg = jnp.argmax(iou, axis=1)
    # anchors straddling the image border are ignored entirely
    if straddle >= 0:
        imh, imw = im_info[0], im_info[1]
        inside = (
            (anchors[:, 0] >= -straddle)
            & (anchors[:, 1] >= -straddle)
            & (anchors[:, 2] < imw + straddle)
            & (anchors[:, 3] < imh + straddle)
        )
    else:
        inside = jnp.ones((m,), bool)
    # fg: best anchor of each gt, or IoU above threshold
    g2a_max = jnp.max(jnp.where(inside[:, None], iou, -1.0), axis=0)  # (G,)
    is_best = jnp.any(
        (iou >= jnp.maximum(g2a_max, 1e-10)[None, :]) & valid_gt[None, :],
        axis=1,
    )
    fg = inside & ((a2g_max >= pos_ov) | is_best) & (a2g_max > 0)
    bg = inside & ~fg & (a2g_max < neg_ov)
    matched_gt = gt[a2g_arg]                           # (M, 4)
    loc_t = _encode_boxes(anchors, matched_gt, var)
    return fg, bg, a2g_arg, loc_t


@register_op("rpn_target_assign")
def _rpn_target_assign(ctx, ins, attrs):
    """RPN anchor targets (ref detection/rpn_target_assign_op.cc), dense
    form: ScoreTarget (N, M) in {1 fg, 0 bg, -1 ignore}, LocTarget
    (N, M, 4) encoded gt offsets, BBoxInsideWeight (N, M, 4) = fg mask.
    Sampling to rpn_batch_size_per_im with rpn_fg_fraction follows the
    reference's deterministic (use_random=False) index-order rule."""
    anchors = ins["Anchor"][0].reshape(-1, 4)
    gt = ins["GtBoxes"][0]               # (N, G, 4) zero-padded
    crowd = ins["IsCrowd"][0]            # (N, G)
    im_info = ins["ImInfo"][0]           # (N, 3)
    var = ins["AnchorVar"][0].reshape(-1, 4) if ins.get("AnchorVar") else None
    batch_per_im = attrs.get("rpn_batch_size_per_im", 256)
    straddle = attrs.get("rpn_straddle_thresh", 0.0)
    fg_frac = attrs.get("rpn_fg_fraction", 0.5)
    pos_ov = attrs.get("rpn_positive_overlap", 0.7)
    neg_ov = attrs.get("rpn_negative_overlap", 0.3)
    fg_cap = int(batch_per_im * fg_frac)

    def per_image(gt_i, crowd_i, info_i):
        fg, bg, _, loc_t = _assign_one_image(
            anchors, gt_i, crowd_i, info_i, pos_ov, neg_ov, straddle, var
        )
        # deterministic subsample in index order
        fg_rank = jnp.cumsum(fg.astype(jnp.int32)) - 1
        fg_keep = fg & (fg_rank < fg_cap)
        n_fg = jnp.sum(fg_keep.astype(jnp.int32))
        bg_cap = batch_per_im - n_fg
        bg_rank = jnp.cumsum(bg.astype(jnp.int32)) - 1
        bg_keep = bg & (bg_rank < bg_cap)
        score_t = jnp.where(
            fg_keep, 1, jnp.where(bg_keep, 0, -1)
        ).astype(jnp.int32)
        w = fg_keep.astype(jnp.float32)[:, None] * jnp.ones((1, 4))
        return score_t, loc_t * w, w

    score_t, loc_t, w = jax.vmap(per_image)(gt, crowd, im_info)
    return {
        "ScoreTarget": [score_t],
        "LocationTarget": [loc_t],
        "BBoxInsideWeight": [w],
    }


@register_op("retinanet_target_assign")
def _retinanet_target_assign(ctx, ins, attrs):
    """RetinaNet anchor targets (ref rpn_target_assign_op.cc retinanet
    variant): no subsampling; fg labels carry the 1-indexed gt class,
    bg = 0, ignore = -1; also emits ForegroundNumber (N, 1)."""
    anchors = ins["Anchor"][0].reshape(-1, 4)
    gt = ins["GtBoxes"][0]               # (N, G, 4)
    gt_labels = ins["GtLabels"][0]       # (N, G) int32, 1-indexed
    crowd = ins["IsCrowd"][0]
    im_info = ins["ImInfo"][0]
    var = ins["AnchorVar"][0].reshape(-1, 4) if ins.get("AnchorVar") else None
    pos_ov = attrs.get("positive_overlap", 0.5)
    neg_ov = attrs.get("negative_overlap", 0.4)

    def per_image(gt_i, lab_i, crowd_i, info_i):
        fg, bg, arg, loc_t = _assign_one_image(
            anchors, gt_i, crowd_i, info_i, pos_ov, neg_ov, -1.0, var
        )
        cls = lab_i.astype(jnp.int32)[arg]
        score_t = jnp.where(fg, cls, jnp.where(bg, 0, -1)).astype(jnp.int32)
        w = fg.astype(jnp.float32)[:, None] * jnp.ones((1, 4))
        return score_t, loc_t * w, w, jnp.sum(fg.astype(jnp.int32))[None]

    score_t, loc_t, w, fg_num = jax.vmap(per_image)(
        gt, gt_labels, crowd, im_info
    )
    return {
        "ScoreTarget": [score_t],
        "LocationTarget": [loc_t],
        "BBoxInsideWeight": [w],
        "ForegroundNumber": [fg_num],
    }


@register_op("generate_proposals")
def _generate_proposals(ctx, ins, attrs):
    """RPN proposal generation (ref detection/generate_proposals_op.cc):
    decode deltas vs anchors, clip to image, drop boxes below min_size,
    pre-NMS top-k, greedy NMS, emit exactly post_nms_top_n rows per image
    (zero-padded) — static shapes instead of LoD output."""
    scores = ins["Scores"][0]            # (N, A, H, W)
    deltas = ins["BboxDeltas"][0]        # (N, A*4, H, W)
    im_info = ins["ImInfo"][0]           # (N, 3)
    anchors = ins["Anchors"][0].reshape(-1, 4)     # (H*W*A, 4)
    variances = ins["Variances"][0].reshape(-1, 4)
    pre_n = attrs.get("pre_nms_topN", 6000)
    post_n = attrs.get("post_nms_topN", 1000)
    nms_thresh = attrs.get("nms_thresh", 0.5)
    min_size = attrs.get("min_size", 0.1)
    n, a, h, w = scores.shape
    m = h * w * a
    pre_n = min(pre_n, m)

    def per_image(sc, dl, info):
        # (A, H, W) -> (H, W, A) to match the anchor layout
        sc = sc.transpose(1, 2, 0).reshape(-1)
        dl = dl.reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        t = dl * variances
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + aw / 2
        acy = anchors[:, 1] + ah / 2
        cx = t[:, 0] * aw + acx
        cy = t[:, 1] * ah + acy
        bw = jnp.exp(jnp.minimum(t[:, 2], np.log(1000.0 / 16))) * aw
        bh = jnp.exp(jnp.minimum(t[:, 3], np.log(1000.0 / 16))) * ah
        boxes = jnp.stack(
            [cx - bw / 2, cy - bh / 2, cx + bw / 2 - 1, cy + bh / 2 - 1],
            axis=-1,
        )
        # clip to image, then min_size filter in original-image scale
        imh, imw, scale = info[0], info[1], jnp.maximum(info[2], 1e-6)
        boxes = jnp.stack(
            [
                jnp.clip(boxes[:, 0], 0, imw - 1),
                jnp.clip(boxes[:, 1], 0, imh - 1),
                jnp.clip(boxes[:, 2], 0, imw - 1),
                jnp.clip(boxes[:, 3], 0, imh - 1),
            ],
            axis=-1,
        )
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        keep = (ws >= min_size * scale) & (hs >= min_size * scale)
        sc = jnp.where(keep, sc, -jnp.inf)
        top_sc, top_idx = lax.top_k(sc, pre_n)
        top_boxes = boxes[top_idx]

        def body(carry, _):
            cur = carry
            best = jnp.argmax(cur)
            best_sc = cur[best]
            best_box = top_boxes[best]
            ious = _iou_xyxy(best_box[None], top_boxes)[0]
            cur = jnp.where(
                (ious > nms_thresh) | (jnp.arange(pre_n) == best),
                -jnp.inf, cur,
            )
            valid = jnp.isfinite(best_sc)
            return cur, (
                jnp.where(valid, best_box, 0.0),
                jnp.where(valid, best_sc, 0.0),
            )

        _, (rois, probs) = lax.scan(body, top_sc, None, length=post_n)
        return rois, probs

    rois, probs = jax.vmap(per_image)(scores, deltas, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs[..., None]]}


@register_op("retinanet_detection_output")
def _retinanet_detection_output(ctx, ins, attrs):
    """RetinaNet decode + NMS (ref detection/retinanet_detection_output_op):
    per-FPN-level top-k by score, decode vs that level's anchors, then
    class-aware greedy NMS over the concatenation. Output (N, keep_top_k,
    6) rows [label, score, x1, y1, x2, y2], label -1 padding."""
    bbox_list = ins["BBoxes"]            # list of (N, Mi, 4) deltas
    score_list = ins["Scores"]           # list of (N, Mi, C) probs
    anchor_list = ins["Anchors"]         # list of (Mi, 4)
    im_info = ins["ImInfo"][0]
    score_thresh = attrs.get("score_threshold", 0.05)
    nms_top_k = attrs.get("nms_top_k", 1000)
    keep_top_k = attrs.get("keep_top_k", 100)
    nms_thresh = attrs.get("nms_threshold", 0.3)

    sel_boxes, sel_scores = [], []
    for bb, sc, an in zip(bbox_list, score_list, anchor_list):
        an = an.reshape(-1, 4)
        mi, c = sc.shape[1], sc.shape[2]
        k = min(nms_top_k, mi * c)

        def level(bb_i, sc_i, an=an, mi=mi, c=c, k=k):
            flat = sc_i.reshape(-1)                       # (Mi*C,)
            top, idx = lax.top_k(flat, k)
            box_idx = idx // c
            cls_idx = idx % c
            t = bb_i[box_idx]
            anc = an[box_idx]
            aw = anc[:, 2] - anc[:, 0] + 1.0
            ah = anc[:, 3] - anc[:, 1] + 1.0
            cx = t[:, 0] * aw + anc[:, 0] + aw / 2
            cy = t[:, 1] * ah + anc[:, 1] + ah / 2
            bw = jnp.exp(jnp.minimum(t[:, 2], np.log(1000.0 / 16))) * aw
            bh = jnp.exp(jnp.minimum(t[:, 3], np.log(1000.0 / 16))) * ah
            boxes = jnp.stack(
                [cx - bw / 2, cy - bh / 2, cx + bw / 2 - 1, cy + bh / 2 - 1],
                axis=-1,
            )
            return boxes, jnp.where(top > score_thresh, top, -1.0), cls_idx

        b, s, ci = jax.vmap(level)(bb, sc)
        sel_boxes.append((b, s, ci))

    boxes = jnp.concatenate([b for b, _, _ in sel_boxes], axis=1)
    scores = jnp.concatenate([s for _, s, _ in sel_boxes], axis=1)
    clses = jnp.concatenate([c for _, _, c in sel_boxes], axis=1)
    total = boxes.shape[1]

    def per_image(bx, sc, cl, info):
        imh, imw = info[0], info[1]
        bx = jnp.stack(
            [
                jnp.clip(bx[:, 0], 0, imw - 1),
                jnp.clip(bx[:, 1], 0, imh - 1),
                jnp.clip(bx[:, 2], 0, imw - 1),
                jnp.clip(bx[:, 3], 0, imh - 1),
            ],
            axis=-1,
        )

        def body(carry, _):
            cur = carry
            best = jnp.argmax(cur)
            best_sc = cur[best]
            bb = bx[best]
            cc = cl[best]
            ious = _iou_xyxy(bb[None], bx)[0]
            cur = jnp.where(
                ((ious > nms_thresh) & (cl == cc))
                | (jnp.arange(total) == best),
                -1.0, cur,
            )
            row = jnp.concatenate(
                [
                    jnp.where(best_sc > 0, cc + 1, -1)[None].astype(bx.dtype),
                    jnp.maximum(best_sc, 0.0)[None],
                    jnp.where(best_sc > 0, bb, 0.0),
                ]
            )
            return cur, row

        _, rows = lax.scan(body, sc, None, length=keep_top_k)
        return rows

    out = jax.vmap(per_image)(boxes, scores, clses, im_info)
    return {"Out": [out]}


@register_op("locality_aware_nms")
def _locality_aware_nms(ctx, ins, attrs):
    """EAST locality-aware NMS (ref detection/locality_aware_nms_op.cc):
    pass 1 merges consecutive same-class boxes with IoU > threshold by
    score-weighted averaging (row order = geometric locality); pass 2 is
    standard greedy NMS. Static (N, keep_top_k, 6) output like
    multiclass_nms."""
    bboxes = ins["BBoxes"][0]   # (N, M, 4)
    scores = ins["Scores"][0]   # (N, C, M)
    score_thresh = attrs["score_threshold"]
    nms_thresh = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", -1)
    nms_eta = attrs.get("nms_eta", 1.0)
    normalized = attrs.get("normalized", True)
    keep_top_k = attrs["keep_top_k"]
    background = attrs.get("background_label", -1)
    n, c, m = scores.shape
    # +1 pixel convention for unnormalized (pixel-coordinate) boxes
    iou_fn = _iou_matrix if normalized else _iou_xyxy

    def merge_pass(boxes, sc):
        """Sequential left-to-right merge (the EAST row-order pass)."""
        if nms_top_k is not None and 0 < nms_top_k < m:
            kth = lax.top_k(sc, nms_top_k)[0][-1]
            sc = jnp.where(sc >= kth, sc, -1.0)

        def body(carry, inp):
            cur_box, cur_score, have = carry
            box, s = inp
            valid = s > score_thresh
            iou = iou_fn(box[None], cur_box[None])[0, 0]
            mergeable = have & valid & (iou > nms_thresh)
            w_old = jnp.maximum(cur_score, 1e-12)
            w_new = jnp.maximum(s, 1e-12)
            merged_box = (cur_box * w_old + box * w_new) / (w_old + w_new)
            merged_score = cur_score + s
            # emit the finished cluster when the new box doesn't merge
            emit_box = jnp.where(have & valid & ~mergeable, cur_box, 0.0)
            emit_score = jnp.where(have & valid & ~mergeable, cur_score,
                                   -1.0)
            cur_box = jnp.where(
                mergeable, merged_box, jnp.where(valid, box, cur_box)
            )
            cur_score = jnp.where(
                mergeable, merged_score,
                jnp.where(valid, s, cur_score),
            )
            have = have | valid
            return (cur_box, cur_score, have), (emit_box, emit_score)

        init = (jnp.zeros((4,), boxes.dtype), jnp.asarray(-1.0, boxes.dtype),
                jnp.asarray(False))
        (last_box, last_score, have), (eb, es) = lax.scan(
            body, init, (boxes, sc)
        )
        eb = jnp.concatenate([eb, last_box[None]], axis=0)
        es = jnp.concatenate(
            [es, jnp.where(have, last_score, -1.0)[None]], axis=0
        )
        return eb, es

    def per_image(boxes, sc_all):
        all_boxes, all_scores, all_cls = [], [], []
        for cls in range(c):
            if cls == background:
                continue
            eb, es = merge_pass(boxes, sc_all[cls])
            all_boxes.append(eb)
            all_scores.append(es)
            all_cls.append(jnp.full(es.shape, cls, jnp.int32))
        flat_box = jnp.concatenate(all_boxes, axis=0)
        flat_scores = jnp.concatenate(all_scores, axis=0)
        flat_cls = jnp.concatenate(all_cls, axis=0)
        total = flat_scores.shape[0]

        def body(carry, _):
            cur, thresh = carry
            best = jnp.argmax(cur)
            best_score = cur[best]
            best_box = flat_box[best]
            best_cls = flat_cls[best]
            ious = iou_fn(best_box[None], flat_box)[0]
            suppress = ((ious > thresh) & (flat_cls == best_cls)) | (
                jnp.arange(total) == best
            )
            cur = jnp.where(suppress, -1.0, cur)
            # adaptive NMS: decay the threshold per kept box while > 0.5
            thresh = jnp.where(
                (best_score > 0) & (thresh > 0.5) & (nms_eta < 1.0),
                thresh * nms_eta, thresh,
            )
            row = jnp.concatenate(
                [
                    jnp.where(best_score > 0, best_cls, -1)[None].astype(
                        boxes.dtype
                    ),
                    jnp.maximum(best_score, 0.0)[None],
                    jnp.where(best_score > 0, best_box, 0.0),
                ]
            )
            return (cur, thresh), row

        init = (flat_scores, jnp.asarray(nms_thresh, boxes.dtype))
        _, rows = lax.scan(body, init, None, length=keep_top_k)
        return rows

    out = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [out]}


@register_op("generate_proposal_labels")
def _generate_proposal_labels(ctx, ins, attrs):
    """Fast-RCNN head sampling (ref detection/generate_proposal_labels_op
    .cc), dense static form: for every input roi (+ gt boxes appended),
    labels (fg class / 0 bg / -1 unsampled), per-roi encoded regression
    targets and inside weights. Sampling is deterministic index-order
    (use_random=False path)."""
    rois = ins["RpnRois"][0]            # (N, R, 4)
    gt_classes = ins["GtClasses"][0].astype(jnp.int32)   # (N, G)
    is_crowd = ins["IsCrowd"][0]        # (N, G)
    gt_boxes = ins["GtBoxes"][0]        # (N, G, 4)
    im_info = ins["ImInfo"][0]          # (N, 3)
    batch_per_im = attrs.get("batch_size_per_im", 256)
    fg_frac = attrs.get("fg_fraction", 0.25)
    fg_thresh = attrs.get("fg_thresh", 0.25)
    bg_hi = attrs.get("bg_thresh_hi", 0.5)
    bg_lo = attrs.get("bg_thresh_lo", 0.0)
    weights = jnp.asarray(
        attrs.get("bbox_reg_weights", [0.1, 0.1, 0.2, 0.2]), jnp.float32
    )
    fg_cap = int(batch_per_im * fg_frac)

    def per_image(roi, gt_cls, crowd, gt, info):
        # gt boxes join the candidate pool (ref appends them); crowd and
        # zero-padding gt rows are NOT candidates (the reference filters
        # crowd before sampling — letting them in would label crowd
        # regions as background and burn bg quota)
        valid_gt = ((gt[:, 2] - gt[:, 0]) > 0) & (~(crowd > 0))
        cand = jnp.concatenate([roi, gt], axis=0)            # (R+G, 4)
        row_valid = jnp.concatenate(
            [jnp.ones((roi.shape[0],), bool), valid_gt]
        )
        iou = _iou_xyxy(cand, gt)
        iou = jnp.where(valid_gt[None, :], iou, -1.0)
        max_iou = jnp.max(iou, axis=1)
        argmax_gt = jnp.argmax(iou, axis=1)
        fg = row_valid & (max_iou >= fg_thresh)
        bg = row_valid & (max_iou < bg_hi) & (max_iou >= bg_lo)
        fg_rank = jnp.cumsum(fg.astype(jnp.int32)) - 1
        fg_keep = fg & (fg_rank < fg_cap)
        n_fg = jnp.sum(fg_keep.astype(jnp.int32))
        bg_rank = jnp.cumsum(bg.astype(jnp.int32)) - 1
        bg_keep = bg & (bg_rank < batch_per_im - n_fg)
        labels = jnp.where(
            fg_keep, gt_cls[argmax_gt],
            jnp.where(bg_keep, 0, -1),
        ).astype(jnp.int32)
        matched = gt[argmax_gt]
        targets = _encode_boxes(cand, matched) / weights
        w = fg_keep.astype(jnp.float32)[:, None] * jnp.ones((1, 4))
        return cand, labels, targets * w, w

    rois_o, labels, targets, w = jax.vmap(per_image)(
        rois, gt_classes, is_crowd, gt_boxes, im_info
    )
    return {
        "Rois": [rois_o],
        "LabelsInt32": [labels],
        "BboxTargets": [targets],
        "BboxInsideWeights": [w],
        "BboxOutsideWeights": [w],
    }


@register_op("generate_mask_labels")
def _generate_mask_labels(ctx, ins, attrs):
    """Mask-RCNN mask targets (ref detection/generate_mask_labels_op.cc):
    for each foreground roi, rasterize its matched instance polygon into
    the roi-local resolution x resolution grid. TPU redesign: polygons
    travel dense-padded (N, G, P, 2) with per-gt vertex counts; the
    point-in-polygon test is a vectorized ray cast over all pixel centers
    and edges — no host geometry library. One polygon per instance (the
    reference's multi-part polygons pre-merge host-side)."""
    gt_classes = ins["GtClasses"][0].astype(jnp.int32)   # (N, G)
    is_crowd = ins["IsCrowd"][0]                          # (N, G)
    gt_segms = ins["GtSegms"][0]                          # (N, G, P, 2)
    segm_lens = ins["GtSegmLens"][0].astype(jnp.int32)    # (N, G)
    rois = ins["Rois"][0]                                 # (N, R, 4)
    labels = ins["LabelsInt32"][0].astype(jnp.int32)      # (N, R)
    num_classes = attrs["num_classes"]
    res = attrs["resolution"]
    n, g, p_max, _ = gt_segms.shape
    r = rois.shape[1]

    def rasterize(poly, nverts, roi):
        """(res, res) 0/1 mask of the polygon inside roi-local coords."""
        x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
        w = jnp.maximum(x2 - x1, 1e-6)
        h = jnp.maximum(y2 - y1, 1e-6)
        px = x1 + (jnp.arange(res) + 0.5) * w / res
        py = y1 + (jnp.arange(res) + 0.5) * h / res
        gx, gy = jnp.meshgrid(px, py)                     # (res, res)
        vi = poly                                          # (P, 2)
        vj = jnp.roll(poly, -1, axis=0)
        eidx = jnp.arange(p_max)
        # closing edge connects vertex nverts-1 back to vertex 0
        vj = jnp.where(
            (eidx == nverts - 1)[:, None], poly[0][None, :], vj
        )
        valid_e = eidx < nverts
        yi, yj = vi[:, 1], vj[:, 1]
        xi, xj = vi[:, 0], vj[:, 0]
        # ray cast to +x: edge crosses the horizontal line of the pixel
        crosses = (yi[:, None, None] > gy[None]) != (
            yj[:, None, None] > gy[None]
        )
        t = (gy[None] - yi[:, None, None]) / jnp.where(
            jnp.abs(yj - yi)[:, None, None] < 1e-12,
            1e-12, (yj - yi)[:, None, None],
        )
        x_at = xi[:, None, None] + t * (xj - xi)[:, None, None]
        hit = crosses & (gx[None] < x_at) & valid_e[:, None, None]
        inside = jnp.sum(hit.astype(jnp.int32), axis=0) % 2
        return inside                                      # (res, res)

    def per_image(segms, lens, cls, crowd, roi, lab):
        valid_gt = (lens >= 3) & (~(crowd > 0))
        # bbox per gt over its REAL vertices only (padding rows would
        # otherwise drag the box toward the origin)
        vmask = (
            jnp.arange(p_max)[None, :, None] < lens[:, None, None]
        )
        lo = jnp.where(vmask, segms, jnp.inf).min(axis=1)
        hi = jnp.where(vmask, segms, -jnp.inf).max(axis=1)
        iou = _iou_xyxy(roi, jnp.concatenate([lo, hi], axis=-1))
        iou = jnp.where(valid_gt[None, :], iou, -1.0)
        match = jnp.argmax(iou, axis=1)                    # (R,)
        fg = lab > 0

        def one_roi(rb, m, l, is_fg):
            mask = rasterize(segms[m], lens[m], rb)        # (res, res)
            # class-specific slot: channel l gets the mask, others 0;
            # non-fg rois are all -1 (ignore), like the reference
            oh = (jnp.arange(num_classes) == l).astype(jnp.int32)
            full = oh[:, None, None] * mask[None]
            return jnp.where(is_fg, full, -1)

        masks = jax.vmap(one_roi)(roi, match, lab, fg)
        return roi, fg.astype(jnp.int32), masks.reshape(
            r, num_classes * res * res
        )

    mask_rois, has_mask, mask_int32 = jax.vmap(per_image)(
        gt_segms, segm_lens, gt_classes, is_crowd, rois, labels
    )
    return {
        "MaskRois": [mask_rois],
        "RoiHasMaskInt32": [has_mask],
        "MaskInt32": [mask_int32],
    }


@register_op("roi_perspective_transform")
def _roi_perspective_transform(ctx, ins, attrs):
    """Perspective-warp quad ROIs to a fixed grid (ref detection/
    roi_perspective_transform_op.cc, EAST OCR): each ROI is 8 coords
    (x1..y4 clockwise); the exact homography (square -> quad, handles
    foreshortening) maps output pixels to source points, sampled
    bilinearly."""
    x = ins["X"][0]                      # (N, C, H, W)
    rois = ins["ROIs"][0]                # (R, 8)
    bidx = (
        ins["RoisBatchIdx"][0].astype(jnp.int32)
        if ins.get("RoisBatchIdx")
        else jnp.zeros((rois.shape[0],), jnp.int32)
    )
    th = attrs.get("transformed_height", 1)
    tw = attrs.get("transformed_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def warp_one(quad, bi):
        q = quad.reshape(4, 2) * scale   # (x, y) clockwise from top-left
        # TRUE perspective transform (ref get_transform_matrix): the
        # homography mapping the unit square's corners (0,0),(1,0),(1,1),
        # (0,1) onto the quad, closed form for a square source. A
        # ruled-surface blend would only coincide for parallelograms.
        p0, p1, p2, p3 = q[0], q[1], q[2], q[3]
        s = p0 - p1 + p2 - p3
        d1 = p1 - p2
        d2 = p3 - p2
        den = d1[0] * d2[1] - d2[0] * d1[1]
        den = jnp.where(jnp.abs(den) < 1e-12, 1e-12, den)
        g = (s[0] * d2[1] - d2[0] * s[1]) / den
        hh = (d1[0] * s[1] - s[0] * d1[1]) / den
        affine = jnp.all(jnp.abs(s) < 1e-9)
        g = jnp.where(affine, 0.0, g)
        hh = jnp.where(affine, 0.0, hh)
        H = jnp.array(
            [
                [p1[0] - p0[0] + g * p1[0], p3[0] - p0[0] + hh * p3[0],
                 p0[0]],
                [p1[1] - p0[1] + g * p1[1], p3[1] - p0[1] + hh * p3[1],
                 p0[1]],
                [g, hh, 1.0],
            ]
        )
        us = (jnp.arange(tw) + 0.5) / tw
        vs = (jnp.arange(th) + 0.5) / th
        ug, vg = jnp.meshgrid(us, vs)    # (th, tw)
        ones = jnp.ones_like(ug)
        uv1 = jnp.stack([ug, vg, ones], axis=-1)        # (th, tw, 3)
        xyw = uv1 @ H.T                                  # (th, tw, 3)
        px = xyw[..., 0] / xyw[..., 2]
        py = xyw[..., 1] / xyw[..., 2]
        x0 = jnp.floor(px).astype(jnp.int32)
        y0 = jnp.floor(py).astype(jnp.int32)
        wx = px - x0
        wy = py - y0
        img = x[bi]

        def at(yy, xx):
            inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
            v = img[:, jnp.clip(yy, 0, h - 1), jnp.clip(xx, 0, w - 1)]
            return v * inb.astype(img.dtype)

        out = (
            at(y0, x0) * (1 - wy) * (1 - wx)
            + at(y0, x0 + 1) * (1 - wy) * wx
            + at(y0 + 1, x0) * wy * (1 - wx)
            + at(y0 + 1, x0 + 1) * wy * wx
        )
        return out                        # (C, th, tw)

    out = jax.vmap(warp_one)(rois, bidx)
    return {"Out": [out]}


@register_op("detection_map")
def _detection_map(ctx, ins, attrs):
    """VOC-style mAP (ref detection/detection_map_op.h) over one padded
    batch: DetectRes (N, D, 6) [label score x1 y1 x2 y2] with label=-1
    padding; Label (N, G, 6) [label x1 y1 x2 y2 difficult] (or 5 cols, no
    difficult). Greedy per-image match in global score order per class;
    integral or 11point AP; classes with no gt are skipped."""
    det = ins["DetectRes"][0]
    gt = ins["Label"][0]
    class_num = attrs["class_num"]
    background = attrs.get("background_label", 0)
    ov_thresh = attrs.get("overlap_threshold", 0.5)
    eval_difficult = attrs.get("evaluate_difficult", True)
    ap_version = attrs.get("ap_type", "integral")
    n, d_cap = det.shape[0], det.shape[1]
    g_cap = gt.shape[1]
    gt_label = gt[..., 0].astype(jnp.int32)
    gt_boxes = gt[..., 1:5]
    difficult = (
        gt[..., 5] > 0 if gt.shape[-1] > 5
        else jnp.zeros(gt_label.shape, bool)
    )
    gt_valid = gt_label >= 0
    if not eval_difficult:
        gt_count_mask = gt_valid & ~difficult
    else:
        gt_count_mask = gt_valid

    det_label = det[..., 0].astype(jnp.int32)
    det_score = det[..., 1]
    det_boxes = det[..., 2:6]
    det_valid = det_label >= 0

    # plain (not +1) IoU: detection_map matches SSD-style normalized boxes
    def iou_plain(a, b):
        return _iou_matrix(a[None], b)[0]

    aps = []
    has_gt = []
    for c in range(class_num):
        if c == background:
            continue
        cls_det = det_valid & (det_label == c)          # (N, D)
        flat_score = jnp.where(cls_det, det_score, -jnp.inf).reshape(-1)
        order = jnp.argsort(-flat_score)                # (N*D,)
        img_of = order // d_cap
        slot_of = order % d_cap
        cls_gt = gt_count_mask & (gt_label == c)        # (N, G)
        npos = jnp.sum(cls_gt.astype(jnp.float32))

        def body(carry, od):
            matched = carry                              # (N, G) bool
            i, s = od
            sc = flat_score[i * d_cap + s]
            box = det_boxes[i, s]
            ious = iou_plain(box, gt_boxes[i])
            cand = cls_gt[i] & ~matched[i] & (ious >= ov_thresh)
            ious_m = jnp.where(cand, ious, -1.0)
            best = jnp.argmax(ious_m)
            hit = ious_m[best] >= 0
            valid = jnp.isfinite(sc)
            # difficult gts absorb the det but score as neither tp nor fp
            diff_hit = jnp.any(
                (gt_label[i] == c) & difficult[i] & (ious >= ov_thresh)
            ) & (not eval_difficult)
            tp = valid & hit
            fp = valid & ~hit & ~diff_hit
            matched = matched.at[i, best].set(matched[i, best] | tp)
            return matched, (tp.astype(jnp.float32), fp.astype(jnp.float32))

        init = jnp.zeros((n, g_cap), bool)
        _, (tps, fps) = lax.scan(body, init, (img_of, slot_of))
        cum_tp = jnp.cumsum(tps)
        cum_fp = jnp.cumsum(fps)
        recall = cum_tp / jnp.maximum(npos, 1.0)
        precision = cum_tp / jnp.maximum(cum_tp + cum_fp, 1e-10)
        if ap_version == "11point":
            pts = []
            for t in np.arange(0.0, 1.01, 0.1):
                pts.append(
                    jnp.max(jnp.where(recall >= t, precision, 0.0))
                )
            ap = jnp.mean(jnp.stack(pts))
        else:
            prev_rec = jnp.concatenate([jnp.zeros(1), recall[:-1]])
            ap = jnp.sum((recall - prev_rec) * precision)
        aps.append(jnp.where(npos > 0, ap, 0.0))
        has_gt.append((npos > 0).astype(jnp.float32))

    ap_sum = jnp.sum(jnp.stack(aps))
    n_classes = jnp.maximum(jnp.sum(jnp.stack(has_gt)), 1.0)
    return {"MAP": [ap_sum / n_classes]}
