"""Op lowering library — importing this package registers all lowerings."""
from .registry import (  # noqa: F401
    LOWERINGS,
    LowerContext,
    get_lowering,
    has_lowering,
    register_op,
)

from . import math_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import loss_ops  # noqa: F401
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import metric_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import control_ops  # noqa: F401
from . import collective_ops  # noqa: F401
from . import pallas_attention  # noqa: F401
from . import detection_ops  # noqa: F401
from . import rcnn_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import crf_ops  # noqa: F401


def _register_late_modules():
    """All op modules are imported eagerly above; kept for compatibility."""
