"""Recurrent op lowerings.

Replaces lstm_op, gru_op, cudnn_lstm_op (ref: paddle/fluid/operators/
{lstm_op.cc,gru_op.cc,cudnn_lstm_op.cu.cc}) with lax.scan recurrences.
The per-step matmuls are batched (B, 4D/3D) MXU matmuls; the input
projection x@W is hoisted out of the scan so the loop body is the small
recurrent matmul only. Dense-padded sequences + SeqLen masking (state
freezes past each row's length, matching LoD semantics).
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


def _lens(ins, x, t_axis=1):
    if ins.get("SeqLen"):
        return ins["SeqLen"][0].astype(jnp.int32)
    return jnp.full((x.shape[0],), x.shape[t_axis], jnp.int32)


def _act(name):
    return {
        "sigmoid": jax.nn.sigmoid,
        "tanh": jnp.tanh,
        "relu": jax.nn.relu,
        "identity": lambda v: v,
    }[name]


@register_op("lstm")
def _lstm(ctx, ins, attrs):
    """Single-layer LSTM over (B, T, 4D) pre-projected input
    (ref lstm_op.cc: Input is x@Wx (+bias), Weight is recurrent (D, 4D)).
    Gate order i, c(g), f, o — reference's candidate-before-forget layout."""
    xproj = ins["Input"][0]              # (B, T, 4D)
    w = ins["Weight"][0]                 # (D, 4D)
    b = ins["Bias"][0] if ins.get("Bias") else None
    lens = _lens(ins, xproj)
    d = w.shape[0]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((xproj.shape[0], d), xproj.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((xproj.shape[0], d), xproj.dtype)
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    is_reverse = attrs.get("is_reverse", False)
    use_peepholes = attrs.get("use_peepholes", False)
    if b is not None:
        xproj = xproj + b.reshape((1, 1, -1))[:, :, : 4 * d]

    xs = jnp.moveaxis(xproj, 1, 0)       # (T, B, 4D)
    tsteps = xs.shape[0]
    if is_reverse:
        xs = xs[::-1]

    def step(carry, inp):
        h, c = carry
        xt, t = inp
        gates = xt + h @ w
        i = gate_act(gates[:, :d])
        g = cand_act(gates[:, d : 2 * d])
        f = gate_act(gates[:, 2 * d : 3 * d])
        o = gate_act(gates[:, 3 * d :])
        c_new = f * c + i * g
        h_new = o * cell_act(c_new)
        tt = (tsteps - 1 - t) if is_reverse else t
        live = (tt < lens)[:, None]
        h_new = jnp.where(live, h_new, h)
        c_new = jnp.where(live, c_new, c)
        return (h_new, c_new), (h_new, c_new)

    (h_last, c_last), (hs, cs) = lax.scan(
        step, (h0, c0), (xs, jnp.arange(tsteps))
    )
    if is_reverse:
        hs = hs[::-1]
        cs = cs[::-1]
    return {
        "Hidden": [jnp.moveaxis(hs, 0, 1)],
        "Cell": [jnp.moveaxis(cs, 0, 1)],
        "LastH": [h_last],
        "LastC": [c_last],
    }


@register_op("lstmp")
def _lstmp(ctx, ins, attrs):
    """Projected LSTM (ref lstmp_op.cc / Sak et al. 2014): the recurrent
    state is the projection r = h @ W_proj (P-dim), shrinking the
    recurrent matmul from (D,4D) to (P,4D). Gate order i, c(g), f, o;
    peephole weights live in bias cols 4D:7D (i, f, o)."""
    xproj = ins["Input"][0]              # (B, T, 4D)
    w = ins["Weight"][0]                 # (P, 4D)
    w_proj = ins["ProjWeight"][0]        # (D, P)
    b = ins["Bias"][0] if ins.get("Bias") else None
    lens = _lens(ins, xproj)
    d = w_proj.shape[0]
    p = w.shape[0]
    B = xproj.shape[0]
    r0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, p), xproj.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, d), xproj.dtype)
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cell_act = _act(attrs.get("cell_activation", "tanh"))
    cand_act = _act(attrs.get("candidate_activation", "tanh"))
    proj_act = _act(attrs.get("proj_activation", "tanh"))
    is_reverse = attrs.get("is_reverse", False)
    use_peepholes = attrs.get("use_peepholes", False)
    cell_clip = attrs.get("cell_clip")
    proj_clip = attrs.get("proj_clip")
    peep = None
    if b is not None:
        bias = b.reshape((1, 1, -1))
        xproj = xproj + bias[:, :, : 4 * d]
        if use_peepholes and b.shape[-1] >= 7 * d:
            peep = b.reshape(-1)[4 * d: 7 * d]   # w_ic, w_fc, w_oc

    xs = jnp.moveaxis(xproj, 1, 0)       # (T, B, 4D)
    tsteps = xs.shape[0]
    if is_reverse:
        xs = xs[::-1]

    def step(carry, inp):
        r, c = carry
        xt, t = inp
        gates = xt + r @ w
        gi = gates[:, :d]
        gg = gates[:, d: 2 * d]
        gf = gates[:, 2 * d: 3 * d]
        go = gates[:, 3 * d:]
        if peep is not None:
            gi = gi + c * peep[:d]
            gf = gf + c * peep[d: 2 * d]
        i = gate_act(gi)
        g = cand_act(gg)
        f = gate_act(gf)
        c_new = f * c + i * g
        if cell_clip is not None:
            c_new = jnp.clip(c_new, -cell_clip, cell_clip)
        if peep is not None:
            go = go + c_new * peep[2 * d:]
        o = gate_act(go)
        h_new = o * cell_act(c_new)
        r_new = proj_act(h_new @ w_proj)
        if proj_clip is not None:
            r_new = jnp.clip(r_new, -proj_clip, proj_clip)
        tt = (tsteps - 1 - t) if is_reverse else t
        live = (tt < lens)[:, None]
        r_new = jnp.where(live, r_new, r)
        c_new = jnp.where(live, c_new, c)
        return (r_new, c_new), (r_new, c_new)

    _, (rs, cs) = lax.scan(step, (r0, c0), (xs, jnp.arange(tsteps)))
    if is_reverse:
        rs = rs[::-1]
        cs = cs[::-1]
    return {
        "Projection": [jnp.moveaxis(rs, 0, 1)],
        "Cell": [jnp.moveaxis(cs, 0, 1)],
    }


@register_op("gru")
def _gru(ctx, ins, attrs):
    """GRU over (B, T, 3D) pre-projected input (ref gru_op.cc)."""
    xproj = ins["Input"][0]
    w = ins["Weight"][0]                 # (D, 3D)
    b = ins["Bias"][0] if ins.get("Bias") else None
    lens = _lens(ins, xproj)
    d = w.shape[0]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((xproj.shape[0], d), xproj.dtype)
    gate_act = _act(attrs.get("gate_activation", "sigmoid"))
    cand_act = _act(attrs.get("activation", "tanh"))
    origin_mode = attrs.get("origin_mode", False)
    is_reverse = attrs.get("is_reverse", False)
    if b is not None:
        xproj = xproj + b.reshape((1, 1, -1))
    xs = jnp.moveaxis(xproj, 1, 0)
    tsteps = xs.shape[0]
    if is_reverse:
        xs = xs[::-1]

    def step(h, inp):
        xt, t = inp
        ru = gate_act(xt[:, : 2 * d] + h @ w[:, : 2 * d])
        u = ru[:, :d]
        r = ru[:, d:]
        c = cand_act(xt[:, 2 * d :] + (r * h) @ w[:, 2 * d :])
        h_new = u * h + (1 - u) * c if origin_mode else (1 - u) * h + u * c
        tt = (tsteps - 1 - t) if is_reverse else t
        h_new = jnp.where((tt < lens)[:, None], h_new, h)
        return h_new, h_new

    h_last, hs = lax.scan(step, h0, (xs, jnp.arange(tsteps)))
    if is_reverse:
        hs = hs[::-1]
    return {
        "Hidden": [jnp.moveaxis(hs, 0, 1)],
        "LastH": [h_last],
    }


@register_op("lstm_unit")
def _lstm_unit(ctx, ins, attrs):
    """One LSTM cell step (ref lstm_unit_op.cc): X = [x, h] @ W + b already
    projected to (B, 4D)."""
    gates = ins["X"][0]
    c_prev = ins["C_prev"][0]
    d = c_prev.shape[-1]
    forget_bias = attrs.get("forget_bias", 0.0)
    i = jax.nn.sigmoid(gates[:, :d])
    g = jnp.tanh(gates[:, d : 2 * d])
    f = jax.nn.sigmoid(gates[:, 2 * d : 3 * d] + forget_bias)
    o = jax.nn.sigmoid(gates[:, 3 * d :])
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return {"C": [c], "H": [h]}


@register_op("cudnn_lstm")
def _cudnn_lstm(ctx, ins, attrs):
    """Multi-layer (optionally bidirectional) LSTM — the cuDNN-fused kernel's
    role, rebuilt as stacked scans (XLA fuses the per-step matmuls)."""
    x = ins["Input"][0]  # (B, T, D_in)
    w_ih = ins["WeightIh"]  # list per layer(*dir): (D_in, 4D)
    w_hh = ins["WeightHh"]
    biases = ins.get("Bias", [])
    num_layers = attrs.get("num_layers", 1)
    bidirectional = attrs.get("is_bidirec", False)
    lens = _lens(ins, x)
    ndir = 2 if bidirectional else 1
    out = x
    for layer in range(num_layers):
        dir_outs = []
        for dr in range(ndir):
            idx = layer * ndir + dr
            proj = jnp.einsum("btd,df->btf", out, w_ih[idx])
            if idx < len(biases):
                proj = proj + biases[idx].reshape(1, 1, -1)
            sub = _lstm(
                ctx,
                {
                    "Input": [proj],
                    "Weight": [w_hh[idx]],
                    "SeqLen": [lens],
                },
                {"is_reverse": dr == 1},
            )
            dir_outs.append(sub["Hidden"][0])
        out = (
            jnp.concatenate(dir_outs, axis=-1) if ndir == 2 else dir_outs[0]
        )
    return {"Out": [out], "LastH": [out[:, -1]], "LastC": [out[:, -1]]}


@register_op("beam_search")
def _beam_search(ctx, ins, attrs):
    """One beam expansion (ref beam_search_op.cc), static (B, beam) shapes.
    pre_ids/pre_scores: (B*beam, 1); ids/scores: (B*beam, K) candidates
    (scores already accumulated when is_accumulated)."""
    pre_ids = ins["pre_ids"][0].reshape(-1)
    pre_scores = ins["pre_scores"][0].reshape(-1)
    ids = ins["ids"][0]
    scores = ins["scores"][0]
    beam = attrs["beam_size"]
    end_id = attrs["end_id"]
    bb, k = scores.shape
    b = bb // beam

    finished = pre_ids == end_id
    # finished beams contribute exactly one candidate: (end_id, pre_score)
    neg = jnp.full((k,), -1e30, scores.dtype)
    scores = jnp.where(
        finished[:, None],
        jnp.concatenate(
            [pre_scores[:, None], jnp.broadcast_to(neg[1:], (bb, k - 1))],
            axis=1,
        ),
        scores,
    )
    ids = jnp.where(finished[:, None], end_id, ids)

    flat_scores = scores.reshape(b, beam * k)
    flat_ids = ids.reshape(b, beam * k)
    top_scores, top_pos = lax.top_k(flat_scores, beam)
    sel_ids = jnp.take_along_axis(flat_ids, top_pos, axis=1)
    parent_local = top_pos // k                      # beam index in batch
    parent = parent_local + jnp.arange(b)[:, None] * beam
    return {
        "selected_ids": [sel_ids.reshape(-1, 1).astype(jnp.int64)],
        "selected_scores": [top_scores.reshape(-1, 1)],
        "parent_idx": [parent.reshape(-1).astype(jnp.int64)],
    }


@register_op("beam_search_decode")
def _beam_search_decode(ctx, ins, attrs):
    """Backtrace beams into sequences (ref beam_search_decode_op.cc).
    Ids: (T, B*beam, 1) selected ids per step; Parents: (T, B*beam) global
    parent indices (optional — identity if omitted)."""
    ids = ins["Ids"][0]
    scores = ins["Scores"][0]
    tsteps = ids.shape[0]
    ids2 = ids.reshape(tsteps, -1)       # (T, BB)
    bb = ids2.shape[1]
    if ins.get("Parents"):
        parents = ins["Parents"][0].reshape(tsteps, bb).astype(jnp.int32)
    else:
        parents = jnp.broadcast_to(jnp.arange(bb, dtype=jnp.int32), (tsteps, bb))

    def back(cursor, t):
        # walking t = T-1 .. 0
        tok = ids2[t][cursor]
        cursor_new = parents[t][cursor]
        return cursor_new, tok

    cursor0 = jnp.arange(bb, dtype=jnp.int32)
    _, toks_rev = lax.scan(back, cursor0, jnp.arange(tsteps - 1, -1, -1))
    seqs = toks_rev[::-1].T              # (BB, T)
    final_scores = scores.reshape(tsteps, -1)[-1]
    return {
        "SentenceIds": [seqs.astype(jnp.int64)],
        "SentenceScores": [final_scores],
    }
