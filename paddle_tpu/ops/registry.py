"""Op lowering registry.

TPU-native replacement for the reference's per-device kernel registry
(ref: paddle/fluid/framework/op_registry.h + ~581 kernels under
paddle/fluid/operators/). Each op type maps to ONE lowering function written
in jax/lax — XLA generates the TPU kernels, fuses across ops, and autodiff
comes from jax.vjp over the lowered region instead of hand-written grad
kernels.

Lowering signature::

    def lower(ctx, ins, attrs) -> {output_slot: [jax values]}

``ins`` maps input slot -> list of jax values (missing optional slots are
empty lists). ``ctx`` is a LowerContext carrying PRNG state, train/test mode
and the mesh axis environment for collective ops.
"""
import jax

LOWERINGS = {}


def register_op(name):
    def deco(fn):
        if name in LOWERINGS:
            raise ValueError("op %s registered twice" % name)
        LOWERINGS[name] = fn
        return fn

    return deco


# Known-unsupported op manifest: reference op types with NO TPU lowering
# BY DESIGN, each with the alternative a porting user should reach for.
# Anything not here and not registered is an accidental gap — the error
# text distinguishes the two cases.
KNOWN_UNSUPPORTED = {
    # pserver / async-distributed machinery -> sharding + collectives
    "send": "pserver RPC: gradients ride ICI collectives inside the "
            "jitted step (fleet collective mode)",
    "recv": "pserver RPC: see 'send'",
    "fetch_barrier": "pserver sync barrier: XLA steps are synchronous",
    "send_barrier": "pserver sync barrier: XLA steps are synchronous",
    "listen_and_serv": "pserver main loop: no parameter servers on TPU; "
                       "use fleet collective mode",
    "ref_by_trainer_id": "pserver sharding detail; use mesh sharding",
    "distributed_lookup_table": "vocab-sharded embedding over the mesh "
                                "(parallel/sharding.py) replaces the "
                                "pserver-sharded table",
    "nccl_init": "NCCL context: XLA manages ICI/DCN collectives",
    "gen_nccl_id": "NCCL context: XLA manages ICI/DCN collectives",
    # GPU-runtime specifics
    "cudnn_lstm": "use layers.lstm / the cell API (lax.scan fusion)",
    "fused_embedding_fc_lstm": "compose embedding + fc + lstm; XLA fuses",
    "tensorrt_engine": "TensorRT subgraph: the AOT Predictor compiles "
                       "the whole program with XLA instead",
    "anakin_engine": "Anakin subgraph: see 'tensorrt_engine'",
    # mkldnn / x86 quantization runtime
    "dequantize_mkldnn": "int8 runs via quantized_mul/quantized_conv2d",
    "quantize_mkldnn": "int8 runs via quantized_mul/quantized_conv2d",
    # reader ops: the data path is DataLoader/dataset + the native ring
    "create_py_reader": "use fluid.DataLoader.from_generator",
    "read": "use fluid.DataLoader / dataset trainer path",
    "open_files": "use fluid.dataset (QueueDataset/InMemoryDataset)",
}


def get_lowering(op_type):
    fn = LOWERINGS.get(op_type)
    if fn is None:
        if op_type in KNOWN_UNSUPPORTED:
            raise NotImplementedError(
                "op '%s' is intentionally unsupported on TPU: %s"
                % (op_type, KNOWN_UNSUPPORTED[op_type])
            )
        import difflib

        close = difflib.get_close_matches(
            op_type, list(LOWERINGS), n=3, cutoff=0.6)
        hint = ("; nearest supported: %s" % ", ".join(close)) if close \
            else ""
        raise NotImplementedError(
            "no TPU lowering registered for op '%s' (registered: %d "
            "ops%s). If the reference supports this op, this is a "
            "coverage gap — please report it."
            % (op_type, len(LOWERINGS), hint)
        )
    return fn


def has_lowering(op_type):
    return op_type in LOWERINGS


class LowerContext:
    """Carries trace-time state through a block lowering."""

    def __init__(self, rng=None, is_test=False, mesh_axes=None, program=None,
                 platform=None, mesh=None):
        self._rng = rng
        self._rng_count = 0
        self._op_tag = 0
        # traced per-iteration token set by while/scan lowerings so random
        # draws differ across loop iterations (a bare fold_in inside a traced
        # body would be a compile-time constant reused every iteration)
        self._iter_token = None
        self.is_test = is_test
        self.mesh_axes = mesh_axes or {}  # logical axis name -> mesh axis
        self.mesh = mesh  # the jax Mesh when lowering an SPMD program
        self.program = program
        # target platform of the computation ('cpu'/'tpu'); lowerings that
        # pick platform-specific kernels (pallas) must use this, NOT
        # jax.default_backend() — an Executor(CPUPlace()) on a TPU host
        # compiles for cpu
        self.platform = platform

    def set_op_tag(self, tag):
        """Key PRNG draws by op position so a vjp replay of the same op
        reproduces identical randomness (dropout masks etc.)."""
        self._op_tag = int(tag)
        self._rng_count = 0

    def next_rng(self):
        """Deterministic per-(op, draw) PRNG key derived from the step key."""
        if self._rng is None:
            raise RuntimeError(
                "op requires randomness but no PRNG key was provided"
            )
        self._rng_count += 1
        key = jax.random.fold_in(
            self._rng, (self._op_tag << 10) + self._rng_count
        )
        if self._iter_token is not None:
            key = jax.random.fold_in(key, self._iter_token)
        return key


def single(val):
    """Helper: wrap a single output value for the conventional 'Out' slot."""
    return {"Out": [val]}
