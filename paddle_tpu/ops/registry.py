"""Op lowering registry.

TPU-native replacement for the reference's per-device kernel registry
(ref: paddle/fluid/framework/op_registry.h + ~581 kernels under
paddle/fluid/operators/). Each op type maps to ONE lowering function written
in jax/lax — XLA generates the TPU kernels, fuses across ops, and autodiff
comes from jax.vjp over the lowered region instead of hand-written grad
kernels.

Lowering signature::

    def lower(ctx, ins, attrs) -> {output_slot: [jax values]}

``ins`` maps input slot -> list of jax values (missing optional slots are
empty lists). ``ctx`` is a LowerContext carrying PRNG state, train/test mode
and the mesh axis environment for collective ops.
"""
import jax

LOWERINGS = {}


def register_op(name):
    def deco(fn):
        if name in LOWERINGS:
            raise ValueError("op %s registered twice" % name)
        LOWERINGS[name] = fn
        return fn

    return deco


def get_lowering(op_type):
    fn = LOWERINGS.get(op_type)
    if fn is None:
        raise NotImplementedError(
            "no TPU lowering registered for op '%s' (registered: %d ops)"
            % (op_type, len(LOWERINGS))
        )
    return fn


def has_lowering(op_type):
    return op_type in LOWERINGS


class LowerContext:
    """Carries trace-time state through a block lowering."""

    def __init__(self, rng=None, is_test=False, mesh_axes=None, program=None,
                 platform=None, mesh=None):
        self._rng = rng
        self._rng_count = 0
        self._op_tag = 0
        # traced per-iteration token set by while/scan lowerings so random
        # draws differ across loop iterations (a bare fold_in inside a traced
        # body would be a compile-time constant reused every iteration)
        self._iter_token = None
        self.is_test = is_test
        self.mesh_axes = mesh_axes or {}  # logical axis name -> mesh axis
        self.mesh = mesh  # the jax Mesh when lowering an SPMD program
        self.program = program
        # target platform of the computation ('cpu'/'tpu'); lowerings that
        # pick platform-specific kernels (pallas) must use this, NOT
        # jax.default_backend() — an Executor(CPUPlace()) on a TPU host
        # compiles for cpu
        self.platform = platform

    def set_op_tag(self, tag):
        """Key PRNG draws by op position so a vjp replay of the same op
        reproduces identical randomness (dropout masks etc.)."""
        self._op_tag = int(tag)
        self._rng_count = 0

    def next_rng(self):
        """Deterministic per-(op, draw) PRNG key derived from the step key."""
        if self._rng is None:
            raise RuntimeError(
                "op requires randomness but no PRNG key was provided"
            )
        self._rng_count += 1
        key = jax.random.fold_in(
            self._rng, (self._op_tag << 10) + self._rng_count
        )
        if self._iter_token is not None:
            key = jax.random.fold_in(key, self._iter_token)
        return key


def single(val):
    """Helper: wrap a single output value for the conventional 'Out' slot."""
    return {"Out": [val]}
