"""Loss op lowerings (ref: paddle/fluid/operators/cross_entropy_op.cc,
softmax_with_cross_entropy_op.cc, squared_l2_distance, bce ops, hinge,
huber, margin_rank, etc.)."""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


def _squeeze_label(label):
    if label.ndim >= 2 and label.shape[-1] == 1:
        return label[..., 0]
    return label


@register_op("cross_entropy")
def _cross_entropy(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    soft = attrs.get("soft_label", False)
    ignore = attrs.get("ignore_index", -100)
    eps = 1e-12
    if soft:
        out = -jnp.sum(label * jnp.log(jnp.maximum(x, eps)), axis=-1, keepdims=True)
    else:
        lab = _squeeze_label(label).astype(jnp.int32)
        picked = jnp.take_along_axis(
            x, lab[..., None].clip(0, x.shape[-1] - 1), axis=-1
        )[..., 0]
        out = -jnp.log(jnp.maximum(picked, eps))
        out = jnp.where(lab == ignore, 0.0, out)
        out = out[..., None]
    return {"Y": [out]}


@register_op("cross_entropy2")
def _cross_entropy2(ctx, ins, attrs):
    r = _cross_entropy(ctx, ins, attrs)
    y = r["Y"][0]
    return {"Y": [y], "XShape": [jnp.zeros((0,))], "MatchX": [y]}


@register_op("softmax_with_cross_entropy")
def _softmax_with_ce(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Label"][0]
    soft = attrs.get("soft_label", False)
    ignore = attrs.get("ignore_index", -100)
    axis = attrs.get("axis", -1)
    logp = jax.nn.log_softmax(logits, axis=axis)
    softmax = jnp.exp(logp)
    if soft:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lab = _squeeze_label(label).astype(jnp.int32)
        picked = jnp.take_along_axis(
            logp, lab[..., None].clip(0, logits.shape[axis] - 1), axis=axis
        )[..., 0]
        loss = -picked
        loss = jnp.where(lab == ignore, 0.0, loss)
        loss = loss[..., None]
    return {"Softmax": [softmax], "Loss": [loss]}


@register_op("sigmoid_cross_entropy_with_logits")
def _sigmoid_ce(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    ignore = attrs.get("ignore_index", -100)
    loss = jnp.maximum(x, 0.0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    loss = jnp.where(label == ignore, 0.0, loss)
    if attrs.get("normalize", False):
        cnt = jnp.sum((label != ignore).astype(loss.dtype))
        loss = loss / jnp.maximum(cnt, 1.0)
    return single(loss)


@register_op("square_error_cost")
def _square_error_cost(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    d = x - y
    return single(d * d)


@register_op("squared_l2_distance")
def _squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    d = x - y
    return {
        "Out": [jnp.sum(d * d, axis=-1, keepdims=True)],
        "sub_result": [d],
    }


@register_op("smooth_l1_loss")
def _smooth_l1(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    inw = ins["InsideWeight"][0] if ins.get("InsideWeight") else 1.0
    outw = ins["OutsideWeight"][0] if ins.get("OutsideWeight") else 1.0
    s2 = sigma * sigma
    d = (x - y) * inw
    ad = jnp.abs(d)
    val = jnp.where(ad < 1.0 / s2, 0.5 * d * d * s2, ad - 0.5 / s2)
    out = jnp.sum(val * outw, axis=tuple(range(1, x.ndim)))[:, None]
    return {"Out": [out], "Diff": [d]}


@register_op("huber_loss")
def _huber_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    delta = attrs.get("delta", 1.0)
    r = y - x
    ar = jnp.abs(r)
    out = jnp.where(ar <= delta, 0.5 * r * r, delta * (ar - 0.5 * delta))
    return {"Out": [out], "Residual": [r]}


@register_op("hinge_loss")
def _hinge_loss(ctx, ins, attrs):
    logits, label = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2 * label - 1) * logits)]}


@register_op("margin_rank_loss")
def _margin_rank_loss(ctx, ins, attrs):
    label, left, right = ins["Label"][0], ins["X1"][0], ins["X2"][0]
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (left - right) + margin)
    return {"Out": [out], "Activated": [(out > 0).astype(out.dtype)]}


@register_op("rank_loss")
def _rank_loss(ctx, ins, attrs):
    label, left, right = ins["Label"][0], ins["Left"][0], ins["Right"][0]
    d = left - right
    return single(jnp.log1p(jnp.exp(d)) - label * d)


@register_op("bpr_loss")
def _bpr_loss(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    lab = _squeeze_label(label).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lab[:, None], axis=-1)
    diff = x - pos
    loss = jnp.mean(
        jnp.log1p(jnp.exp(diff)), axis=-1, keepdims=True
    )
    return {"Y": [loss]}


@register_op("log_loss")
def _log_loss(ctx, ins, attrs):
    pred, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    return {
        "Loss": [
            -label * jnp.log(pred + eps)
            - (1 - label) * jnp.log(1 - pred + eps)
        ]
    }


@register_op("kldiv_loss")
def _kldiv_loss(ctx, ins, attrs):
    x, target = ins["X"][0], ins["Target"][0]
    red = attrs.get("reduction", "mean")
    loss = target * (jnp.log(jnp.maximum(target, 1e-12)) - x)
    loss = jnp.where(target > 0, loss, 0.0)
    if red == "mean":
        return {"Loss": [jnp.mean(loss)]}
    if red == "sum":
        return {"Loss": [jnp.sum(loss)]}
    if red == "batchmean":
        return {"Loss": [jnp.sum(loss) / x.shape[0]]}
    return {"Loss": [loss]}


@register_op("dice_loss")
def _dice_loss(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    eps = attrs.get("epsilon", 1e-5)
    label_oh = jax.nn.one_hot(_squeeze_label(label).astype(jnp.int32), x.shape[-1])
    reduce_axes = tuple(range(1, x.ndim))
    inter = jnp.sum(x * label_oh, axis=reduce_axes)
    union = jnp.sum(x, axis=reduce_axes) + jnp.sum(label_oh, axis=reduce_axes)
    return single(jnp.mean(1 - (2 * inter + eps) / (union + eps)))


@register_op("center_loss")
def _center_loss(ctx, ins, attrs):
    x, label, centers = ins["X"][0], ins["Label"][0], ins["Centers"][0]
    alpha = ins["CenterUpdateRate"][0] if ins.get("CenterUpdateRate") else 0.5
    lab = _squeeze_label(label).astype(jnp.int32)
    picked = centers[lab]
    diff = x - picked
    loss = 0.5 * jnp.sum(diff * diff, axis=-1, keepdims=True)
    if attrs.get("need_update", True):
        counts = jnp.zeros((centers.shape[0],)).at[lab].add(1.0)
        upd = jnp.zeros_like(centers).at[lab].add(diff)
        new_centers = centers + alpha * upd / (counts[:, None] + 1.0)
    else:
        new_centers = centers
    return {
        "Loss": [loss],
        "SampleCenterDiff": [diff],
        "CentersOut": [new_centers],
    }


@register_op("npair_loss_helper")
def _npair_dummy(ctx, ins, attrs):  # composed in python layer
    raise NotImplementedError


@register_op("mse_loss")
def _mse_loss(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return single(jnp.mean((x - y) ** 2))


@register_op("sampled_softmax_with_cross_entropy")
def _sampled_softmax_ce(ctx, ins, attrs):
    """Sampled softmax (ref: sample_logits_op.cc). TPU-native: uniform
    candidate sampling with log-q correction, static sample count."""
    logits, label = ins["Logits"][0], ins["Label"][0]
    num_samples = attrs.get("num_samples", 64)
    n_classes = logits.shape[-1]
    lab = label.astype(jnp.int32)  # (batch, num_true)
    samples = jax.random.randint(
        ctx.next_rng(), (num_samples,), 0, n_classes
    )
    # gather true + sampled logits
    true_logits = jnp.take_along_axis(logits, lab, axis=-1)
    sampled_logits = logits[:, samples]
    # remove accidental hits softly: subtract large where sample == label
    hits = (samples[None, None, :] == lab[:, :, None]).any(axis=1)
    sampled_logits = jnp.where(hits, -1e20, sampled_logits)
    all_logits = jnp.concatenate([true_logits, sampled_logits], axis=-1)
    logq = jnp.log(1.0 / n_classes)
    all_logits = all_logits - logq
    tgt = jnp.zeros(all_logits.shape[0], dtype=jnp.int32)
    logp = jax.nn.log_softmax(all_logits, axis=-1)
    loss = -jnp.take_along_axis(logp, tgt[:, None], axis=-1)
    return {"Loss": [loss]}


@register_op("teacher_student_sigmoid_loss")
def _ts_sigmoid_loss(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    soft_max_up = attrs.get("soft_max_up_bound", 15.0)
    z = jnp.clip(x, -soft_max_up, soft_max_up)
    loss = jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(z, 0.0) - z * label
    return {"Y": [loss]}
