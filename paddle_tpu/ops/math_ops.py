"""Math op lowerings: elementwise, matmul, reductions, comparisons.

Replaces the reference's elementwise_*_op.cc/cu, matmul_op, mul_op,
reduce_*_op, scale_op, sum_op, clip_op, compare/logical ops
(ref: paddle/fluid/operators/elementwise/*, matmul_op.cc, reduce_ops/*)
with jax.numpy lowerings — XLA fuses the elementwise chains into the
surrounding matmuls on TPU.
"""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


def _broadcast_y(x, y, axis):
    """Paddle elementwise broadcast: y aligns to x starting at `axis`
    (axis=-1 → align trailing dims)."""
    if x.shape == y.shape:
        return y
    if y.ndim == 0:
        return y
    if axis == -1 or axis is None:
        axis = x.ndim - y.ndim
    # trim trailing size-1 dims of y that paddle allows (e.g. shape (N,1))
    yshape = list(y.shape)
    while yshape and yshape[-1] == 1 and axis + len(yshape) > x.ndim:
        yshape.pop()
    new_shape = [1] * axis + yshape + [1] * (x.ndim - axis - len(yshape))
    return y.reshape(new_shape)


def _ew(fn):
    def lower(ctx, ins, attrs):
        x = ins["X"][0]
        y = ins["Y"][0]
        y = _broadcast_y(x, y, attrs.get("axis", -1))
        return single(fn(x, y))

    return lower


register_op("elementwise_add")(_ew(jnp.add))
register_op("elementwise_sub")(_ew(jnp.subtract))
register_op("elementwise_mul")(_ew(jnp.multiply))
register_op("elementwise_div")(_ew(jnp.divide))
register_op("elementwise_max")(_ew(jnp.maximum))
register_op("elementwise_min")(_ew(jnp.minimum))
register_op("elementwise_pow")(_ew(jnp.power))
register_op("elementwise_mod")(_ew(jnp.mod))
register_op("elementwise_floordiv")(_ew(jnp.floor_divide))


@register_op("mul")
def _mul(ctx, ins, attrs):
    """Flattening matmul (ref: paddle/fluid/operators/mul_op.cc): x is
    flattened to 2-D at x_num_col_dims, y at y_num_col_dims."""
    x, y = ins["X"][0], ins["Y"][0]
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(_prod(xs[:xnc])), int(_prod(xs[xnc:]))))
    y2 = y.reshape((int(_prod(ys[:ync])), int(_prod(ys[ync:]))))
    out = x2 @ y2
    out_shape = xs[:xnc] + ys[ync:]
    return single(out.reshape(out_shape))


def _prod(t):
    r = 1
    for v in t:
        r *= int(v)
    return r


@register_op("matmul")
def _matmul(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    tx = attrs.get("transpose_X", False)
    ty = attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return single(out)


def _reduce(fn, bool_out=False):
    def lower(ctx, ins, attrs):
        x = ins["X"][0]
        dim = attrs.get("dim", None)
        keep_dim = attrs.get("keep_dim", False)
        reduce_all = attrs.get("reduce_all", False) or dim is None
        if reduce_all:
            axis = None
        else:
            axis = tuple(d if d >= 0 else d + x.ndim for d in dim)
        out = fn(x, axis=axis, keepdims=keep_dim)
        return single(out)

    return lower


register_op("reduce_sum")(_reduce(jnp.sum))
register_op("reduce_mean")(_reduce(jnp.mean))
register_op("reduce_max")(_reduce(jnp.max))
register_op("reduce_min")(_reduce(jnp.min))
register_op("reduce_prod")(_reduce(jnp.prod))
register_op("reduce_all")(_reduce(jnp.all))
register_op("reduce_any")(_reduce(jnp.any))


@register_op("mean")
def _mean(ctx, ins, attrs):
    return single(jnp.mean(ins["X"][0]))


@register_op("scale")
def _scale(ctx, ins, attrs):
    x = ins["X"][0]
    scale = attrs.get("scale", 1.0)
    if ins.get("ScaleTensor"):
        scale = ins["ScaleTensor"][0]
    bias = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        out = x * scale + bias
    else:
        out = (x + bias) * scale
    return single(out.astype(x.dtype) if hasattr(out, "astype") else out)


@register_op("sum")
def _sum(ctx, ins, attrs):
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return single(out)


@register_op("clip")
def _clip(ctx, ins, attrs):
    x = ins["X"][0]
    lo = ins["Min"][0] if ins.get("Min") else attrs["min"]
    hi = ins["Max"][0] if ins.get("Max") else attrs["max"]
    return single(jnp.clip(x, lo, hi))


@register_op("clip_by_norm")
def _clip_by_norm(ctx, ins, attrs):
    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(x * x))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return single(x * scale.astype(x.dtype))


def _cmp(fn):
    def lower(ctx, ins, attrs):
        x, y = ins["X"][0], ins["Y"][0]
        y = _broadcast_y(x, y, attrs.get("axis", -1))
        return single(fn(x, y))

    return lower


register_op("equal")(_cmp(jnp.equal))
register_op("not_equal")(_cmp(jnp.not_equal))
register_op("less_than")(_cmp(jnp.less))
register_op("less_equal")(_cmp(jnp.less_equal))
register_op("greater_than")(_cmp(jnp.greater))
register_op("greater_equal")(_cmp(jnp.greater_equal))

register_op("logical_and")(_cmp(jnp.logical_and))
register_op("logical_or")(_cmp(jnp.logical_or))
register_op("logical_xor")(_cmp(jnp.logical_xor))


@register_op("logical_not")
def _logical_not(ctx, ins, attrs):
    return single(jnp.logical_not(ins["X"][0]))


@register_op("isfinite")
def _isfinite(ctx, ins, attrs):
    return single(jnp.all(jnp.isfinite(ins["X"][0])))


@register_op("abs")
def _abs(ctx, ins, attrs):
    return single(jnp.abs(ins["X"][0]))


@register_op("sign")
def _sign(ctx, ins, attrs):
    return single(jnp.sign(ins["X"][0]))


@register_op("pow")
def _pow(ctx, ins, attrs):
    x = ins["X"][0]
    factor = ins["FactorTensor"][0] if ins.get("FactorTensor") else attrs.get("factor", 1.0)
    return single(jnp.power(x, factor))


@register_op("l2_normalize")
def _l2_normalize(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-12)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    return {"Out": [x / jnp.maximum(norm, eps)], "Norm": [norm]}


@register_op("cos_sim")
def _cos_sim(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {"Out": [out], "XNorm": [xn], "YNorm": [yn]}


@register_op("bilinear_tensor_product")
def _bilinear(ctx, ins, attrs):
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    # w: (size, dx, dy)
    out = jnp.einsum("bi,oij,bj->bo", x, w, y)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return single(out)


@register_op("squared_l2_norm")
def _squared_l2_norm(ctx, ins, attrs):
    x = ins["X"][0]
    return single(jnp.sum(x * x).reshape(()))


@register_op("frobenius_norm")
def _frobenius_norm(ctx, ins, attrs):
    x = ins["X"][0]
    return single(jnp.sqrt(jnp.sum(x * x)))


@register_op("kron")
def _kron(ctx, ins, attrs):
    return single(jnp.kron(ins["X"][0], ins["Y"][0]))


@register_op("dot")
def _dot(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return single(jnp.sum(x * y, axis=-1, keepdims=x.ndim > 1))


@register_op("cumsum")
def _cumsum(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    if attrs.get("flatten", False):
        x = x.reshape(-1)
        axis = 0
    rev = attrs.get("reverse", False)
    if rev:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        out = jnp.pad(out, pad)[
            tuple(
                slice(0, -1) if i == (axis % x.ndim) else slice(None)
                for i in range(x.ndim)
            )
        ]
    if rev:
        out = jnp.flip(out, axis)
    return single(out)
