"""Metric op lowerings (ref: paddle/fluid/operators/metrics/accuracy_op.cc,
auc_op.cc, precision_recall_op, mean_iou_op)."""
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


@register_op("accuracy")
def _accuracy(ctx, ins, attrs):
    pred_idx = ins["Indices"][0]  # (N, k) top-k indices
    label = ins["Label"][0]
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label[:, 0]
    hit = (pred_idx == label[:, None].astype(pred_idx.dtype)).any(axis=-1)
    correct = jnp.sum(hit.astype(jnp.float32))
    total = jnp.array(float(pred_idx.shape[0]), jnp.float32)
    return {
        "Accuracy": [correct / total],
        "Correct": [correct.astype(jnp.int32)],
        "Total": [total.astype(jnp.int32)],
    }


@register_op("auc")
def _auc(ctx, ins, attrs):
    """Streaming AUC with fixed histogram bins; stat tensors are persistable
    state threaded through the step like batch-norm running stats."""
    predict = ins["Predict"][0]
    label = ins["Label"][0]
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]
    num_thresholds = attrs.get("num_thresholds", 4095)
    if label.ndim == 2:
        label = label[:, 0]
    pos_prob = predict[:, -1] if predict.ndim == 2 else predict
    bins = jnp.clip(
        (pos_prob * num_thresholds).astype(jnp.int32), 0, num_thresholds
    )
    is_pos = (label > 0).astype(stat_pos.dtype)
    stat_pos = stat_pos.at[bins].add(is_pos)
    stat_neg = stat_neg.at[bins].add(1 - is_pos)
    # AUC by trapezoid over thresholds (descending)
    tp = jnp.cumsum(stat_pos[::-1])
    fp = jnp.cumsum(stat_neg[::-1])
    tot_pos = tp[-1]
    tot_neg = fp[-1]
    tpr = tp / jnp.maximum(tot_pos, 1.0)
    fpr = fp / jnp.maximum(tot_neg, 1.0)
    auc = jnp.trapezoid(tpr, fpr)
    return {
        "AUC": [auc],
        "StatPosOut": [stat_pos],
        "StatNegOut": [stat_neg],
    }


@register_op("mean_iou")
def _mean_iou(ctx, ins, attrs):
    pred, label = ins["Predictions"][0], ins["Labels"][0]
    n = attrs["num_classes"]
    pred = pred.reshape(-1).astype(jnp.int32)
    label = label.reshape(-1).astype(jnp.int32)
    idx = label * n + pred
    cm = jnp.zeros((n * n,), jnp.float32).at[idx].add(1.0).reshape(n, n)
    inter = jnp.diag(cm)
    union = cm.sum(0) + cm.sum(1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
    return {
        "OutMeanIou": [miou],
        "OutWrong": [(cm.sum(1) - inter).astype(jnp.int32)],
        "OutCorrect": [inter.astype(jnp.int32)],
    }


@register_op("precision_recall")
def _precision_recall(ctx, ins, attrs):
    # simplified single-batch precision/recall per class
    idx = ins["Indices"][0][:, 0]
    label = ins["Labels"][0]
    if label.ndim == 2:
        label = label[:, 0]
    n = attrs["class_number"]
    idx = idx.astype(jnp.int32)
    label = label.astype(jnp.int32)
    tp = jnp.zeros((n,)).at[label].add((idx == label).astype(jnp.float32))
    pred_cnt = jnp.zeros((n,)).at[idx].add(1.0)
    lab_cnt = jnp.zeros((n,)).at[label].add(1.0)
    precision = tp / jnp.maximum(pred_cnt, 1.0)
    recall = tp / jnp.maximum(lab_cnt, 1.0)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-6)
    metrics = jnp.stack(
        [precision.mean(), recall.mean(), f1.mean(),
         precision.mean(), recall.mean(), f1.mean()]
    )
    return {
        "BatchMetrics": [metrics],
        "AccumMetrics": [metrics],
        "AccumStatesInfo": [jnp.stack([tp, pred_cnt - tp, lab_cnt - tp], axis=1)],
    }
