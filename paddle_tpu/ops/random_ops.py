"""Random op lowerings (ref: uniform_random_op.cc, gaussian_random_op.cc,
truncated_gaussian_random_op.cc, randint_op, sampling_id_op, randperm_op,
random_crop_op). All draw from the LowerContext's threaded PRNG key — the
fork-in counter makes every trace site deterministic given the step key."""
import jax
import jax.numpy as jnp

from ..fluid import core
from .registry import register_op, single


def _dtype(attrs, default="float32"):
    return core.np_dtype(core.convert_dtype(attrs.get("dtype", default)))


def _shape(ins, attrs):
    if ins.get("ShapeTensor"):
        return tuple(int(v) for v in ins["ShapeTensor"])
    return tuple(int(s) for s in attrs["shape"])


@register_op("uniform_random")
def _uniform_random(ctx, ins, attrs):
    shape = _shape(ins, attrs)
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    return single(
        jax.random.uniform(
            ctx.next_rng(), shape, minval=lo, maxval=hi
        ).astype(_dtype(attrs))
    )


@register_op("uniform_random_batch_size_like")
def _uniform_random_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[
        attrs.get("input_dim_idx", 0)
    ]
    return single(
        jax.random.uniform(
            ctx.next_rng(),
            tuple(shape),
            minval=attrs.get("min", -1.0),
            maxval=attrs.get("max", 1.0),
        ).astype(_dtype(attrs))
    )


@register_op("gaussian_random")
def _gaussian_random(ctx, ins, attrs):
    shape = _shape(ins, attrs)
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    return single(
        (mean + std * jax.random.normal(ctx.next_rng(), shape)).astype(
            _dtype(attrs)
        )
    )


@register_op("gaussian_random_batch_size_like")
def _gaussian_random_bsl(ctx, ins, attrs):
    ref = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = ref.shape[
        attrs.get("input_dim_idx", 0)
    ]
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    return single(
        (mean + std * jax.random.normal(ctx.next_rng(), tuple(shape))).astype(
            _dtype(attrs)
        )
    )


@register_op("truncated_gaussian_random")
def _truncated_gaussian_random(ctx, ins, attrs):
    shape = _shape(ins, attrs)
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    out = jax.random.truncated_normal(ctx.next_rng(), -2.0, 2.0, shape)
    return single((mean + std * out).astype(_dtype(attrs)))


@register_op("randint")
def _randint(ctx, ins, attrs):
    shape = _shape(ins, attrs)
    return single(
        jax.random.randint(
            ctx.next_rng(), shape, attrs.get("low", 0), attrs.get("high", 100)
        ).astype(_dtype(attrs, "int64"))
    )


@register_op("randperm")
def _randperm(ctx, ins, attrs):
    n = attrs["n"]
    return single(
        jax.random.permutation(ctx.next_rng(), n).astype(_dtype(attrs, "int64"))
    )


@register_op("sampling_id")
def _sampling_id(ctx, ins, attrs):
    x = ins["X"][0]
    idx = jax.random.categorical(ctx.next_rng(), jnp.log(jnp.maximum(x, 1e-20)))
    return single(idx.astype(jnp.int64))


@register_op("multinomial")
def _multinomial(ctx, ins, attrs):
    x = ins["X"][0]
    num = attrs.get("num_samples", 1)
    logits = jnp.log(jnp.maximum(x, 1e-20))
    out = jax.random.categorical(ctx.next_rng(), logits, shape=(num,) + x.shape[:-1])
    return single(jnp.moveaxis(out, 0, -1).astype(jnp.int64))


@register_op("shuffle_channel")
def _shuffle_channel(ctx, ins, attrs):
    x = ins["X"][0]
    g = attrs.get("group", 1)
    n, c, h, w = x.shape
    return single(
        x.reshape(n, g, c // g, h, w).swapaxes(1, 2).reshape(n, c, h, w)
    )


@register_op("random_crop")
def _random_crop(ctx, ins, attrs):
    x = ins["X"][0]
    shape = attrs["shape"]
    # crop trailing len(shape) dims to `shape` at a random offset
    k = len(shape)
    key = ctx.next_rng()
    starts = []
    for i, s in enumerate(shape):
        dim = x.shape[x.ndim - k + i]
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, dim - s + 1))
    idx = [slice(None)] * (x.ndim - k)
    start_full = [0] * (x.ndim - k) + [int(0)] * k
    # dynamic_slice for traced starts
    from jax import lax

    starts_full = [jnp.array(0)] * (x.ndim - k) + starts
    sizes = list(x.shape[: x.ndim - k]) + list(shape)
    return single(lax.dynamic_slice(x, starts_full, sizes))
