"""Fused multi-head attention as Pallas TPU kernels (FlashAttention-2 style).

TPU-native replacement for the reference's attention pattern (ref:
python/paddle/fluid/nets.py:scaled_dot_product_attention and the
matmul+softmax+dropout+matmul chain in its transformer models). Instead of
materialising the (B, H, T, T) score tensor in HBM, the forward kernel keeps
one (block_q, block_k) tile in VMEM at a time with online-softmax
accumulation; backward recomputes tiles flash-style from the saved
log-sum-exp, so attention memory is O(T·D) instead of O(T²).

Design notes (TPU):
- grid = (B*H, Tq/block_q); K and V for one (batch, head) ride whole in VMEM
  (T·D ≤ ~1M elements covers T=16k at D=64 — beyond that, sequence
  parallelism via parallel/ring_attention.py splits T across chips anyway).
- QK^T and P·V hit the MXU via dot_general with f32 accumulation; the
  running max/sum rescale is VPU work fused around them.
- dropout uses a counter-based hash PRNG written in plain integer jnp ops
  (murmur3 finalizer over absolute tile coordinates), NOT pltpu.prng_*:
  the same bits are regenerated bit-exactly in the backward kernels and in
  interpret mode on CPU, which makes the dropout path unit-testable off-TPU.
- backward = two kernels (FlashAttention-2 split): dq over q-tiles, dk/dv
  over k-tiles, both re-forming P from the saved lse.

`flash_attention` carries a custom_vjp; `reference_attention` is the plain
jax oracle used by tests and by the CPU lowering fallback.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "reference_attention"]

_NEG_INF = -1e30

# Mosaic tiles f32 as (8, 128) sublanes x lanes. Row-vector arrays (lse,
# delta, key-padding mask, dkpm) can't ride a (1, block) block shape on a
# real TPU, so — like jax's official flash kernel (MIN_BLOCK_SIZE) — they
# carry a broadcast trailing lane axis (.., 128) or a sublane axis (8, ..)
# and the kernels slice lane/sublane 0.
_LANES = 128
_SUBLANES = 8


# ---------------------------------------------------------------------------
# counter-based dropout bits (identical in fwd/bwd kernels and on CPU)
# ---------------------------------------------------------------------------
def fold_bh_seed(seed, bh):
    """Mix the (batch·head) grid index into the dropout seed so every head
    draws independent bits (also used by tests to rebuild the mask)."""
    return seed + bh.astype(jnp.int32) * jnp.int32(1000003)


def _tile_random_bits(seed, qi, kj, bq, bk):
    """uint32 bits for the (qi, kj) score tile; pure jnp integer ops."""
    rows = lax.broadcasted_iota(jnp.uint32, (bq, bk), 0)
    cols = lax.broadcasted_iota(jnp.uint32, (bq, bk), 1)
    h = (
        seed.astype(jnp.uint32)
        ^ (qi.astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
        ^ (kj.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
    )
    h = h + rows * jnp.uint32(0x27D4EB2F) + cols * jnp.uint32(0x165667B1)
    # murmur3 fmix32
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _keep_mask(seed, qi, kj, bq, bk, dropout_p):
    bits = _tile_random_bits(seed, qi, kj, bq, bk)
    threshold = jnp.uint32(min(int(dropout_p * 4294967296.0), 4294967295))
    return bits >= threshold


def _causal_mask_tile(qi, kj, bq, bk):
    rows = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = kj * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return rows >= cols


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------
def _fwd_kernel(seed_ref, kpm_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                sm_scale, causal, dropout_p, block_k, nk):
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    d = q_ref.shape[2]
    q = q_ref[0]                                     # (bq, D)
    seed = fold_bh_seed(seed_ref[0, 0], pl.program_id(0))

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]         # (bk, D)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                          # (bq, bk)
        if kpm_ref is not None:
            # kpm block is (1, SUBLANES, tk) broadcast rows; take row 0
            s = s + kpm_ref[0, 0:1, pl.ds(j * block_k, block_k)]
        if causal:
            s = jnp.where(_causal_mask_tile(qi, j, bq, block_k), s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                                # (bq, bk)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        if dropout_p > 0.0:
            keep = _keep_mask(seed, qi, j, bq, block_k, dropout_p)
            p_use = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_p))
        else:
            p_use = p
        v = v_ref[0, pl.ds(j * block_k, block_k), :]          # (bk, D)
        pv = lax.dot_general(
            p_use.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = acc * alpha + pv
        return m_new, l, acc

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    if causal:
        # only tiles that intersect the lower triangle of this q block
        upper = ((qi + 1) * bq + block_k - 1) // block_k
        upper = jnp.minimum(upper, nk)
    else:
        upper = nk
    m, l, acc = lax.fori_loop(0, upper, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    # fully-masked query rows (m never rose above the mask floor) output 0 —
    # the framework-defined semantic for degenerate causal/padding combos
    dead = m <= _NEG_INF * 0.5
    o_ref[0] = jnp.where(dead, 0.0, acc / l_safe).astype(o_ref.dtype)
    lse_val = jnp.where(dead, _NEG_INF, m + jnp.log(l_safe))   # (bq, 1)
    lse_ref[0] = jnp.broadcast_to(lse_val, (bq, _LANES))


# ---------------------------------------------------------------------------
# backward kernels (FlashAttention-2 split)
# ---------------------------------------------------------------------------
def _p_tile(q, k, kpm_row, lse, qi, j, bq, bk, sm_scale, causal):
    """Recompute P = exp(S - lse) for tile (qi, j); f32. kpm_row is a
    (1, bk) row (sliced from the sublane-broadcast layout)."""
    s = lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    if kpm_row is not None:
        s = s + kpm_row
    if causal:
        s = jnp.where(_causal_mask_tile(qi, j, bq, bk), s, _NEG_INF)
    # dead rows carry lse = _NEG_INF (see fwd); their P must be 0, not e^0
    return jnp.where(lse <= _NEG_INF * 0.5, 0.0, jnp.exp(s - lse))


def _dq_kernel(seed_ref, kpm_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, *, sm_scale, causal, dropout_p, block_k,
               nk):
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    q = q_ref[0]
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, 0:1]                # (bq, 1) from lane-broadcast
    delta = delta_ref[0][:, 0:1]
    seed = fold_bh_seed(seed_ref[0, 0], pl.program_id(0))

    def body(j, dq_acc):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        kpm_row = (
            kpm_ref[0, 0:1, pl.ds(j * block_k, block_k)]
            if kpm_ref is not None else None
        )
        p = _p_tile(q, k, kpm_row, lse, qi, j, bq, block_k, sm_scale, causal)
        dpd = lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                     # (bq, bk)
        if dropout_p > 0.0:
            keep = _keep_mask(seed, qi, j, bq, block_k, dropout_p)
            dp = jnp.where(keep, dpd, 0.0) * (1.0 / (1.0 - dropout_p))
        else:
            dp = dpd
        ds = p * (dp - delta)                                 # (bq, bk)
        dq_acc = dq_acc + lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        return dq_acc

    if causal:
        upper = ((qi + 1) * bq + block_k - 1) // block_k
        upper = jnp.minimum(upper, nk)
    else:
        upper = nk
    dq = lax.fori_loop(
        0, upper, body, jnp.zeros((bq, q_ref.shape[2]), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkdv_kernel(seed_ref, kpm_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                 delta_ref, dk_ref, dv_ref, dkpm_ref=None, *, sm_scale,
                 causal, dropout_p, block_q, nq):
    kj = pl.program_id(1)
    bk = k_ref.shape[1]
    k = k_ref[0]
    v = v_ref[0]
    kpm_row = kpm_ref[0, 0:1, :] if kpm_ref is not None else None
    seed = fold_bh_seed(seed_ref[0, 0], pl.program_id(0))

    def body(i, carry):
        dk_acc, dv_acc, dkpm_acc = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :][:, 0:1]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :][:, 0:1]
        p = _p_tile(q, k, kpm_row, lse, i, kj, block_q, bk, sm_scale, causal)
        if dropout_p > 0.0:
            keep = _keep_mask(seed, i, kj, block_q, bk, dropout_p)
            inv = 1.0 / (1.0 - dropout_p)
            pd = jnp.where(keep, p, 0.0) * inv
        else:
            pd = p
        dv_acc = dv_acc + lax.dot_general(
            pd.astype(do_ref.dtype), do.astype(do_ref.dtype),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                     # (bk, D)
        dpd = lax.dot_general(
            do.astype(v.dtype), v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                     # (bq, bk)
        if dropout_p > 0.0:
            dp = jnp.where(keep, dpd, 0.0) * inv
        else:
            dp = dpd
        ds = p * (dp - delta)                                 # (bq, bk)
        dk_acc = dk_acc + lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        # kpm enters every S row additively -> dkpm[k] = sum over q of dS
        dkpm_acc = dkpm_acc + jnp.sum(ds, axis=0, keepdims=True)
        return dk_acc, dv_acc, dkpm_acc

    if causal:
        lower = (kj * bk) // block_q
    else:
        lower = 0
    d = k_ref.shape[2]
    dk, dv, dkpm = lax.fori_loop(
        lower, nq, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32),
         jnp.zeros((1, bk), jnp.float32)),
    )
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)
    if dkpm_ref is not None:
        dkpm_ref[0] = jnp.broadcast_to(dkpm, (_SUBLANES, bk))


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------
def _specs(bh, t, d, block, have_kpm, heads):
    """Common in_specs for (seed, kpm?, q, k, v) with q blocked over axis 1."""
    seed_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    q_spec = pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0))
    kv_spec = pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0))
    kpm_spec = (
        pl.BlockSpec((1, _SUBLANES, t), lambda b, i: (b // heads, 0, 0))
        if have_kpm else None
    )
    return seed_spec, kpm_spec, q_spec, kv_spec


def _kpm3(kpm):
    """(B, T) additive mask -> sublane-broadcast (B, SUBLANES, T)."""
    return jnp.broadcast_to(
        kpm[:, None, :], (kpm.shape[0], _SUBLANES, kpm.shape[1])
    )


def _fwd_call(q, k, v, kpm, seed, sm_scale, causal, dropout_p, block_q,
              block_k, heads, interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq = tq // block_q
    nk = tk // block_k
    seed_spec, kpm_spec, q_spec, kv_spec = _specs(
        bh, tk, d, block_q, kpm is not None, heads
    )
    kernel = functools.partial(
        _fwd_kernel if kpm is not None else _fwd_kernel_nokpm,
        sm_scale=sm_scale, causal=causal, dropout_p=dropout_p,
        block_k=block_k, nk=nk,
    )
    in_specs = [seed_spec]
    args = [seed]
    if kpm is not None:
        in_specs.append(kpm_spec)
        args.append(_kpm3(kpm))
    in_specs += [q_spec, kv_spec, kv_spec]
    args += [q, k, v]
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, nq),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LANES), lambda b, i: (b, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, _LANES), jnp.float32),
        ),
        interpret=interpret,
    )(*args)
    return out, lse


def _fwd_kernel_nokpm(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, **kw):
    _fwd_kernel(seed_ref, None, q_ref, k_ref, v_ref, o_ref, lse_ref, **kw)


def _dq_kernel_nokpm(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dq_ref, **kw):
    _dq_kernel(seed_ref, None, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, **kw)


def _dkdv_kernel_nokpm(seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                       delta_ref, dk_ref, dv_ref, **kw):
    _dkdv_kernel(seed_ref, None, q_ref, k_ref, v_ref, do_ref, lse_ref,
                 delta_ref, dk_ref, dv_ref, None, **kw)


def _bwd_call(q, k, v, kpm, seed, do, lse, delta, sm_scale, causal,
              dropout_p, block_q, block_k, heads, interpret):
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq = tq // block_q
    nk = tk // block_k
    seed_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    kpm_spec = pl.BlockSpec(
        (1, _SUBLANES, tk), lambda b, i: (b // heads, 0, 0)
    )
    full_q = pl.BlockSpec((1, tq, d), lambda b, i: (b, 0, 0))
    full_k = pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0))
    row_q = pl.BlockSpec((1, tq, _LANES), lambda b, i: (b, 0, 0))

    kpm3 = _kpm3(kpm) if kpm is not None else None
    # dq: grid over q tiles
    qb = pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0))
    lse_b = pl.BlockSpec((1, block_q, _LANES), lambda b, i: (b, i, 0))
    in_specs = [seed_spec]
    args = [seed]
    if kpm is not None:
        in_specs.append(kpm_spec)
        args.append(kpm3)
    in_specs += [qb, full_k, full_k, qb, lse_b, lse_b]
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel if kpm is not None else _dq_kernel_nokpm,
            sm_scale=sm_scale, causal=causal, dropout_p=dropout_p,
            block_k=block_k, nk=nk,
        ),
        grid=(bh, nq),
        in_specs=in_specs,
        out_specs=qb,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(*(args + [q, k, v, do, lse, delta]))

    # dk/dv: grid over k tiles
    kb = pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0))
    kpm_b = pl.BlockSpec(
        (1, _SUBLANES, block_k), lambda b, i: (b // heads, 0, i)
    )
    in_specs = [seed_spec]
    args = [seed]
    if kpm is not None:
        in_specs.append(kpm_b)
        args.append(kpm3)
    in_specs += [full_q, kb, kb, full_q, row_q, row_q]
    out_specs = [kb, kb]
    out_shape = [
        jax.ShapeDtypeStruct(k.shape, k.dtype),
        jax.ShapeDtypeStruct(v.shape, v.dtype),
    ]
    if kpm is not None:
        # per-(b·h) partial dkpm rows (sublane-broadcast); summed over
        # heads by the caller
        out_specs.append(
            pl.BlockSpec((1, _SUBLANES, block_k), lambda b, i: (b, 0, i))
        )
        out_shape.append(
            jax.ShapeDtypeStruct((bh, _SUBLANES, tk), jnp.float32)
        )
    outs = pl.pallas_call(
        functools.partial(
            _dkdv_kernel if kpm is not None else _dkdv_kernel_nokpm,
            sm_scale=sm_scale, causal=causal, dropout_p=dropout_p,
            block_q=block_q, nq=nq,
        ),
        grid=(bh, nk),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        out_shape=tuple(out_shape),
        interpret=interpret,
    )(*(args + [q, k, v, do, lse, delta]))
    if kpm is not None:
        dk, dv, dkpm_bh = outs
    else:
        (dk, dv), dkpm_bh = outs, None
    return dq, dk, dv, dkpm_bh


# ---------------------------------------------------------------------------
# public entry: (B, H, T, D) with custom vjp
# ---------------------------------------------------------------------------
def _pick_block(t, want):
    b = min(want, t)
    while t % b:
        b -= 1
    return b


def _pad_len(t, block):
    """Padded length: pad up to a block multiple rather than shrinking the
    tile (a divisor-poor T like a prime would otherwise degrade to 1-wide
    tiles and O(T²) grid steps)."""
    return (t + block - 1) // block * block


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10)
)
def _flash(q, k, v, kpm, seed, sm_scale, causal, dropout_p, block_q,
           block_k, interpret):
    return _flash_fwd(
        q, k, v, kpm, seed, sm_scale, causal, dropout_p, block_q, block_k,
        interpret,
    )[0]


def _flash_fwd(q, k, v, kpm, seed, sm_scale, causal, dropout_p, block_q,
               block_k, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * h, tk, d)
    vf = v.reshape(b * h, tk, d)
    out, lse = _fwd_call(
        qf, kf, vf, kpm, seed, sm_scale, causal, dropout_p, block_q,
        block_k, h, interpret,
    )
    return out.reshape(b, h, tq, d), (q, k, v, kpm, seed, out, lse)


def _flash_bwd(sm_scale, causal, dropout_p, block_q, block_k, interpret,
               res, g):
    q, k, v, kpm, seed, out_f, lse = res
    b, h, tq, d = q.shape
    tk = k.shape[2]
    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * h, tk, d)
    vf = v.reshape(b * h, tk, d)
    gf = g.reshape(b * h, tq, d)
    delta = jnp.sum(
        gf.astype(jnp.float32) * out_f.astype(jnp.float32), axis=-1
    )
    # same lane-broadcast layout as lse (see _LANES note at the top)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (_LANES,))
    dq, dk, dv, dkpm_bh = _bwd_call(
        qf, kf, vf, kpm, seed, gf, lse, delta, sm_scale, causal,
        dropout_p, block_q, block_k, h, interpret,
    )
    dkpm = None
    if kpm is not None:
        dkpm = (
            dkpm_bh[:, 0, :].reshape(b, h, tk).sum(axis=1).astype(kpm.dtype)
        )
    # the int32 seed's formal tangent type is float0 — returning an int32
    # zero relies on lenient custom_vjp checking and can break on upgrades
    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    return (
        dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape),
        dkpm, dseed,
    )


_flash.defvjp(
    lambda *a: _flash_fwd(*a),
    _flash_bwd,
)


def flash_attention(q, k, v, key_padding_mask=None, seed=None, sm_scale=None,
                    causal=False, dropout_p=0.0, block_q=128, block_k=128,
                    interpret=False):
    """Flash multi-head attention.

    q: (B, H, Tq, D); k, v: (B, H, Tk, D).
    key_padding_mask: optional additive f32 (B, Tk) (-inf/-1e30 at pads).
    seed: int32 scalar array driving dropout bits (ignored if dropout_p=0).
    Returns (B, H, Tq, D) in q.dtype.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    tq, tk = q.shape[2], k.shape[2]
    # prefer exact tiling; for divisor-poor lengths pad up to the block
    # (padding + masking beats shrinking tiles to degenerate widths)
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    pad_q = pad_k = 0
    if bq < min(block_q, tq) // 2:
        bq = min(block_q, tq)
        pad_q = _pad_len(tq, bq) - tq
    if bk < min(block_k, tk) // 2:
        bk = min(block_k, tk)
        pad_k = _pad_len(tk, bk) - tk
    if seed is None:
        if dropout_p > 0.0:
            raise ValueError(
                "flash_attention(dropout_p>0) needs an explicit integer "
                "seed (vary it per step, or dropout masks repeat)"
            )
        seed = jnp.zeros((1, 1), jnp.int32)
    else:
        seed = jnp.asarray(seed, jnp.int32).reshape((1, 1))
    kpm = None
    if key_padding_mask is not None:
        kpm = jnp.asarray(key_padding_mask, jnp.float32)
    if pad_k:
        # padded keys are masked out; pad/slice sit OUTSIDE the custom_vjp
        # so autodiff zeroes the pad cotangents for free
        if kpm is None:
            kpm = jnp.zeros((q.shape[0], tk), jnp.float32)
        kpm = jnp.pad(kpm, ((0, 0), (0, pad_k)), constant_values=_NEG_INF)
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    out = _flash(
        q, k, v, kpm, seed, float(sm_scale), bool(causal), float(dropout_p),
        bq, bk, interpret,
    )
    if pad_q:
        out = out[:, :, :tq, :]
    return out


def reference_attention(q, k, v, key_padding_mask=None, sm_scale=None,
                        causal=False, dropout_p=0.0, dropout_rng=None):
    """Plain-jax oracle with the same semantics (dropout via jax.random —
    bits differ from the pallas kernel; use dropout_p=0 for exact compares).
    Used as the CPU lowering fallback of the fused_multihead_attention op."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * sm_scale
    if key_padding_mask is not None:
        s = s + key_padding_mask[:, None, None, :]
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        rows = lax.broadcasted_iota(jnp.int32, (tq, tk), 0)
        cols = lax.broadcasted_iota(jnp.int32, (tq, tk), 1)
        s = jnp.where((rows >= cols)[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # match the kernel semantic: fully-masked rows produce 0, not uniform
    dead = jnp.max(s, axis=-1, keepdims=True) <= _NEG_INF * 0.5
    p = jnp.where(dead, 0.0, p)
    if dropout_p > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p, 0.0) / (1.0 - dropout_p)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# op registration (layer API: fluid.layers.fused_multihead_attention)
# ---------------------------------------------------------------------------
from .registry import register_op, single  # noqa: E402


@register_op("fused_multihead_attention")
def _fused_mha_lowering(ctx, ins, attrs):
    """Q/K/V: (B, H, T, D). Pallas flash kernels on a single TPU device;
    the plain-jax path otherwise (CPU, and under a device mesh — a
    pallas_call is an opaque custom call the SPMD partitioner can't split,
    while the einsum formulation partitions over (dp, tp) for free)."""
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    kpm = ins["KeyPaddingMask"][0] if ins.get("KeyPaddingMask") else None
    causal = bool(attrs.get("causal", False))
    p = float(attrs.get("dropout_prob", 0.0))
    if attrs.get("is_test", False) or ctx.is_test:
        p = 0.0
    key = ctx.next_rng() if p > 0.0 else None
    import os
    platform = ctx.platform or jax.default_backend()
    # measured on v5e (BERT-base): XLA's own attention fusion beats the
    # pallas flash kernel at EVERY length tried — T=128: 104k vs 80k,
    # T=512: 91k vs 69k, T=1024: 68k vs 51k, T=2048: 42k vs 34k tok/s —
    # so auto-engage is off by default; set PADDLE_TPU_FLASH_MIN_SEQ to a
    # threshold to opt in (the kernel is correctness-tested and remains
    # the basis for the masked/dropout ring-attention block path).
    _flash_env = os.environ.get("PADDLE_TPU_FLASH_MIN_SEQ")
    min_t = int(_flash_env) if _flash_env else (1 << 30)
    use_pallas = (
        platform == "tpu"
        and not ctx.mesh_axes
        and not os.environ.get("PADDLE_TPU_DISABLE_PALLAS")
        and q.shape[2] >= min_t
    )
    if use_pallas:
        seed = None
        if key is not None:
            seed = jax.random.randint(
                key, (), 0, 2 ** 31 - 1, dtype=jnp.int32
            )
        out = flash_attention(
            q, k, v, kpm, seed=seed, causal=causal, dropout_p=p
        )
        return single(out)

    # Under an 'sp'-sharded mesh, exact RING attention keeps every chip
    # holding only its sequence shard of K/V (rotated over ICI via
    # ppermute) instead of the all-gather the einsum formulation would
    # cost — the long-context path. Falls back to einsum for kpm/dropout
    # or non-divisible shapes.
    sp = ctx.mesh_axes.get("sp")
    mesh = getattr(ctx, "mesh", None)
    if (
        sp is not None
        and mesh is not None
        and kpm is None
        and p == 0.0
        and q.shape == k.shape
        and q.shape[2] % mesh.shape[sp] == 0
    ):
        from functools import partial

        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from ..parallel.ring_attention import ring_attention

        dp = ctx.mesh_axes.get("dp")
        tp = ctx.mesh_axes.get("tp")
        dp = dp if dp in mesh.shape else None
        tp = tp if tp in mesh.shape else None
        if dp is not None and q.shape[0] % mesh.shape[dp] != 0:
            dp = None
        if tp is not None and q.shape[1] % mesh.shape[tp] != 0:
            tp = None
        # q/k/v are (B, H, T, D); ring_attention wants (B, T, H, D)
        spec = P(dp, tp, sp, None)

        def body(q_, k_, v_):
            qt = jnp.moveaxis(q_, 1, 2)
            kt = jnp.moveaxis(k_, 1, 2)
            vt = jnp.moveaxis(v_, 1, 2)
            ot = ring_attention(qt, kt, vt, axis_name=sp, causal=causal)
            return jnp.moveaxis(ot, 2, 1)

        out = shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec),
            out_specs=spec, check_rep=False,
        )(q, k, v)
        return single(out)

    out = reference_attention(
        q, k, v, kpm, causal=causal, dropout_p=p, dropout_rng=key
    )
    return single(out)
