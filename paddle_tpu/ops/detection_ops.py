"""Detection op lowerings (ref: paddle/fluid/operators/detection/ and
roi_pool_op.cc / roi_align_op.cc). ROIs use the dense (N_roi, 4) box format
with a companion batch-index vector (LoD → static shapes)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


def _roi_batch_idx(ins, n_rois):
    if ins.get("RoisBatchIdx"):
        return ins["RoisBatchIdx"][0].astype(jnp.int32)
    return jnp.zeros((n_rois,), jnp.int32)


@register_op("roi_pool")
def _roi_pool(ctx, ins, attrs):
    x = ins["X"][0]            # (N, C, H, W)
    rois = ins["ROIs"][0]      # (R, 4) [x1, y1, x2, y2]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape
    r = rois.shape[0]
    bidx = _roi_batch_idx(ins, r)

    def pool_one(roi, bi):
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.maximum(jnp.round(roi[2] * scale).astype(jnp.int32), x1 + 1)
        y2 = jnp.maximum(jnp.round(roi[3] * scale).astype(jnp.int32), y1 + 1)
        # sample a dense grid and max-reduce per bin (static shapes)
        gh, gw = ph * 4, pw * 4
        ys = y1 + (jnp.arange(gh) + 0.5) * (y2 - y1) / gh
        xs = x1 + (jnp.arange(gw) + 0.5) * (x2 - x1) / gw
        yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
        patch = x[bi][:, yi][:, :, xi]  # (C, gh, gw)
        patch = patch.reshape(c, ph, 4, pw, 4)
        return jnp.max(patch, axis=(2, 4))

    out = jax.vmap(pool_one)(rois, bidx)
    return {"Out": [out], "Argmax": [jnp.zeros(out.shape, jnp.int32)]}


@register_op("roi_align")
def _roi_align(ctx, ins, attrs):
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    ratio = attrs.get("sampling_ratio", -1)
    if ratio <= 0:
        ratio = 2
    n, c, h, w = x.shape
    r = rois.shape[0]
    bidx = _roi_batch_idx(ins, r)

    def bilinear(img, y, x_):
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x_).astype(jnp.int32)
        y1, x1 = y0 + 1, x0 + 1
        wy = y - y0
        wx = x_ - x0
        y0c = jnp.clip(y0, 0, h - 1)
        y1c = jnp.clip(y1, 0, h - 1)
        x0c = jnp.clip(x0, 0, w - 1)
        x1c = jnp.clip(x1, 0, w - 1)
        v = (
            img[:, y0c, x0c] * (1 - wy) * (1 - wx)
            + img[:, y0c, x1c] * (1 - wy) * wx
            + img[:, y1c, x0c] * wy * (1 - wx)
            + img[:, y1c, x1c] * wy * wx
        )
        return v

    def align_one(roi, bi):
        x1 = roi[0] * scale
        y1 = roi[1] * scale
        x2 = roi[2] * scale
        y2 = roi[3] * scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = x[bi]
        acc = jnp.zeros((c, ph, pw))
        for iy in range(ratio):
            for ix in range(ratio):
                yy = y1 + (jnp.arange(ph)[:, None] + (iy + 0.5) / ratio) * bin_h
                xx = x1 + (jnp.arange(pw)[None, :] + (ix + 0.5) / ratio) * bin_w
                yyb = jnp.broadcast_to(yy, (ph, pw))
                xxb = jnp.broadcast_to(xx, (ph, pw))
                acc = acc + bilinear(img, yyb, xxb)
        return acc / (ratio * ratio)

    out = jax.vmap(align_one)(rois, bidx)
    return single(out)


@register_op("box_coder")
def _box_coder(ctx, ins, attrs):
    """Encode/decode boxes vs priors (ref: detection/box_coder_op.cc)."""
    prior = ins["PriorBox"][0]         # (M, 4)
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    target = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    off = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph_ = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph_ * 0.5
    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph_[None, :]
        dw = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        dh = jnp.log(jnp.abs(th[:, None] / ph_[None, :]))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if pvar is not None:
            out = out / pvar[None, :, :]
        return {"OutputBox": [out]}
    # decode: target (N, M, 4)
    t = target
    if pvar is not None:
        t = t * pvar[None, :, :]
    dcx = t[..., 0] * pw + pcx
    dcy = t[..., 1] * ph_ + pcy
    dw = jnp.exp(t[..., 2]) * pw
    dh = jnp.exp(t[..., 3]) * ph_
    out = jnp.stack(
        [dcx - dw * 0.5, dcy - dh * 0.5,
         dcx + dw * 0.5 - off, dcy + dh * 0.5 - off],
        axis=-1,
    )
    return {"OutputBox": [out]}


@register_op("iou_similarity")
def _iou_similarity(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]  # (N,4), (M,4)
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    return single(inter / jnp.maximum(union, 1e-10))


@register_op("prior_box")
def _prior_box(ctx, ins, attrs):
    """SSD prior boxes (ref: detection/prior_box_op.cc)."""
    feat = ins["Input"][0]   # (N, C, H, W)
    image = ins["Image"][0]  # (N, C, IH, IW)
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ratios = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", True)
    clip = attrs.get("clip", False)
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w or iw / w
    sh = step_h or ih / h
    ars = []
    for r in ratios:
        ars.append(r)
        if flip and abs(r - 1.0) > 1e-6:
            ars.append(1.0 / r)
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        sizes = []
        for ar in ars:
            sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if ms_i < len(max_sizes):
            sizes.append(
                (np.sqrt(ms * max_sizes[ms_i]),) * 2
            )
        for bw, bh in sizes:
            cx = (jnp.arange(w) + offset) * sw
            cy = (jnp.arange(h) + offset) * sh
            cxg, cyg = jnp.meshgrid(cx, cy)
            box = jnp.stack(
                [
                    (cxg - bw / 2) / iw,
                    (cyg - bh / 2) / ih,
                    (cxg + bw / 2) / iw,
                    (cyg + bh / 2) / ih,
                ],
                axis=-1,
            )
            boxes.append(box)
    out = jnp.stack(boxes, axis=2)  # (H, W, num_priors, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances), out.shape
    )
    return {"Boxes": [out], "Variances": [var]}


@register_op("yolo_box")
def _yolo_box(ctx, ins, attrs):
    """YOLOv3 box decoding (ref: detection/yolo_box_op.cc)."""
    x = ins["X"][0]            # (N, A*(5+C), H, W)
    img_size = ins["ImgSize"][0]  # (N, 2) [h, w]
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w)[None, None, None, :]
    grid_y = jnp.arange(h)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    input_h = downsample * h
    input_w = downsample * w
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    bw = jnp.exp(x[:, :, 2]) * aw / input_w
    bh = jnp.exp(x[:, :, 3]) * ah / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    mask = (conf > conf_thresh).astype(x.dtype)
    imgh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imgw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    boxes = jnp.stack(
        [
            (bx - bw / 2) * imgw,
            (by - bh / 2) * imgh,
            (bx + bw / 2) * imgw,
            (by + bh / 2) * imgh,
        ],
        axis=-1,
    )
    boxes = boxes * mask[..., None]
    boxes = boxes.reshape(n, na * h * w, 4)
    scores = (probs * mask[:, :, None]).transpose(0, 1, 3, 4, 2).reshape(
        n, na * h * w, class_num
    )
    return {"Boxes": [boxes], "Scores": [scores]}
