"""Detection op lowerings (ref: paddle/fluid/operators/detection/ and
roi_pool_op.cc / roi_align_op.cc). ROIs use the dense (N_roi, 4) box format
with a companion batch-index vector (LoD → static shapes)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


def _roi_batch_idx(ins, n_rois):
    if ins.get("RoisBatchIdx"):
        return ins["RoisBatchIdx"][0].astype(jnp.int32)
    return jnp.zeros((n_rois,), jnp.int32)


@register_op("roi_pool")
def _roi_pool(ctx, ins, attrs):
    x = ins["X"][0]            # (N, C, H, W)
    rois = ins["ROIs"][0]      # (R, 4) [x1, y1, x2, y2]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape
    r = rois.shape[0]
    bidx = _roi_batch_idx(ins, r)

    def pool_one(roi, bi):
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.maximum(jnp.round(roi[2] * scale).astype(jnp.int32), x1 + 1)
        y2 = jnp.maximum(jnp.round(roi[3] * scale).astype(jnp.int32), y1 + 1)
        # sample a dense grid and max-reduce per bin (static shapes)
        gh, gw = ph * 4, pw * 4
        ys = y1 + (jnp.arange(gh) + 0.5) * (y2 - y1) / gh
        xs = x1 + (jnp.arange(gw) + 0.5) * (x2 - x1) / gw
        yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
        patch = x[bi][:, yi][:, :, xi]  # (C, gh, gw)
        patch = patch.reshape(c, ph, 4, pw, 4)
        return jnp.max(patch, axis=(2, 4))

    out = jax.vmap(pool_one)(rois, bidx)
    return {"Out": [out], "Argmax": [jnp.zeros(out.shape, jnp.int32)]}


@register_op("roi_align")
def _roi_align(ctx, ins, attrs):
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    ratio = attrs.get("sampling_ratio", -1)
    if ratio <= 0:
        ratio = 2
    n, c, h, w = x.shape
    r = rois.shape[0]
    bidx = _roi_batch_idx(ins, r)

    def bilinear(img, y, x_):
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x_).astype(jnp.int32)
        y1, x1 = y0 + 1, x0 + 1
        wy = y - y0
        wx = x_ - x0
        y0c = jnp.clip(y0, 0, h - 1)
        y1c = jnp.clip(y1, 0, h - 1)
        x0c = jnp.clip(x0, 0, w - 1)
        x1c = jnp.clip(x1, 0, w - 1)
        v = (
            img[:, y0c, x0c] * (1 - wy) * (1 - wx)
            + img[:, y0c, x1c] * (1 - wy) * wx
            + img[:, y1c, x0c] * wy * (1 - wx)
            + img[:, y1c, x1c] * wy * wx
        )
        return v

    def align_one(roi, bi):
        x1 = roi[0] * scale
        y1 = roi[1] * scale
        x2 = roi[2] * scale
        y2 = roi[3] * scale
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = x[bi]
        acc = jnp.zeros((c, ph, pw))
        for iy in range(ratio):
            for ix in range(ratio):
                yy = y1 + (jnp.arange(ph)[:, None] + (iy + 0.5) / ratio) * bin_h
                xx = x1 + (jnp.arange(pw)[None, :] + (ix + 0.5) / ratio) * bin_w
                yyb = jnp.broadcast_to(yy, (ph, pw))
                xxb = jnp.broadcast_to(xx, (ph, pw))
                acc = acc + bilinear(img, yyb, xxb)
        return acc / (ratio * ratio)

    out = jax.vmap(align_one)(rois, bidx)
    return single(out)


@register_op("psroi_pool")
def _psroi_pool(ctx, ins, attrs):
    """Position-sensitive ROI pooling (ref detection/psroi_pool_op.h,
    R-FCN): input channels = output_channels * ph * pw; bin (i, j) of
    output channel c average-pools input channel c*ph*pw + i*pw + j."""
    x = ins["X"][0]            # (N, C*ph*pw, H, W)
    rois = ins["ROIs"][0]      # (R, 4)
    bidx = _roi_batch_idx(ins, rois.shape[0])
    out_c = attrs["output_channels"]
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n, c_in, h, w = x.shape

    def pool_one(roi, bi):
        x1 = roi[0] * scale
        y1 = roi[1] * scale
        x2 = roi[2] * scale
        y2 = roi[3] * scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw
        # dense 4-sample grid per bin, averaged (static shapes)
        gh, gw = ph * 4, pw * 4
        ys = y1 + (jnp.arange(gh) + 0.5) * rh / gh
        xs = x1 + (jnp.arange(gw) + 0.5) * rw / gw
        yi = jnp.clip(ys.astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(xs.astype(jnp.int32), 0, w - 1)
        patch = x[bi][:, yi][:, :, xi]          # (C_in, gh, gw)
        patch = patch.reshape(c_in, ph, 4, pw, 4).mean(axis=(2, 4))
        # position-sensitive channel select: out[c, i, j] =
        # patch[c*ph*pw + i*pw + j, i, j]
        ci = jnp.arange(out_c)[:, None, None]
        ii = jnp.arange(ph)[None, :, None]
        jj = jnp.arange(pw)[None, None, :]
        chan = ci * ph * pw + ii * pw + jj
        return patch[chan, ii, jj]

    out = jax.vmap(pool_one)(rois, bidx)
    return {"Out": [out]}


@register_op("prroi_pool")
def _prroi_pool(ctx, ins, attrs):
    """Precise ROI pooling (ref detection/prroi_pool_op.h): exact
    integral of the bilinearly-interpolated feature over each bin —
    approximated here with a dense 8x8 sample average per bin (the
    closed-form integral's quadrature; differentiable in the rois)."""
    x = ins["X"][0]
    rois = ins["ROIs"][0]
    bidx = _roi_batch_idx(ins, rois.shape[0])
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    scale = attrs.get("spatial_scale", 1.0)
    n, c, h, w = x.shape
    ss = 8  # sub-samples per bin side

    def pool_one(roi, bi):
        x1 = roi[0] * scale
        y1 = roi[1] * scale
        x2 = roi[2] * scale
        y2 = roi[3] * scale
        rh = jnp.maximum(y2 - y1, 1e-6)
        rw = jnp.maximum(x2 - x1, 1e-6)
        gh, gw = ph * ss, pw * ss
        ys = y1 + (jnp.arange(gh) + 0.5) * rh / gh - 0.5
        xs = x1 + (jnp.arange(gw) + 0.5) * rw / gw - 0.5
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        wy = (ys - y0)[:, None]
        wx = (xs - x0)[None, :]
        img = x[bi]

        def at(yy, xx):
            return img[:, jnp.clip(yy, 0, h - 1)][:, :, jnp.clip(xx, 0, w - 1)]

        val = (
            at(y0, x0) * (1 - wy) * (1 - wx)
            + at(y0, x0 + 1) * (1 - wy) * wx
            + at(y0 + 1, x0) * wy * (1 - wx)
            + at(y0 + 1, x0 + 1) * wy * wx
        )                                        # (C, gh, gw)
        return val.reshape(c, ph, ss, pw, ss).mean(axis=(2, 4))

    out = jax.vmap(pool_one)(rois, bidx)
    return {"Out": [out]}


@register_op("deformable_conv")
def _deformable_conv(ctx, ins, attrs):
    """Deformable convolution v1/v2 (ref operators/deformable_conv_op.h):
    per output position and kernel tap, sample the input bilinearly at
    (p + p_k + delta p_k), optionally modulated (v2); then contract with
    the weights — the gather/matmul form XLA tiles well, instead of the
    reference's im2col loop."""
    x = ins["Input"][0]        # (N, C, H, W)
    offset = ins["Offset"][0]  # (N, 2*dg*kh*kw, Ho, Wo), (dy, dx) pairs
    mask = ins["Mask"][0] if ins.get("Mask") else None  # (N, dg*kh*kw, ...)
    w = ins["Filter"][0]       # (Co, C/g, kh, kw)
    strides = _pair2(attrs.get("strides", [1, 1]))
    pads = _pair2(attrs.get("paddings", [1, 1]))
    dils = _pair2(attrs.get("dilations", [1, 1]))
    groups = attrs.get("groups", 1) or 1
    dg = attrs.get("deformable_groups", 1) or 1
    n, c, h, wd = x.shape
    co, cg, kh, kw = w.shape
    ho = (h + 2 * pads[0] - dils[0] * (kh - 1) - 1) // strides[0] + 1
    wo = (wd + 2 * pads[1] - dils[1] * (kw - 1) - 1) // strides[1] + 1

    def per_image(xi, off, mk):
        # base sampling grid per tap
        oy = jnp.arange(ho) * strides[0] - pads[0]
        ox = jnp.arange(wo) * strides[1] - pads[1]
        ky = jnp.arange(kh) * dils[0]
        kx = jnp.arange(kw) * dils[1]
        base_y = oy[:, None, None, None] + ky[None, None, :, None]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]
        off = off.reshape(dg, kh, kw, 2, ho, wo)
        dy = jnp.moveaxis(off[:, :, :, 0], (1, 2), (3, 4))  # (dg,ho,wo,kh,kw)
        dx = jnp.moveaxis(off[:, :, :, 1], (1, 2), (3, 4))
        py = base_y[None] + dy                      # (dg, ho, wo, kh, kw)
        px = base_x[None] + dx
        y0 = jnp.floor(py).astype(jnp.int32)
        x0 = jnp.floor(px).astype(jnp.int32)
        wy = py - y0
        wx = px - x0
        cpd = c // dg                                # channels per dgroup
        xg = xi.reshape(dg, cpd, h, wd)

        def at(yy, xx):
            inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < wd)
            yy = jnp.clip(yy, 0, h - 1)
            xx = jnp.clip(xx, 0, wd - 1)
            # gather per deformable group
            v = jax.vmap(lambda img, y_, x_: img[:, y_, x_])(xg, yy, xx)
            return v * inb[:, None].astype(xi.dtype)  # (dg,cpd,ho,wo,kh,kw)

        val = (
            at(y0, x0) * ((1 - wy) * (1 - wx))[:, None]
            + at(y0, x0 + 1) * ((1 - wy) * wx)[:, None]
            + at(y0 + 1, x0) * (wy * (1 - wx))[:, None]
            + at(y0 + 1, x0 + 1) * (wy * wx)[:, None]
        )
        if mk is not None:
            m = jnp.moveaxis(
                mk.reshape(dg, kh, kw, ho, wo), (1, 2), (3, 4)
            )
            val = val * m[:, None]
        val = val.reshape(c, ho, wo, kh, kw)
        # grouped contraction with the filter
        vg = val.reshape(groups, c // groups, ho, wo, kh, kw)
        wg = w.reshape(groups, co // groups, cg, kh, kw)
        out = jnp.einsum("gchwkl,gockl->gohw", vg, wg)
        return out.reshape(co, ho, wo)

    if mask is None:
        out = jax.vmap(lambda a, b: per_image(a, b, None))(x, offset)
    else:
        out = jax.vmap(per_image)(x, offset, mask)
    return {"Output": [out]}


def _pair2(v, k=2):
    return list(v) if isinstance(v, (list, tuple)) else [v] * k


@register_op("box_coder")
def _box_coder(ctx, ins, attrs):
    """Encode/decode boxes vs priors (ref: detection/box_coder_op.cc)."""
    prior = ins["PriorBox"][0]         # (M, 4)
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    target = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    off = 0.0 if norm else 1.0
    pw = prior[:, 2] - prior[:, 0] + off
    ph_ = prior[:, 3] - prior[:, 1] + off
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph_ * 0.5
    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + off
        th = target[:, 3] - target[:, 1] + off
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        dx = (tcx[:, None] - pcx[None, :]) / pw[None, :]
        dy = (tcy[:, None] - pcy[None, :]) / ph_[None, :]
        dw = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        dh = jnp.log(jnp.abs(th[:, None] / ph_[None, :]))
        out = jnp.stack([dx, dy, dw, dh], axis=-1)
        if pvar is not None:
            out = out / pvar[None, :, :]
        return {"OutputBox": [out]}
    # decode: target (N, M, 4)
    t = target
    if pvar is not None:
        t = t * pvar[None, :, :]
    dcx = t[..., 0] * pw + pcx
    dcy = t[..., 1] * ph_ + pcy
    dw = jnp.exp(t[..., 2]) * pw
    dh = jnp.exp(t[..., 3]) * ph_
    out = jnp.stack(
        [dcx - dw * 0.5, dcy - dh * 0.5,
         dcx + dw * 0.5 - off, dcy + dh * 0.5 - off],
        axis=-1,
    )
    return {"OutputBox": [out]}


@register_op("iou_similarity")
def _iou_similarity(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]  # (N,4), (M,4)
    area_x = (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    area_y = (y[:, 2] - y[:, 0]) * (y[:, 3] - y[:, 1])
    lt = jnp.maximum(x[:, None, :2], y[None, :, :2])
    rb = jnp.minimum(x[:, None, 2:], y[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_x[:, None] + area_y[None, :] - inter
    return single(inter / jnp.maximum(union, 1e-10))


@register_op("prior_box")
def _prior_box(ctx, ins, attrs):
    """SSD prior boxes (ref: detection/prior_box_op.cc)."""
    feat = ins["Input"][0]   # (N, C, H, W)
    image = ins["Image"][0]  # (N, C, IH, IW)
    min_sizes = attrs["min_sizes"]
    max_sizes = attrs.get("max_sizes", [])
    ratios = attrs.get("aspect_ratios", [1.0])
    flip = attrs.get("flip", True)
    clip = attrs.get("clip", False)
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w or iw / w
    sh = step_h or ih / h
    ars = []
    for r in ratios:
        ars.append(r)
        if flip and abs(r - 1.0) > 1e-6:
            ars.append(1.0 / r)
    boxes = []
    for ms_i, ms in enumerate(min_sizes):
        sizes = []
        for ar in ars:
            sizes.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if ms_i < len(max_sizes):
            sizes.append(
                (np.sqrt(ms * max_sizes[ms_i]),) * 2
            )
        for bw, bh in sizes:
            cx = (jnp.arange(w) + offset) * sw
            cy = (jnp.arange(h) + offset) * sh
            cxg, cyg = jnp.meshgrid(cx, cy)
            box = jnp.stack(
                [
                    (cxg - bw / 2) / iw,
                    (cyg - bh / 2) / ih,
                    (cxg + bw / 2) / iw,
                    (cyg + bh / 2) / ih,
                ],
                axis=-1,
            )
            boxes.append(box)
    out = jnp.stack(boxes, axis=2)  # (H, W, num_priors, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances), out.shape
    )
    return {"Boxes": [out], "Variances": [var]}


@register_op("density_prior_box")
def _density_prior_box(ctx, ins, attrs):
    """Density prior boxes (ref: detection/density_prior_box_op.h): for each
    (density d, fixed_size s) the s-sized boxes are replicated on a d x d
    sub-grid inside every cell, shifted by step/d — NOT d*d copies at the
    cell center."""
    feat = ins["Input"][0]   # (N, C, H, W)
    image = ins["Image"][0]  # (N, C, IH, IW)
    densities = attrs.get("densities", [1])
    fixed_sizes = attrs.get("fixed_sizes", [1.0])
    fixed_ratios = attrs.get("fixed_ratios", [1.0])
    clip = attrs.get("clip", False)
    step_w = attrs.get("step_w", 0.0)
    step_h = attrs.get("step_h", 0.0)
    offset = attrs.get("offset", 0.5)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    h, w = feat.shape[2], feat.shape[3]
    ih, iw = image.shape[2], image.shape[3]
    sw = step_w or iw / w
    sh = step_h or ih / h
    # cell centers
    cx0 = jnp.arange(w) * sw + offset * sw
    cy0 = jnp.arange(h) * sh + offset * sh
    cxg, cyg = jnp.meshgrid(cx0, cy0)  # (H, W)
    if len(densities) != len(fixed_sizes):
        raise ValueError(
            "density_prior_box: densities (%d) and fixed_sizes (%d) must "
            "align one-to-one" % (len(densities), len(fixed_sizes))
        )
    boxes = []
    for d, s in zip(densities, fixed_sizes):
        shift_w = sw / d
        shift_h = sh / d
        for r in fixed_ratios:
            bw = s * np.sqrt(r)
            bh = s / np.sqrt(r)
            for dy in range(d):
                for dx in range(d):
                    cx = cxg - sw / 2 + shift_w / 2 + dx * shift_w
                    cy = cyg - sh / 2 + shift_h / 2 + dy * shift_h
                    boxes.append(jnp.stack(
                        [(cx - bw / 2) / iw, (cy - bh / 2) / ih,
                         (cx + bw / 2) / iw, (cy + bh / 2) / ih],
                        axis=-1,
                    ))
    out = jnp.stack(boxes, axis=2)  # (H, W, num_priors, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances), out.shape)
    if attrs.get("flatten_to_2d", False):
        out = out.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return {"Boxes": [out], "Variances": [var]}


@register_op("yolo_box")
def _yolo_box(ctx, ins, attrs):
    """YOLOv3 box decoding (ref: detection/yolo_box_op.cc)."""
    x = ins["X"][0]            # (N, A*(5+C), H, W)
    img_size = ins["ImgSize"][0]  # (N, 2) [h, w]
    anchors = attrs["anchors"]
    class_num = attrs["class_num"]
    conf_thresh = attrs.get("conf_thresh", 0.01)
    downsample = attrs.get("downsample_ratio", 32)
    n, _, h, w = x.shape
    na = len(anchors) // 2
    x = x.reshape(n, na, 5 + class_num, h, w)
    grid_x = jnp.arange(w)[None, None, None, :]
    grid_y = jnp.arange(h)[None, None, :, None]
    aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
    ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
    input_h = downsample * h
    input_w = downsample * w
    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    bw = jnp.exp(x[:, :, 2]) * aw / input_w
    bh = jnp.exp(x[:, :, 3]) * ah / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    mask = (conf > conf_thresh).astype(x.dtype)
    imgh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imgw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    boxes = jnp.stack(
        [
            (bx - bw / 2) * imgw,
            (by - bh / 2) * imgh,
            (bx + bw / 2) * imgw,
            (by + bh / 2) * imgh,
        ],
        axis=-1,
    )
    boxes = boxes * mask[..., None]
    boxes = boxes.reshape(n, na * h * w, 4)
    scores = (probs * mask[:, :, None]).transpose(0, 1, 3, 4, 2).reshape(
        n, na * h * w, class_num
    )
    return {"Boxes": [boxes], "Scores": [scores]}


@register_op("box_clip")
def _box_clip(ctx, ins, attrs):
    boxes = ins["Input"][0]
    im_info = ins["ImInfo"][0]  # (N, 3) h, w, scale  or (3,)
    if im_info.ndim == 1:
        im_info = im_info[None]
    # im_info = [resized_h, resized_w, scale]; clip in ORIGINAL image coords
    scale = jnp.maximum(im_info[:, 2], 1e-6) if im_info.shape[1] > 2 else 1.0
    h = jnp.round(im_info[:, 0] / scale) - 1
    w = jnp.round(im_info[:, 1] / scale) - 1
    squeeze = boxes.ndim == 2
    if squeeze:
        boxes = boxes[None]
    x1 = jnp.clip(boxes[..., 0], 0, w[:, None])
    y1 = jnp.clip(boxes[..., 1], 0, h[:, None])
    x2 = jnp.clip(boxes[..., 2], 0, w[:, None])
    y2 = jnp.clip(boxes[..., 3], 0, h[:, None])
    out = jnp.stack([x1, y1, x2, y2], axis=-1)
    if squeeze:  # keep the caller-declared rank
        out = out[0]
    return {"Output": [out]}


def _iou_matrix(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter, 1e-10)


def _nms_adaptive(flat_scores, flat_box, flat_cls, n_cls, keep_top_k,
                  nms_thresh, nms_eta, dtype):
    """Adaptive NMS (nms_eta < 1), matching the reference NMSFast order:
    every candidate is tested at ITS turn in score order against the kept
    set, with the per-class threshold decayed once per kept box (while the
    threshold stays > 0.5). O(C·M·keep_top_k) — the eta<1 path only."""
    # Pre-truncate to the top keep_top_k candidates PER CLASS: the scan is
    # sequential, and C*M steps (80 classes x 1000 boxes = 80k) would crawl
    # on TPU. Suppression is within-class and at most keep_top_k boxes are
    # kept in total, so capping each class at keep_top_k (rather than a
    # global score cut that one dense class could monopolise) bounds the
    # scan at keep_top_k*C steps while keeping every realistic keeper.
    total = flat_scores.shape[0]
    cap = min(total, max(int(keep_top_k), 1) * max(int(n_cls), 1))
    sorted_idx = jnp.argsort(-flat_scores)
    cls_sorted = flat_cls[sorted_idx]
    onehot = jax.nn.one_hot(cls_sorted, n_cls, dtype=jnp.int32)
    rank_in_class = (
        jnp.take_along_axis(
            jnp.cumsum(onehot, axis=0), cls_sorted[:, None], axis=1
        )[:, 0]
        - 1
    )
    eligible = rank_in_class < keep_top_k
    keyed = jnp.where(eligible, flat_scores[sorted_idx], -jnp.inf)
    _, sel = lax.top_k(keyed, cap)
    order = sorted_idx[sel]
    k = keep_top_k
    slots = jnp.arange(k)

    def body(carry, idx):
        kept_box, kept_cls, kept_score, n_kept, thresh = carry
        sc = flat_scores[idx]
        box = flat_box[idx]
        cls = flat_cls[idx]
        ious = _iou_matrix(box[None], kept_box)[0]          # (K,)
        overlapped = jnp.any(
            (ious > thresh[cls]) & (kept_cls == cls) & (slots < n_kept)
        )
        keep = (sc > 0) & ~overlapped & (n_kept < k)
        write = keep & (slots == n_kept)
        kept_box = jnp.where(write[:, None], box[None], kept_box)
        kept_cls = jnp.where(write, cls, kept_cls)
        kept_score = jnp.where(write, sc, kept_score)
        thresh = jnp.where(
            keep & (jnp.arange(n_cls) == cls) & (thresh > 0.5),
            thresh * nms_eta, thresh,
        )
        n_kept = n_kept + keep.astype(jnp.int32)
        return (kept_box, kept_cls, kept_score, n_kept, thresh), None

    init = (
        jnp.zeros((k, 4), dtype),
        jnp.full((k,), -1, jnp.int32),
        jnp.zeros((k,), dtype),
        jnp.asarray(0, jnp.int32),
        jnp.full((n_cls,), nms_thresh, dtype),
    )
    (kept_box, kept_cls, kept_score, _, _), _ = lax.scan(
        body, init, order
    )
    return jnp.concatenate(
        [
            jnp.where(kept_score > 0, kept_cls, -1)[:, None].astype(dtype),
            kept_score[:, None],
            kept_box,
        ],
        axis=1,
    )


@register_op("multiclass_nms")
def _multiclass_nms(ctx, ins, attrs):
    """Static-shape greedy NMS (ref detection/multiclass_nms_op.cc): output
    is exactly (N, keep_top_k, 6) rows [label, score, x1, y1, x2, y2] padded
    with label=-1 — fixed shapes instead of the reference's LoD output."""
    bboxes = ins["BBoxes"][0]   # (N, M, 4)
    scores = ins["Scores"][0]   # (N, C, M)
    score_thresh = attrs["score_threshold"]
    nms_thresh = attrs.get("nms_threshold", 0.3)
    nms_top_k = attrs.get("nms_top_k", -1)
    nms_eta = attrs.get("nms_eta", 1.0)
    keep_top_k = attrs["keep_top_k"]
    background = attrs.get("background_label", 0)
    n, c, m = scores.shape

    def per_image(boxes, sc):
        # pre-NMS per-class top-k (ref keeps only the nms_top_k highest
        # scoring candidates of each class before suppression)
        if nms_top_k is not None and 0 < nms_top_k < m:
            kth = lax.top_k(sc, nms_top_k)[0][:, -1:]
            sc = jnp.where(sc >= kth, sc, -1.0)
        # candidates: all (class, box) pairs except background
        cls_ids = jnp.arange(c)[:, None].repeat(m, 1)   # (C, M)
        flat_scores = sc.reshape(-1)
        flat_cls = cls_ids.reshape(-1)
        flat_box = jnp.tile(boxes, (c, 1))
        valid = (flat_scores > score_thresh) & (flat_cls != background)
        flat_scores = jnp.where(valid, flat_scores, -1.0)

        if nms_eta < 1.0:
            rows = _nms_adaptive(
                flat_scores, flat_box, flat_cls, c, keep_top_k, nms_thresh,
                nms_eta, boxes.dtype,
            )
            # adaptive path doesn't track source indices
            return rows, jnp.full((keep_top_k,), -1, jnp.int32)

        def body(carry, _):
            cur_scores = carry
            best = jnp.argmax(cur_scores)
            best_score = cur_scores[best]
            best_box = flat_box[best]
            best_cls = flat_cls[best]
            # suppress same-class overlapping candidates + self
            ious = _iou_matrix(best_box[None], flat_box)[0]
            suppress = ((ious > nms_thresh) & (flat_cls == best_cls)) | (
                jnp.arange(flat_scores.shape[0]) == best
            )
            cur_scores = jnp.where(suppress, -1.0, cur_scores)
            row = jnp.concatenate(
                [
                    jnp.where(best_score > 0, best_cls, -1)[None].astype(
                        boxes.dtype
                    ),
                    best_score[None],
                    best_box,
                ]
            )
            # kept box's index into the input boxes (ref
            # multiclass_nms2's Index output); -1 on padding rows
            idx = jnp.where(best_score > 0, best % m, -1).astype(
                jnp.int32)
            return cur_scores, (row, idx)

        _, (rows, idxs) = lax.scan(body, flat_scores, None,
                                   length=keep_top_k)
        return rows, idxs

    out, index = jax.vmap(per_image)(bboxes, scores)
    return {"Out": [out], "Index": [index[..., None]]}


@register_op("bipartite_match")
def _bipartite_match(ctx, ins, attrs):
    """Greedy bipartite matching (ref detection/bipartite_match_op.cc):
    repeatedly take the global max of the distance matrix."""
    dist = ins["DistMat"][0]  # (R, C): rows=gt, cols=priors
    r, c = dist.shape

    def body(carry, _):
        d, col_to_row, col_dist = carry
        idx = jnp.argmax(d)
        ri, ci = idx // c, idx % c
        val = d[ri, ci]
        take = val > -1e20
        col_to_row = jnp.where(
            take & (jnp.arange(c) == ci), ri, col_to_row
        )
        col_dist = jnp.where(take & (jnp.arange(c) == ci), val, col_dist)
        d = jnp.where(jnp.arange(r)[:, None] == ri, -1e30, d)
        d = jnp.where(jnp.arange(c)[None, :] == ci, -1e30, d)
        return (d, col_to_row, col_dist), None

    init = (
        dist,
        jnp.full((c,), -1, jnp.int32),
        jnp.zeros((c,), dist.dtype),
    )
    (d, col_to_row, col_dist), _ = lax.scan(
        body, init, None, length=min(r, c)
    )
    return {
        "ColToRowMatchIndices": [col_to_row[None, :]],
        "ColToRowMatchDist": [col_dist[None, :]],
    }


@register_op("yolov3_loss")
def _yolov3_loss(ctx, ins, attrs):
    """YOLOv3 training loss (ref detection/yolov3_loss_op.cc): coordinate
    MSE + objectness/class BCE; gt assigned to the best-matching masked
    anchor at its center cell."""
    x = ins["X"][0]            # (N, A*(5+C), H, W)
    gt_box = ins["GTBox"][0]   # (N, G, 4) cx cy w h, normalized
    gt_label = ins["GTLabel"][0].astype(jnp.int32)  # (N, G)
    anchors = np.asarray(attrs["anchors"], np.float32)
    anchor_mask = list(attrs["anchor_mask"])
    class_num = attrs["class_num"]
    ignore_thresh = attrs.get("ignore_thresh", 0.7)
    downsample = attrs.get("downsample_ratio", 32)
    n, _, h, w = x.shape
    na = len(anchor_mask)
    input_h, input_w = downsample * h, downsample * w
    x = x.reshape(n, na, 5 + class_num, h, w)
    # jnp (not numpy) so traced best_a indices can gather into it
    masked_anchors = jnp.asarray(anchors.reshape(-1, 2)[anchor_mask])

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + jnp.log1p(
            jnp.exp(-jnp.abs(logit))
        )

    def per_image(xi, boxes, labels):
        # xi: (5+C, A, H, W); assign each gt to its center cell + best
        # anchor by wh IoU. Invalid (zero-padded) gt rows scatter into a
        # dump column that is sliced away, so they cannot clobber cell 0.
        valid = (boxes[:, 2] > 0) & (boxes[:, 3] > 0)
        gi = jnp.clip((boxes[:, 0] * w).astype(jnp.int32), 0, w - 1)
        gj = jnp.clip((boxes[:, 1] * h).astype(jnp.int32), 0, h - 1)
        gi = jnp.where(valid, gi, w)  # dump column index
        gw = boxes[:, 2] * input_w
        gh = boxes[:, 3] * input_h
        aw = masked_anchors[:, 0][None, :]
        ah = masked_anchors[:, 1][None, :]
        inter = jnp.minimum(gw[:, None], aw) * jnp.minimum(gh[:, None], ah)
        union = gw[:, None] * gh[:, None] + aw * ah - inter
        best_a = jnp.argmax(inter / jnp.maximum(union, 1e-10), axis=1)

        def scat(vals, init=0.0, dtype=jnp.float32):
            t = jnp.full((na, h, w + 1), init, dtype)
            return t.at[best_a, gj, gi].set(vals)[:, :, :w]

        obj_target = scat(valid.astype(jnp.float32))
        txt = scat(boxes[:, 0] * w - jnp.minimum(gi, w - 1))
        tyt = scat(boxes[:, 1] * h - gj)
        twt = scat(jnp.log(jnp.maximum(
            gw / jnp.maximum(masked_anchors[best_a, 0], 1e-6), 1e-6)))
        tht = scat(jnp.log(jnp.maximum(
            gh / jnp.maximum(masked_anchors[best_a, 1], 1e-6), 1e-6)))
        cls_t = scat(labels, init=0, dtype=jnp.int32)

        pos = obj_target
        txi, tyi, twi, thi = xi[0], xi[1], xi[2], xi[3]
        obj_logit = xi[4]
        cls_logit = xi[5:]
        coord = pos * (
            bce(txi, txt)
            + bce(tyi, tyt)
            + (twi - twt) ** 2
            + (thi - tht) ** 2
        )
        # objectness: positives get BCE vs 1; negatives are ignored when
        # their decoded box overlaps ANY gt above ignore_thresh (ref
        # yolov3_loss_op.h best-IoU ignore rule)
        grid_x = jnp.arange(w)[None, None, :]
        grid_y = jnp.arange(h)[None, :, None]
        px = (jax.nn.sigmoid(txi) + grid_x) / w
        py = (jax.nn.sigmoid(tyi) + grid_y) / h
        pw = jnp.exp(jnp.clip(twi, -10, 10)) * (
            masked_anchors[:, 0][:, None, None] / input_w
        )
        ph = jnp.exp(jnp.clip(thi, -10, 10)) * (
            masked_anchors[:, 1][:, None, None] / input_h
        )
        # IoU of every prediction against every (valid) gt, center-size form
        def iou_vs_gt(gb):
            ix = jnp.minimum(px + pw / 2, gb[0] + gb[2] / 2) - jnp.maximum(
                px - pw / 2, gb[0] - gb[2] / 2
            )
            iy = jnp.minimum(py + ph / 2, gb[1] + gb[3] / 2) - jnp.maximum(
                py - ph / 2, gb[1] - gb[3] / 2
            )
            inter_ = jnp.maximum(ix, 0) * jnp.maximum(iy, 0)
            union_ = pw * ph + gb[2] * gb[3] - inter_
            return inter_ / jnp.maximum(union_, 1e-10)

        ious = jax.vmap(iou_vs_gt)(boxes)  # (G, A, H, W)
        ious = jnp.where(valid[:, None, None, None], ious, 0.0)
        best_iou = jnp.max(ious, axis=0)
        noobj = (pos == 0) & (best_iou <= ignore_thresh)
        obj_l = pos * bce(obj_logit, 1.0) + noobj * bce(obj_logit, 0.0)
        cls_oh = jax.nn.one_hot(cls_t, class_num).transpose(3, 0, 1, 2)
        cls_l = pos[None] * bce(cls_logit, cls_oh)
        return jnp.sum(coord) + jnp.sum(obj_l) + jnp.sum(cls_l)

    losses = jax.vmap(per_image)(jnp.moveaxis(x, 2, 1), gt_box, gt_label)
    return {"Loss": [losses]}
