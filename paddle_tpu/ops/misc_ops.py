"""Misc op lowerings: CTC, NCE, hierarchical sigmoid, row_conv, unfold,
shard_index, hash, cvm, fsp (ref: paddle/fluid/operators/{warpctc_op,nce_op,
hierarchical_sigmoid_op,row_conv_op,unfold_op,shard_index_op,hash_op,cvm_op,
fsp_op}.*)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .registry import register_op, single


@register_op("py_func")
def _py_func(ctx, ins, attrs):
    """Custom python op (ref operators/py_func_op.cc) via
    jax.pure_callback: the host function runs outside the XLA module with
    numpy arrays; backward_func (when given) becomes the custom VJP, also
    a callback."""
    import os

    from ..fluid.layers.nn import _PY_FUNC_REGISTRY

    platform = getattr(ctx, "platform", None) or jax.default_backend()
    if platform == "tpu" and not os.environ.get(
        "PADDLE_TPU_ALLOW_CALLBACKS"
    ):
        # the tunneled axon PJRT runtime rejects host send/recv callbacks
        # at execution time with an opaque UNIMPLEMENTED; fail at lowering
        # with guidance instead (cloud TPU runtimes that do support
        # callbacks can opt in via PADDLE_TPU_ALLOW_CALLBACKS=1)
        raise NotImplementedError(
            "py_func executes host python via jax.pure_callback, which "
            "this TPU runtime does not support — run py_func graphs on "
            "CPU, rewrite the function with fluid ops, or set "
            "PADDLE_TPU_ALLOW_CALLBACKS=1 on a runtime with host-callback "
            "support"
        )
    func, backward_func, skip = _PY_FUNC_REGISTRY[attrs["func_id"]]
    xs = list(ins["X"])
    out_dtypes = [np.dtype(d) for d in attrs["out_dtypes"]]
    batch = xs[0].shape[0] if xs and xs[0].ndim else 1
    out_shapes = []
    for s in attrs["out_shapes"]:
        out_shapes.append(tuple(batch if d == -1 else d for d in s))
    structs = tuple(
        jax.ShapeDtypeStruct(s, d) for s, d in zip(out_shapes, out_dtypes)
    )

    def host_fwd(*arrays):
        res = func(*arrays)
        if res is None:  # debugging/printing use (ref allows it)
            res = arrays[: len(structs)]
        if not isinstance(res, (tuple, list)):
            res = (res,)
        return tuple(
            np.asarray(r, dtype=d).reshape(s)
            for r, s, d in zip(res, out_shapes, out_dtypes)
        )

    if backward_func is None:
        outs = jax.pure_callback(host_fwd, structs, *xs)
        return {"Out": list(outs)}

    x_names = attrs["x_names"]
    out_names = attrs["out_names"]

    @jax.custom_vjp
    def fwd(*xs_):
        return jax.pure_callback(host_fwd, structs, *xs_)

    def fwd_fwd(*xs_):
        outs = jax.pure_callback(host_fwd, structs, *xs_)
        return outs, (xs_, outs)

    def fwd_bwd(res, gouts):
        xs_, outs = res

        def host_bwd(*arrays):
            n_in = len(xs_)
            n_out = len(outs)
            call_args = []
            it = iter(arrays)
            arr_x = [next(it) for _ in range(n_in)]
            arr_out = [next(it) for _ in range(n_out)]
            arr_g = [next(it) for _ in range(n_out)]
            # ref py_func backward signature: x..., out..., dout...
            # with skip_vars_in_backward_input removed
            for name, a in zip(x_names, arr_x):
                if name not in skip:
                    call_args.append(a)
            for name, a in zip(out_names, arr_out):
                if name not in skip:
                    call_args.append(a)
            call_args.extend(arr_g)
            res_ = backward_func(*call_args)
            if not isinstance(res_, (tuple, list)):
                res_ = (res_,)
            return tuple(
                np.zeros(x.shape, x.dtype) if r is None
                else np.asarray(r, x.dtype).reshape(x.shape)
                for r, x in zip(res_, xs_)
            )

        gx_structs = tuple(
            jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs_
        )
        gxs = jax.pure_callback(
            host_bwd, gx_structs, *(list(xs_) + list(outs) + list(gouts))
        )
        return tuple(gxs)

    fwd.defvjp(fwd_fwd, fwd_bwd)
    outs = fwd(*xs)
    return {"Out": list(outs)}


@register_op("similarity_focus")
def _similarity_focus(ctx, ins, attrs):
    """Similarity focus mask (ref operators/similarity_focus_op.h): for
    each selected channel slice T (B', C') greedily pick min(B', C')
    maxima with distinct rows AND columns, OR the picks over indexes,
    broadcast over the focus axis."""
    x = ins["X"][0]              # (N, d1, d2, d3)
    axis = attrs["axis"]
    indexes = attrs["indexes"]
    n = x.shape[0]
    # move the focus axis next to batch: (N, A, B, C)
    perm = [0, axis] + [d for d in range(1, 4) if d != axis]
    xt = jnp.transpose(x, perm)
    b_, c_ = xt.shape[2], xt.shape[3]
    k = min(b_, c_)

    def mask_of(t):
        """(B', C') -> greedy distinct-row/col argmax mask."""
        def body(carry, _):
            cur, mask = carry
            idx = jnp.argmax(cur)
            ri, ci = idx // c_, idx % c_
            mask = mask.at[ri, ci].set(1.0)
            cur = jnp.where(jnp.arange(b_)[:, None] == ri, -jnp.inf, cur)
            cur = jnp.where(jnp.arange(c_)[None, :] == ci, -jnp.inf, cur)
            return (cur, mask), None

        (_, mask), _ = lax.scan(
            body, (t.astype(jnp.float32), jnp.zeros((b_, c_))), None,
            length=k,
        )
        return mask

    total = jnp.zeros((n, b_, c_))
    for ind in indexes:
        total = jnp.maximum(total, jax.vmap(mask_of)(xt[:, int(ind)]))
    out = jnp.broadcast_to(total[:, None], xt.shape).astype(x.dtype)
    inv = [0] * 4
    for i, d in enumerate(perm):
        inv[d] = i
    return single(jnp.transpose(out, inv))


@register_op("merge_selected_rows")
def _merge_selected_rows(ctx, ins, attrs):
    """SelectedRows duplicate-row merge (ref operators/
    merge_selected_rows_op): gradients here are DENSE jax arrays (no
    SelectedRows type — XLA scatters duplicate embedding rows at the
    vjp), so rows are already merged; identity."""
    return {"Out": [ins["X"][0]]}


@register_op("get_tensor_from_selected_rows")
def _get_tensor_from_selected_rows(ctx, ins, attrs):
    """SelectedRows -> dense (ref operators/
    get_tensor_from_selected_rows_op): dense already; identity."""
    return {"Out": [ins["X"][0]]}


@register_op("deformable_psroi_pooling")
def _deformable_psroi_pooling(ctx, ins, attrs):
    """Deformable (PS-)ROI pooling (ref operators/deformable_psroi_pooling
    _op.h): each bin samples at its roi-local position shifted by a
    learned normalized offset, averaged over sample_per_part^2 bilinear
    taps; position_sensitive selects the psroi channel."""
    x = ins["Input"][0]          # (N, C, H, W)
    rois = ins["ROIs"][0]        # (R, 4)
    trans = ins["Trans"][0] if ins.get("Trans") else None
    bidx = (
        ins["RoisBatchIdx"][0].astype(jnp.int32)
        if ins.get("RoisBatchIdx")
        else jnp.zeros((rois.shape[0],), jnp.int32)
    )
    no_trans = attrs.get("no_trans", False)
    scale = attrs.get("spatial_scale", 1.0)
    out_c = attrs.get("output_dim")
    group = attrs.get("group_size", [1, 1])
    ph = attrs.get("pooled_height", 1)
    pw = attrs.get("pooled_width", 1)
    part = attrs.get("part_size", [ph, pw])
    spp = max(attrs.get("sample_per_part", 1), 1)
    trans_std = attrs.get("trans_std", 0.1)
    pos_sensitive = attrs.get("position_sensitive", True)
    n, c_in, h, w = x.shape
    gh, gw = (group if isinstance(group, (list, tuple)) else [group] * 2)
    part_h, part_w = (
        part if isinstance(part, (list, tuple)) else [part] * 2
    )

    def pool_one(roi, bi, tr):
        x1 = roi[0] * scale - 0.5
        y1 = roi[1] * scale - 0.5
        x2 = roi[2] * scale + 0.5
        y2 = roi[3] * scale + 0.5
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h = rh / ph
        bin_w = rw / pw
        img = x[bi]
        ii = jnp.arange(ph)[:, None]
        jj = jnp.arange(pw)[None, :]
        if no_trans or tr is None:
            dy = jnp.zeros((ph, pw))
            dx = jnp.zeros((ph, pw))
        else:
            pi = jnp.clip((ii * part_h) // ph, 0, part_h - 1)
            pj = jnp.clip((jj * part_w) // pw, 0, part_w - 1)
            dy = tr[0, pi, pj] * trans_std * rh
            dx = tr[1, pi, pj] * trans_std * rw

        def sample(sy, sx):
            py = y1 + ii * bin_h + (sy + 0.5) * bin_h / spp + dy
            px = x1 + jj * bin_w + (sx + 0.5) * bin_w / spp + dx
            # out-of-image taps are SKIPPED (excluded from the count),
            # matching the reference kernel — clamping-in would bias the
            # average toward zero at the border
            ok = (py > -1) & (py < h) & (px > -1) & (px < w)
            py = jnp.clip(py, 0.0, h - 1.0)
            px = jnp.clip(px, 0.0, w - 1.0)
            y0 = jnp.floor(py).astype(jnp.int32)
            x0 = jnp.floor(px).astype(jnp.int32)
            wy = py - y0
            wx = px - x0

            def at(yy, xx):
                return img[:, jnp.clip(yy, 0, h - 1),
                           jnp.clip(xx, 0, w - 1)]

            val = (
                at(y0, x0) * (1 - wy) * (1 - wx)
                + at(y0, x0 + 1) * (1 - wy) * wx
                + at(y0 + 1, x0) * wy * (1 - wx)
                + at(y0 + 1, x0 + 1) * wy * wx
            )                                    # (C, ph, pw)
            okf = ok.astype(img.dtype)
            return val * okf, okf

        acc = jnp.zeros((c_in, ph, pw), x.dtype)
        cnt = jnp.zeros((ph, pw), x.dtype)
        for sy in range(spp):
            for sx in range(spp):
                v, okf = sample(sy, sx)
                acc = acc + v
                cnt = cnt + okf
        acc = acc / jnp.maximum(cnt, 1.0)
        if pos_sensitive:
            gi = jnp.clip((ii * gh) // ph, 0, gh - 1)
            gj = jnp.clip((jj * gw) // pw, 0, gw - 1)
            chan = (
                jnp.arange(out_c)[:, None, None] * gh * gw
                + gi[None] * gw + gj[None]
            )
            return acc[chan, ii[None], jj[None]]
        return acc[:out_c]

    if trans is None:
        out = jax.vmap(lambda r_, b_: pool_one(r_, b_, None))(rois, bidx)
    else:
        out = jax.vmap(pool_one)(rois, bidx, trans)
    return {"Output": [out]}


@register_op("tree_conv")
def _tree_conv(ctx, ins, attrs):
    """Tree-based convolution (ref operators/tree_conv_op.h + math/
    tree2col.cc, TBCNN continuous binary tree). TPU redesign: the
    reference's per-node BFS patch walk becomes max_depth reachability
    matmuls (reach_{d+1} = reach_d @ A) with per-(node, depth) eta
    coefficients — all MXU work, no host tree traversal.

    NodesVector (B, N, F); EdgeSet (B, E, 2) int32 (parent, child) pairs,
    1-indexed, zero rows = padding; Filter (F, 3, output_size,
    num_filters) with dim1 ordered (eta_l, eta_r, eta_t) like tree2col's
    patch layout. Out (B, N, output_size, num_filters)."""
    nodes = ins["NodesVector"][0]       # (B, N, F)
    edges = ins["EdgeSet"][0].astype(jnp.int32)  # (B, E, 2)
    w = ins["Filter"][0]                # (F, 3, S, M)
    max_depth = int(attrs.get("max_depth", 2))
    b, n, f = nodes.shape
    e = edges.shape[1]
    fs, _, s_out, m_out = w.shape

    def per_graph(feat, edge):
        parent = edge[:, 0]
        child = edge[:, 1]
        valid = (parent > 0) & (child > 0)
        p0 = jnp.where(valid, parent - 1, n)     # dump row
        c0 = jnp.where(valid, child - 1, n)
        # adjacency with a dump row/col for padded edges
        adj = jnp.zeros((n + 1, n + 1), nodes.dtype).at[p0, c0].set(
            jnp.where(valid, 1.0, 0.0)
        )[:n, :n]
        # index of each child among its parent's children = 1 + number of
        # EARLIER edge rows with the same parent (tree2col uses the
        # child-list order, which is edge-row order)
        same_parent_before = (
            (parent[None, :] == parent[:, None])
            & valid[None, :] & valid[:, None]
            & (jnp.arange(e)[None, :] < jnp.arange(e)[:, None])
        )
        index_e = 1.0 + jnp.sum(same_parent_before, axis=1)
        pclen_e = jnp.sum(
            (parent[None, :] == parent[:, None]) & valid[None, :]
            & valid[:, None],
            axis=1,
        ).astype(nodes.dtype)
        # scatter per-child (index, pclen) to node ids
        idx_n = jnp.ones((n + 1,), nodes.dtype).at[c0].set(
            jnp.where(valid, index_e, 1.0))[:n]
        pcl_n = jnp.ones((n + 1,), nodes.dtype).at[c0].set(
            jnp.where(valid, pclen_e, 1.0))[:n]

        out = jnp.zeros((n, f * 3), nodes.dtype)
        reach = jnp.eye(n, dtype=nodes.dtype)
        for d in range(max_depth):
            eta_t = (max_depth - d) / max_depth
            if d == 0:
                # the root enters its own patch as TreeNode(index=1,
                # pclen=1) regardless of its position under its parent
                lfac = jnp.full((n,), 0.5, nodes.dtype)
            else:
                lfac = jnp.where(
                    pcl_n == 1.0, 0.5,
                    (idx_n - 1.0) / jnp.maximum(pcl_n - 1.0, 1.0),
                )
            eta_l = (1.0 - eta_t) * lfac
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            coefs = jnp.stack(
                [eta_l, eta_r, jnp.full((n,), eta_t, nodes.dtype)], axis=1
            )                                     # (N, 3)
            weighted = feat[:, :, None] * coefs[:, None, :]  # (N, F, 3)
            out = out + reach @ weighted.reshape(n, f * 3)
            reach = reach @ adj
        return out

    patches = jax.vmap(per_graph)(nodes, edges)   # (B, N, F*3)
    wk = w.reshape(fs * 3, s_out * m_out)
    out = (patches.reshape(b, n, fs * 3) @ wk).reshape(b, n, s_out, m_out)
    return single(out)


@register_op("isinf_any")
def _isinf_any(ctx, ins, attrs):
    return single(jnp.any(jnp.isinf(ins["X"][0])))


@register_op("isnan_any")
def _isnan_any(ctx, ins, attrs):
    return single(jnp.any(jnp.isnan(ins["X"][0])))


@register_op("shard_index")
def _shard_index(ctx, ins, attrs):
    x = ins["X"][0]
    index_num = attrs["index_num"]
    nshards = attrs["nshards"]
    shard_id = attrs["shard_id"]
    ignore_value = attrs.get("ignore_value", -1)
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return single(jnp.where(in_shard, x % shard_size, ignore_value))


@register_op("hash")
def _hash(ctx, ins, attrs):
    x = ins["X"][0].astype(jnp.uint32)
    mod_by = attrs["mod_by"]
    num_hash = attrs.get("num_hash", 1)
    outs = []
    for i in range(num_hash):
        h = (x * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B9 * (i + 1)))
        h = h ^ (h >> 16)
        outs.append((h % jnp.uint32(mod_by)).astype(jnp.int64))
    out = jnp.stack(outs, axis=-2) if num_hash > 1 else outs[0]
    return single(out)


@register_op("row_conv")
def _row_conv(ctx, ins, attrs):
    """Lookahead row convolution over (B, T, D) with future context window."""
    x, w = ins["X"][0], ins["Filter"][0]  # w: (ctx+1, D)
    k = w.shape[0]
    out = jnp.zeros_like(x)
    for i in range(k):
        shifted = jnp.pad(x[:, i:, :], ((0, 0), (0, i), (0, 0)))
        out = out + shifted * w[i][None, None, :]
    return single(out)


@register_op("unfold")
def _unfold(ctx, ins, attrs):
    x = ins["X"][0]
    ks = attrs["kernel_sizes"]
    st = attrs["strides"]
    pd = attrs["paddings"]
    dl = attrs["dilations"]
    n, c, h, w = x.shape
    patches = lax.conv_general_dilated_patches(
        x,
        filter_shape=tuple(ks),
        window_strides=tuple(st),
        padding=[(pd[0], pd[0]), (pd[1], pd[1])] if len(pd) == 2 else [(pd[0], pd[1]), (pd[2], pd[3])],
        rhs_dilation=tuple(dl),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    np_, cp, hp, wp = patches.shape
    return {"Y": [patches.reshape(np_, cp, hp * wp)]}


@register_op("im2sequence")
def _im2sequence(ctx, ins, attrs):
    x = ins["X"][0]
    ks = attrs["kernels"]
    st = attrs["strides"]
    patches = lax.conv_general_dilated_patches(
        x,
        filter_shape=tuple(ks),
        window_strides=tuple(st),
        padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    n, cp, hp, wp = patches.shape
    return single(
        jnp.moveaxis(patches.reshape(n, cp, hp * wp), 1, 2).reshape(-1, cp)
    )


@register_op("cvm")
def _cvm(ctx, ins, attrs):
    x = ins["X"][0]
    if attrs.get("use_cvm", True):
        return {"Y": [x]}
    return {"Y": [x[:, 2:]]}


@register_op("fsp")
def _fsp(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    n, cx = x.shape[0], x.shape[1]
    cy = y.shape[1]
    hw = x.shape[2] * x.shape[3]
    xf = x.reshape(n, cx, hw)
    yf = y.reshape(n, cy, hw)
    return single(jnp.einsum("nch,ndh->ncd", xf, yf) / hw)


@register_op("nce")
def _nce(ctx, ins, attrs):
    """Noise-contrastive estimation with uniform negative sampling."""
    x = ins["Input"][0]          # (B, D)
    label = ins["Label"][0]      # (B, num_true)
    w = ins["Weight"][0]         # (C, D)
    b = ins["Bias"][0] if ins.get("Bias") else None  # (C, 1)
    num_neg = attrs.get("num_neg_samples", 10)
    n_classes = attrs["num_total_classes"]
    lab = label.astype(jnp.int32)
    if lab.ndim == 1:
        lab = lab[:, None]
    neg = jax.random.randint(ctx.next_rng(), (num_neg,), 0, n_classes)

    def score(ids):  # ids (..,) -> logits
        s = jnp.einsum("bd,...d->b...", x, w[ids])
        if b is not None:
            s = s + b[ids, 0]
        return s

    true_logit = jnp.sum(x * w[lab[:, 0]], axis=-1)
    if b is not None:
        true_logit = true_logit + b[lab[:, 0], 0]
    neg_logit = x @ w[neg].T
    if b is not None:
        neg_logit = neg_logit + b[neg, 0][None, :]
    logq = jnp.log(num_neg / n_classes)
    pos_loss = jax.nn.softplus(-(true_logit - logq))
    neg_loss = jnp.sum(jax.nn.softplus(neg_logit - logq), axis=-1)
    return {"Cost": [(pos_loss + neg_loss)[:, None]]}


@register_op("hierarchical_sigmoid")
def _hsigmoid(ctx, ins, attrs):
    """Default complete-binary-tree hierarchical sigmoid."""
    x = ins["X"][0]          # (B, D)
    label = ins["Label"][0]  # (B, 1)
    w = ins["W"][0]          # (C-1, D)
    b = ins["Bias"][0] if ins.get("Bias") else None
    num_classes = attrs["num_classes"]
    depth = max(1, int(np.ceil(np.log2(max(num_classes, 2)))))
    lab = label.astype(jnp.int32)
    if lab.ndim == 2:
        lab = lab[:, 0]
    # complete binary tree: internal node ids along the path to leaf `lab`
    loss = jnp.zeros(x.shape[0], x.dtype)
    node = jnp.ones_like(lab)  # root = 1 (1-indexed heap order)
    code = lab + num_classes   # leaf position in heap
    # walk from leaf up: bits of (lab + C) below the msb give directions
    for d in range(depth, 0, -1):
        parent = code >> d
        bit = (code >> (d - 1)) & 1
        nid = jnp.clip(parent - 1, 0, w.shape[0] - 1)
        valid = parent >= 1
        logit = jnp.sum(x * w[nid], axis=-1)
        if b is not None:
            logit = logit + b[nid, 0]
        # bit==1 → go right (target 1), else 0
        step_loss = jax.nn.softplus(jnp.where(bit == 1, -logit, logit))
        loss = loss + jnp.where(valid, step_loss, 0.0)
    return {"Out": [loss[:, None]]}


@register_op("warpctc")
def _warpctc(ctx, ins, attrs):
    """CTC loss, dense log-domain forward algorithm via lax.scan
    (TPU-native replacement for the warp-ctc CUDA kernel).

    Logits: (B, T, C) padded; Label: (B, L) padded with `blank`;
    LogitsLength/LabelLength: (B,) int. Output: (B, 1) loss.
    """
    logits = ins["Logits"][0]
    label = ins["Label"][0].astype(jnp.int32)
    blank = attrs.get("blank", 0)
    B = logits.shape[0] if logits.ndim == 3 else 1
    if logits.ndim == 2:
        logits = logits[None]
        label = label[None] if label.ndim == 1 else label
    T = logits.shape[1]
    L = label.shape[1]
    logits_len = (
        ins["LogitsLength"][0].astype(jnp.int32).reshape(-1)
        if ins.get("LogitsLength")
        else jnp.full((B,), T, jnp.int32)
    )
    label_len = (
        ins["LabelLength"][0].astype(jnp.int32).reshape(-1)
        if ins.get("LabelLength")
        else jnp.sum((label != blank).astype(jnp.int32), axis=1)
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    NEG = -1e30

    # extended label: blank, l1, blank, l2, ..., blank  (length S = 2L+1)
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    pos = jnp.arange(S)[None, :]
    valid_ext = pos < (2 * label_len[:, None] + 1)
    # allowed skip: ext[s] != ext[s-2]
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (ext != ext_m2) & (pos >= 2)

    alpha0 = jnp.full((B, S), NEG)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    first_lab = jnp.take_along_axis(
        logp[:, 0, :], ext[:, 1:2].clip(0), axis=-1
    )[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(label_len > 0, first_lab, NEG))

    def step(alpha, t):
        a_prev = alpha
        a_m1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=NEG)[:, :S]
        a_m2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=NEG)[:, :S]
        a_m2 = jnp.where(can_skip, a_m2, NEG)
        merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_m1), a_m2)
        emit = jnp.take_along_axis(logp[:, t, :], ext.clip(0), axis=-1)
        new_alpha = merged + emit
        new_alpha = jnp.where(valid_ext, new_alpha, NEG)
        # freeze past logits_len
        new_alpha = jnp.where((t < logits_len)[:, None], new_alpha, alpha)
        return new_alpha, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    end1 = 2 * label_len - 1
    end2 = 2 * label_len
    a1 = jnp.take_along_axis(alpha, end1.clip(0)[:, None], axis=1)[:, 0]
    a1 = jnp.where(label_len > 0, a1, NEG)
    a2 = jnp.take_along_axis(alpha, end2[:, None], axis=1)[:, 0]
    loss = -jnp.logaddexp(a1, a2)
    return {"Loss": [loss[:, None]]}


@register_op("space_to_depth")
def _space_to_depth(ctx, ins, attrs):
    x = ins["X"][0]
    b = attrs["blocksize"]
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // b, b, w // b, b)
    x = jnp.transpose(x, (0, 3, 5, 1, 2, 4))
    return single(x.reshape(n, c * b * b, h // b, w // b))


@register_op("affine_channel")
def _affine_channel(ctx, ins, attrs):
    x = ins["X"][0]
    layout = attrs.get("data_layout", "NCHW")
    ch_axis = 1 if layout == "NCHW" else x.ndim - 1
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]
    out = x
    if ins.get("Scale"):
        out = out * ins["Scale"][0].reshape(bshape)
    if ins.get("Bias"):
        out = out + ins["Bias"][0].reshape(bshape)
    return single(out)


@register_op("gru_unit")
def _gru_unit(ctx, ins, attrs):
    """Single GRU step (ref: paddle/fluid/operators/gru_unit_op.cc).
    Input: (B, 3D) projected input; Weight: (D, 3D) with gate weights in
    the first 2D columns and candidate weights in the last D."""
    x = ins["Input"][0]            # (B, 3D)
    h_prev = ins["HiddenPrev"][0]  # (B, D)
    w = ins["Weight"][0]           # (D, 3D)
    b = ins["Bias"][0] if ins.get("Bias") else None
    d = h_prev.shape[-1]
    origin_mode = attrs.get("origin_mode", False)
    gate_act = attrs.get("gate_activation", "sigmoid")
    act = attrs.get("activation", "tanh")
    if b is not None:
        x = x + b.reshape((1, 3 * d))
    gates = x[:, : 2 * d] + h_prev @ w[:, : 2 * d]
    gact = jax.nn.sigmoid if gate_act == "sigmoid" else jnp.tanh
    cact = jnp.tanh if act == "tanh" else jax.nn.relu
    u = gact(gates[:, :d])
    r = gact(gates[:, d : 2 * d])
    reset_h = r * h_prev
    c = cact(x[:, 2 * d :] + reset_h @ w[:, 2 * d :])
    if origin_mode:
        h = u * h_prev + (1 - u) * c
    else:
        h = (1 - u) * h_prev + u * c
    return {"Hidden": [h], "ResetHiddenPrev": [reset_h], "Gate": [gates]}
