"""Reader decorators (ref: python/paddle/reader/decorator.py +
python/paddle/batch.py)."""
import itertools
import random

__all__ = ["batch", "shuffle", "buffered", "map_readers", "chain", "compose",
           "firstn", "xmap_readers", "cache", "ComposeNotAligned",
           "multiprocess_reader", "retry_reader"]


def batch(reader, batch_size, drop_last=False):
    def batch_reader():
        r = reader()
        b = []
        for instance in r:
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    if batch_size <= 0:
        raise ValueError("batch_size should be positive")
    return batch_reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def buffered(reader, size):
    import queue
    import threading

    class _End:
        pass

    def data_reader():
        r = reader()
        q = queue.Queue(maxsize=size)

        def _fill():
            for d in r:
                q.put(d)
            q.put(_End)

        t = threading.Thread(target=_fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e

    return data_reader


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def chain(*readers):
    def reader():
        for r in readers:
            for item in r():
                yield item

    return reader



class ComposeNotAligned(ValueError):
    """Raised by compose(check_alignment=True) when component readers
    yield different numbers of samples (ref reader/decorator.py)."""


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            _end = object()
            for outputs in itertools.zip_longest(*rs, fillvalue=_end):
                if any(o is _end for o in outputs):
                    if all(o is _end for o in outputs):
                        return
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned (different "
                        "lengths); pass check_alignment=False to zip the "
                        "shorter length"
                    )
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                yield sum(
                    list(map(make_tuple, [o for o in outputs if o is not None])),
                    (),
                )

    return reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    # Thread-pool map with a bounded in-flight window of ``buffer_size``
    # futures (pool.map would eagerly consume the whole source reader).
    # Results always come back in input order, which satisfies order=True;
    # order=False merely permits reordering we don't need to exploit.
    import collections
    import concurrent.futures

    def data_reader():
        with concurrent.futures.ThreadPoolExecutor(process_num) as pool:
            pending = collections.deque()
            for sample in reader():
                pending.append(pool.submit(mapper, sample))
                if len(pending) >= max(int(buffer_size), 1):
                    yield pending.popleft().result()
            while pending:
                yield pending.popleft().result()

    return data_reader


def cache(reader):
    all_data = []
    filled = []

    def cache_reader():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        for d in all_data:
            yield d

    return cache_reader



def retry_reader(reader, retries=2, exceptions=(IOError, RuntimeError),
                 delay=0.0, on_error=None):
    """Restart-on-failure decorator for flaky sources (network storage,
    preprocessing races): when the wrapped reader raises one of
    `exceptions` mid-epoch, the underlying reader is RE-OPENED from the
    start of the epoch and items already yielded this epoch are fast-
    forwarded past (not re-yielded), up to `retries` restarts per epoch.
    Budget exhausted — or any other exception — re-raises. `on_error`
    (if given) sees ``(exception, restart_number)`` before each restart;
    `delay` seconds are slept between restarts."""
    import time as _time

    def retry_wrapped():
        yielded = 0
        restarts = 0
        while True:
            it = reader()
            skip = yielded
            try:
                for item in it:
                    if skip:
                        skip -= 1
                        continue
                    yield item
                    yielded += 1
                return
            except exceptions as e:  # noqa: PERF203 — per-epoch, not per-item
                restarts += 1
                if restarts > retries:
                    raise
                if on_error is not None:
                    on_error(e, restarts)
                if delay:
                    _time.sleep(delay)

    if retries < 0:
        raise ValueError("retries must be >= 0")
    return retry_wrapped


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """ref reader/decorator.py multiprocess_reader. Thread-based here:
    the payloads are numpy batches, and the producers' work (IO, numpy
    prep, the C++ staging pipe) releases the GIL, so threads deliver the
    overlap without fork()ing a jax-initialized process (unsafe: the TPU
    client does not survive fork)."""
    import queue as _q
    import threading

    def reader():
        out = _q.Queue(maxsize=queue_size)
        alive = [len(readers)]
        lock = threading.Lock()

        def pump(r):
            try:
                for item in r():
                    out.put(item)
            finally:
                with lock:
                    alive[0] -= 1
                    if alive[0] == 0:
                        out.put(None)

        for r in readers:
            threading.Thread(target=pump, args=(r,), daemon=True).start()
        while True:
            item = out.get()
            if item is None:
                return
            yield item

    return reader
