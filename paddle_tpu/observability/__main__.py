"""Observability CLI.

``python -m paddle_tpu.observability trace <dir> [-o out.json]``
merges the per-process ``trace-*.jsonl`` span files a traced serving
run left under ``$PADDLE_TPU_TRACE_DIR`` into one Perfetto-loadable
Chrome trace-event file (load it at https://ui.perfetto.dev or
``chrome://tracing``) and prints a per-trace phase summary.

``python -m paddle_tpu.observability perf <dir|snapshot.json>``
renders the executable ledger's predicted-vs-XLA-vs-measured drift
table from a bench ``--telemetry-out`` file (the ledger rides under
its ``"ledger"`` key), a bare ``ExecutableLedger.snapshot()`` JSON, or
a directory of either.

``python -m paddle_tpu.observability run <dir|snapshot.json> [B]``
renders a training run-health report — goodput decomposition, loss
trajectory, anomaly counts — from a ``RunHealth.dump()`` snapshot, a
StepSeries JSONL, a crash dump, a bench ``--telemetry-out`` file, or
a directory of any. With a second path it renders the A/B comparison
table instead.
"""
import argparse
import json
import sys

from . import distributed as _dist
from . import perf as _perf
from . import runhealth as _rh


def _cmd_trace(args):
    spans = _dist.read_spans(args.dir)
    if not spans:
        print("no span records under %s" % args.dir, file=sys.stderr)
        return 1
    doc = _dist.chrome_trace(spans, trace_id=args.trace_id)
    out = args.out or "trace.json"
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    import os

    os.replace(tmp, out)
    meta = doc["otherData"]
    print("wrote %s: %d spans, %d cross-process flows, %d process "
          "tracks, %d trace(s)" % (out, meta["spans"], meta["flows"],
                                   len(meta["processes"]),
                                   len(meta["traces"])))
    for tid in meta["traces"]:
        phases = _dist.phase_breakdown(spans, trace_id=tid)
        if not phases:
            continue
        parts = []
        for phase in _dist.PHASES:
            st = phases.get(phase)
            if st:
                parts.append("%s %.1fms x%d"
                             % (phase, st["total_s"] * 1e3, st["count"]))
        print("  trace %s: %s" % (tid[:16], ", ".join(parts) or "-"))
    return 0


def _cmd_perf(args):
    snap = _perf.load_snapshot(args.path)
    rows = _perf.drift_rows(snap)
    if not rows:
        print("no ledger entries under %s (want a bench "
              "--telemetry-out JSON or an ExecutableLedger.snapshot() "
              "file)" % args.path, file=sys.stderr)
        return 1
    print(_perf.render_drift_table(rows))
    s = _perf.drift_summary(rows)
    parts = ["%d executable(s)" % s["entries"],
             "%d partial" % s["partial"],
             "%d measured" % s["with_measured"]]
    if s["mean_abs_step_drift_pct"] is not None:
        parts.append("mean |step drift| %.1f%%"
                     % s["mean_abs_step_drift_pct"])
    if s["mean_abs_hbm_drift_pct"] is not None:
        parts.append("mean |hbm drift| %.1f%%"
                     % s["mean_abs_hbm_drift_pct"])
    print(", ".join(parts))
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"rows": rows, "summary": s}, f)
        import os

        os.replace(tmp, args.out)
        print("wrote %s" % args.out)
    return 0


def _cmd_run(args):
    run_a = _rh.load_run(args.path)
    if run_a["series"] is None and run_a["goodput"] is None:
        print("no run-health records under %s (want a RunHealth "
              "snapshot JSON, a StepSeries JSONL, a crash dump, a "
              "bench --telemetry-out file, or a directory of any)"
              % args.path, file=sys.stderr)
        return 1
    if args.path_b:
        run_b = _rh.load_run(args.path_b)
        if run_b["series"] is None and run_b["goodput"] is None:
            print("no run-health records under %s" % args.path_b,
                  file=sys.stderr)
            return 1
        print("A: %s\nB: %s" % (run_a["path"], run_b["path"]))
        print(_rh.render_comparison(run_a, run_b))
    else:
        print(_rh.render_health_report(run_a))
    if args.out:
        doc = {"a": run_a}
        if args.path_b:
            doc["b"] = run_b
        tmp = args.out + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        import os

        os.replace(tmp, args.out)
        print("wrote %s" % args.out)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability",
        description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("trace", help="merge JSONL span files into a "
                        "Chrome trace-event JSON")
    tr.add_argument("dir", help="trace directory "
                    "(the run's $PADDLE_TPU_TRACE_DIR)")
    tr.add_argument("-o", "--out", default=None,
                    help="output path (default: trace.json)")
    tr.add_argument("--trace-id", default=None,
                    help="keep only this trace id")
    tr.set_defaults(fn=_cmd_trace)
    pf = sub.add_parser("perf", help="render the executable ledger's "
                        "predicted-vs-XLA-vs-measured drift table")
    pf.add_argument("path", help="bench --telemetry-out JSON, a ledger "
                    "snapshot JSON, or a directory of either")
    pf.add_argument("-o", "--out", default=None,
                    help="also write the rows+summary as JSON here")
    pf.set_defaults(fn=_cmd_perf)
    rn = sub.add_parser("run", help="render a training run-health "
                        "report (goodput + anomalies), or an A/B "
                        "comparison of two runs")
    rn.add_argument("path", help="RunHealth snapshot JSON, StepSeries "
                    "JSONL, crash dump, bench --telemetry-out file, "
                    "or a directory of any")
    rn.add_argument("path_b", nargs="?", default=None,
                    help="optional second run for an A/B comparison")
    rn.add_argument("-o", "--out", default=None,
                    help="also write the loaded run doc(s) as JSON")
    rn.set_defaults(fn=_cmd_run)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
