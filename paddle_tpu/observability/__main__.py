"""Observability CLI.

``python -m paddle_tpu.observability trace <dir> [-o out.json]``
merges the per-process ``trace-*.jsonl`` span files a traced serving
run left under ``$PADDLE_TPU_TRACE_DIR`` into one Perfetto-loadable
Chrome trace-event file (load it at https://ui.perfetto.dev or
``chrome://tracing``) and prints a per-trace phase summary.
"""
import argparse
import json
import sys

from . import distributed as _dist


def _cmd_trace(args):
    spans = _dist.read_spans(args.dir)
    if not spans:
        print("no span records under %s" % args.dir, file=sys.stderr)
        return 1
    doc = _dist.chrome_trace(spans, trace_id=args.trace_id)
    out = args.out or "trace.json"
    tmp = out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    import os

    os.replace(tmp, out)
    meta = doc["otherData"]
    print("wrote %s: %d spans, %d cross-process flows, %d process "
          "tracks, %d trace(s)" % (out, meta["spans"], meta["flows"],
                                   len(meta["processes"]),
                                   len(meta["traces"])))
    for tid in meta["traces"]:
        phases = _dist.phase_breakdown(spans, trace_id=tid)
        if not phases:
            continue
        parts = []
        for phase in _dist.PHASES:
            st = phases.get(phase)
            if st:
                parts.append("%s %.1fms x%d"
                             % (phase, st["total_s"] * 1e3, st["count"]))
        print("  trace %s: %s" % (tid[:16], ", ".join(parts) or "-"))
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability",
        description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("trace", help="merge JSONL span files into a "
                        "Chrome trace-event JSON")
    tr.add_argument("dir", help="trace directory "
                    "(the run's $PADDLE_TPU_TRACE_DIR)")
    tr.add_argument("-o", "--out", default=None,
                    help="output path (default: trace.json)")
    tr.add_argument("--trace-id", default=None,
                    help="keep only this trace id")
    tr.set_defaults(fn=_cmd_trace)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
