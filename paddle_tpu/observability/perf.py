"""Predicted-vs-XLA-vs-measured drift reporting over the executable
ledger.

Three columns per executable, one source each:

- **predicted** — the static analyzer's roofline (``analysis.costs``)
  noted into the ledger per program fingerprint,
- **XLA** — what ``compiled.cost_analysis()`` /
  ``memory_analysis()`` reported at registration (absent on partial
  entries: deserialized disk artifacts, backends without the APIs),
- **measured** — steady-state step seconds a bench/serving loop
  attached via ``ExecutableLedger.note_measured``.

``drift_rows`` flattens a ledger (live object or ``snapshot()`` dict)
into comparable rows; ``render_drift_table`` prints them as an aligned
text table; ``load_snapshot`` reads them back from a bench
``--telemetry-out`` JSON (the ledger rides under its ``"ledger"``
key), a bare ledger-snapshot JSON, or a directory of either. The
``python -m paddle_tpu.observability perf <dir|snapshot.json>`` CLI
wraps the three.

Stdlib-only, like the rest of the package.
"""
import json
import os

from . import ledger as _ledger

__all__ = ["drift_rows", "render_drift_table", "load_snapshot",
           "drift_summary"]


def _entries_of(snap):
    if snap is None:
        return []
    if isinstance(snap, _ledger.ExecutableLedger):
        return snap.entries()
    if isinstance(snap, dict):
        return list(snap.get("entries") or [])
    if isinstance(snap, (list, tuple)):
        return list(snap)
    return []


def _pct(new, ref):
    """Signed percent drift of `new` vs `ref` (None when either side
    is unknown or the reference is 0)."""
    if new is None or not ref:
        return None
    return 100.0 * (float(new) - float(ref)) / float(ref)


def drift_rows(snap):
    """One row per ledger entry: the predicted / XLA / measured
    columns plus signed drift percentages (``step_drift_pct`` =
    predicted vs measured step time, ``hbm_drift_pct`` = predicted vs
    XLA peak HBM)."""
    rows = []
    for e in _entries_of(snap):
        pred = e.get("predicted") or {}
        xla = e.get("xla") or {}
        mem = e.get("memory") or {}
        measured_s = e.get("measured_step_seconds")
        pred_s = pred.get("predicted_step_seconds")
        pred_hbm = pred.get("predicted_peak_hbm_bytes")
        xla_hbm = mem.get("total_bytes")
        rows.append({
            "n": e.get("n"),
            "kind": e.get("kind"),
            "source": e.get("source"),
            "fingerprint": (e.get("fingerprint") or "")[:12] or "-",
            "partial": bool(e.get("partial")),
            "compile_s": e.get("compile_seconds"),
            "predicted_step_ms": None if pred_s is None
            else 1e3 * pred_s,
            "predicted_mfu": pred.get("predicted_mfu"),
            "predicted_hbm_mb": None if pred_hbm is None
            else pred_hbm / 1e6,
            "predicted_gflops": None if pred.get("total_flops") is None
            else pred["total_flops"] / 1e9,
            "xla_gflops": None if xla.get("flops") is None
            else xla["flops"] / 1e9,
            "xla_bytes_mb": None if xla.get("bytes_accessed") is None
            else xla["bytes_accessed"] / 1e6,
            "xla_hbm_mb": None if xla_hbm is None else xla_hbm / 1e6,
            "measured_step_ms": None if measured_s is None
            else 1e3 * measured_s,
            "step_drift_pct": _pct(pred_s, measured_s),
            "flops_drift_pct": _pct(pred.get("total_flops"),
                                    xla.get("flops")),
            "hbm_drift_pct": _pct(pred_hbm, xla_hbm),
        })
    return rows


_COLUMNS = (
    # (header, row key, format)
    ("#", "n", "%d"),
    ("kind", "kind", "%s"),
    ("src", "source", "%s"),
    ("fingerprint", "fingerprint", "%s"),
    ("compile_s", "compile_s", "%.2f"),
    ("pred_ms", "predicted_step_ms", "%.2f"),
    ("xla_gflop", "xla_gflops", "%.3f"),
    ("xla_hbm_mb", "xla_hbm_mb", "%.1f"),
    ("meas_ms", "measured_step_ms", "%.2f"),
    ("step_drift%", "step_drift_pct", "%+.1f"),
    ("hbm_drift%", "hbm_drift_pct", "%+.1f"),
)


def render_drift_table(rows):
    """Aligned text table of :func:`drift_rows` output. Unknown cells
    render as ``-`` (partial entries have no XLA columns; executables
    never driven by a timed loop have no measured column)."""
    cells = []
    for r in rows:
        line = []
        for _, key, fmt in _COLUMNS:
            v = r.get(key)
            line.append("-" if v is None else fmt % v)
        cells.append(line)
    headers = [c[0] for c in _COLUMNS]
    widths = [max(len(h), *(len(row[i]) for row in cells))
              if cells else len(h) for i, h in enumerate(headers)]
    out = ["  ".join(h.ljust(widths[i])
                     for i, h in enumerate(headers))]
    out.append("  ".join("-" * w for w in widths))
    for line in cells:
        out.append("  ".join(line[i].ljust(widths[i])
                             for i in range(len(widths))))
    return "\n".join(out)


def drift_summary(rows):
    """Aggregate line: entry counts + mean absolute step/HBM drift over
    the rows where both sides are known."""
    step = [abs(r["step_drift_pct"]) for r in rows
            if r["step_drift_pct"] is not None]
    hbm = [abs(r["hbm_drift_pct"]) for r in rows
           if r["hbm_drift_pct"] is not None]
    return {
        "entries": len(rows),
        "partial": sum(1 for r in rows if r["partial"]),
        "with_measured": sum(1 for r in rows
                             if r["measured_step_ms"] is not None),
        "mean_abs_step_drift_pct": round(sum(step) / len(step), 1)
        if step else None,
        "mean_abs_hbm_drift_pct": round(sum(hbm) / len(hbm), 1)
        if hbm else None,
    }


def _snapshot_of_doc(doc):
    """A ledger snapshot out of one loaded JSON document: either a
    bench telemetry-out file ({"ledger": {...}}) or a bare snapshot
    ({"entries": [...]})."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("ledger"), dict):
        doc = doc["ledger"]
    if isinstance(doc.get("entries"), list):
        return doc
    return None


def load_snapshot(path):
    """Read ledger entries from `path`: a JSON file, or a directory
    whose ``*.json`` files are scanned (unreadable / unrelated files
    are skipped) and merged. Returns a snapshot dict; its ``entries``
    list is empty when nothing ledger-shaped was found."""
    merged = {"entries": [], "predictions": {}, "measured": {}}
    if os.path.isdir(path):
        names = sorted(n for n in os.listdir(path)
                       if n.endswith(".json"))
        paths = [os.path.join(path, n) for n in names]
    else:
        paths = [path]
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        snap = _snapshot_of_doc(doc)
        if snap is None:
            continue
        merged["entries"].extend(snap.get("entries") or [])
        merged["predictions"].update(snap.get("predictions") or {})
        merged["measured"].update(snap.get("measured") or {})
    return merged
