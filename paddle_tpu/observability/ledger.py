"""Process-wide executable ledger: what XLA actually compiled.

The analyzers predict step seconds / MFU / peak HBM *before* a compile
(analysis/costs, analysis/memory); this module records what came out of
the other end — one entry per compiled executable (executor step,
dataset-scan body, Predictor engine, serving/decode warmup programs,
and compile-cache disk hits) carrying:

- the program's **structural fingerprint** (``fluid.compile_cache.
  program_fingerprint`` — stable across processes, unlike
  ``Program._uid``),
- XLA's own accounting, probed with guards so backends/artifacts
  without the APIs degrade to *partial* entries instead of failing:
  ``compiled.cost_analysis()`` FLOPs / bytes-accessed and
  ``compiled.memory_analysis()`` HBM breakdown (argument / output /
  temp / generated-code bytes),
- compile seconds and the donation set,
- the analyzer's *predicted* step-seconds/MFU/peak-HBM for the same
  fingerprint (:meth:`ExecutableLedger.note_prediction`), and
- measured steady-state step seconds when a bench/serving loop reports
  them (:meth:`ExecutableLedger.note_measured`).

That closes the predicted -> compiled -> measured loop per executable:
``observability.perf`` renders the drift table, ``analysis.costs.
DeviceProfile.calibrated_from`` fits effective device constants from
it, and ``FlightRecorder.crash_dump`` appends the ledger tail so a
post-mortem shows what was compiled and resident at death.

Telemetry (gated on ``PADDLE_TPU_TELEMETRY`` like every obs helper):
``ledger.registered`` / ``ledger.partial`` / ``ledger.disk_hits``
counters, ``ledger.entries`` gauge, ``ledger.compile_seconds`` and
``ledger.measured_step_seconds`` histograms, and one
``executable_registered`` flight-recorder event per entry.

Stdlib-only: jax objects are probed with ``getattr`` at registration
time, never imported — crash-path and supervisor code can read the
ledger without accelerator init.
"""
import collections
import threading
import time

from . import recorder as _r
from . import telemetry as _t

__all__ = ["ExecutableLedger", "get_ledger"]

# snapshot()/tail() field caps — entries ride in crash dumps and
# telemetry-out JSON, so every free-form field is bounded
_MAX_DONATED = 32
_MAX_PREDICTIONS = 256

# memory_analysis() attributes -> entry keys
_MEMORY_ATTRS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("alias_size_in_bytes", "alias_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)

_PREDICTED_KEYS = ("predicted_step_seconds", "predicted_mfu",
                   "predicted_peak_hbm_bytes", "total_flops",
                   "total_bytes", "device")


def _num(v):
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return float(v)
    return None


def _probe_cost(compiled):
    """``compiled.cost_analysis()`` -> {flops, bytes_accessed, ...} or
    None. Guarded: backends without the API (deserialized
    ``jax.export`` artifacts, some CPU paths) and API-shape drift
    (dict vs list-of-dict across jax versions) both degrade to None."""
    fn = getattr(compiled, "cost_analysis", None)
    if fn is None:
        return None
    try:
        ca = fn()
    except Exception:  # noqa: BLE001 — absent analysis, not an error
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    for k, v in ca.items():
        v = _num(v)
        if v is None:
            continue
        key = str(k).replace(" ", "_")
        if key in ("flops", "bytes_accessed", "transcendentals",
                   "optimal_seconds"):
            out[key] = v
    return out or None


def _probe_memory(compiled):
    """``compiled.memory_analysis()`` -> HBM breakdown dict or None,
    with the same degradation guards as :func:`_probe_cost`."""
    fn = getattr(compiled, "memory_analysis", None)
    if fn is None:
        return None
    try:
        ma = fn()
    except Exception:  # noqa: BLE001
        return None
    if ma is None:
        return None
    out = {}
    for attr, key in _MEMORY_ATTRS:
        v = _num(getattr(ma, attr, None))
        if v is not None:
            out[key] = int(v)
    if not out:
        return None
    # XLA's convention: arguments + outputs + temps + generated code,
    # minus buffers aliased onto arguments (donation)
    total = (out.get("argument_bytes", 0) + out.get("output_bytes", 0)
             + out.get("temp_bytes", 0)
             + out.get("generated_code_bytes", 0)
             - out.get("alias_bytes", 0))
    out["total_bytes"] = int(max(total, 0))
    return out


def _clean_prediction(predicted):
    if not isinstance(predicted, dict):
        return None
    out = {}
    for k in _PREDICTED_KEYS:
        v = predicted.get(k)
        if k == "device":
            if isinstance(v, dict):
                out[k] = {dk: dv for dk, dv in v.items()
                          if dv is None or isinstance(dv,
                                                      (int, float, str))}
            continue
        v = _num(v)
        if v is not None:
            out[k] = v
    return out or None


class ExecutableLedger:
    """Bounded ring of executable entries + per-fingerprint prediction
    and measurement side tables. Thread-safe; every mutator is cheap
    and never raises past its guards (a ledger must not break a
    compile)."""

    def __init__(self, maxlen=512):
        self._lock = threading.Lock()
        self._entries = collections.deque(maxlen=int(maxlen))
        self._predictions = collections.OrderedDict()  # fp -> dict
        self._measured = collections.OrderedDict()     # fp -> seconds
        self._seq = 0

    # -- write side ------------------------------------------------------
    def register(self, kind, fingerprint=None, compiled=None,
                 source="compile", compile_seconds=None, donated=None,
                 extra=None):
        """Record one executable. ``compiled`` is probed (guarded) for
        ``cost_analysis``/``memory_analysis``; everything else is
        plain data. Returns the entry dict (a live reference — callers
        must not mutate it)."""
        xla = _probe_cost(compiled) if compiled is not None else None
        mem = _probe_memory(compiled) if compiled is not None else None
        with self._lock:
            self._seq += 1
            entry = {
                "n": self._seq,
                "wall": time.time(),
                "kind": str(kind),
                "source": str(source),
                "fingerprint": fingerprint,
                "compile_seconds": _num(compile_seconds),
                "donated": sorted(str(d) for d in donated)[:_MAX_DONATED]
                if donated else [],
                "xla": xla,
                "memory": mem,
                "partial": xla is None and mem is None,
                "predicted": self._predictions.get(fingerprint)
                if fingerprint else None,
                "measured_step_seconds": self._measured.get(fingerprint)
                if fingerprint else None,
            }
            if isinstance(extra, dict):
                for k, v in extra.items():
                    entry.setdefault(str(k), v)
            self._entries.append(entry)
            n_entries = len(self._entries)
        self._emit(entry, n_entries)
        return entry

    def _emit(self, entry, n_entries):
        if _t.mode() == _t.OFF:
            return
        hub = _t._hub
        hub.inc("ledger.registered")
        if entry["partial"]:
            hub.inc("ledger.partial")
        if entry["source"] == "disk":
            hub.inc("ledger.disk_hits")
        hub.set_gauge("ledger.entries", n_entries)
        if entry["compile_seconds"] is not None:
            hub.observe("ledger.compile_seconds",
                        entry["compile_seconds"])
        mem = entry.get("memory") or {}
        if mem.get("total_bytes") is not None:
            hub.set_gauge("ledger.hbm_total_bytes", mem["total_bytes"])
        fields = {"exe_kind": entry["kind"],
                  "exe_source": entry["source"],
                  "partial": entry["partial"]}
        if entry["fingerprint"]:
            fields["fingerprint"] = entry["fingerprint"][:16]
        if entry["compile_seconds"] is not None:
            fields["seconds"] = round(entry["compile_seconds"], 6)
        _r._global.record("executable_registered", source="ledger",
                          **fields)

    def note_prediction(self, fingerprint, predicted):
        """Attach the analyzer's prediction for a program fingerprint;
        backfills entries already registered under it. ``predicted``
        keys: predicted_step_seconds / predicted_mfu /
        predicted_peak_hbm_bytes / total_flops / total_bytes / device
        (a ``DeviceProfile.to_dict()``)."""
        if not fingerprint:
            return
        predicted = _clean_prediction(predicted)
        if predicted is None:
            return
        with self._lock:
            self._predictions[fingerprint] = predicted
            self._predictions.move_to_end(fingerprint)
            while len(self._predictions) > _MAX_PREDICTIONS:
                self._predictions.popitem(last=False)
            for e in self._entries:
                if e["fingerprint"] == fingerprint:
                    e["predicted"] = predicted

    def note_measured(self, fingerprint, step_seconds, kind=None):
        """Attach a measured steady-state step time (seconds) to every
        entry under ``fingerprint`` (optionally restricted to one
        ``kind``)."""
        t = _num(step_seconds)
        if not fingerprint or t is None or t <= 0:
            return
        with self._lock:
            self._measured[fingerprint] = t
            self._measured.move_to_end(fingerprint)
            while len(self._measured) > _MAX_PREDICTIONS:
                self._measured.popitem(last=False)
            for e in self._entries:
                if e["fingerprint"] == fingerprint and (
                        kind is None or e["kind"] == kind):
                    e["measured_step_seconds"] = t
        if _t.mode() != _t.OFF:
            _t._hub.observe("ledger.measured_step_seconds", t)

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._predictions.clear()
            self._measured.clear()

    # -- read side -------------------------------------------------------
    def entries(self):
        with self._lock:
            return [dict(e) for e in self._entries]

    def __len__(self):
        with self._lock:
            return len(self._entries)

    def snapshot(self):
        """JSON-safe view: {"entries": [...], "predictions": {...},
        "measured": {...}} — what bench's ``--telemetry-out`` embeds
        under the ``"ledger"`` key and the perf CLI reads back."""
        with self._lock:
            return {
                "entries": [dict(e) for e in self._entries],
                "predictions": {k: dict(v)
                                for k, v in self._predictions.items()},
                "measured": dict(self._measured),
            }

    def tail(self, n=16):
        """Compact newest-last view for crash dumps: fingerprint,
        kind/source, compile seconds, HBM bytes."""
        out = []
        for e in self.entries()[-int(n):]:
            mem = e.get("memory") or {}
            out.append({
                "n": e["n"],
                "kind": e["kind"],
                "source": e["source"],
                "fingerprint": (e["fingerprint"] or "")[:16] or None,
                "compile_seconds": e["compile_seconds"],
                "hbm_total_bytes": mem.get("total_bytes"),
                "partial": e["partial"],
            })
        return out


_global = ExecutableLedger()


def get_ledger():
    """The process-wide executable ledger."""
    return _global
