"""paddle_tpu.observability — unified telemetry hub + flight recorder.

One import point for every instrumented layer::

    from paddle_tpu import observability as obs

    obs.inc("executor.cache_hit")
    obs.observe("checkpoint.save_seconds", dt)
    obs.set_gauge("reader.queue_depth", q.qsize())
    obs.event("retry", source="guard", attempt=2)
    with obs.span("executor.run"):
        ...

Every helper here is gated on the live ``PADDLE_TPU_TELEMETRY`` mode
(``off`` | ``on`` | ``trace``): with ``off`` each call is a single
env-flag check and an early return — no allocation, no lock — so the
instrumentation stays compiled into the hot paths permanently.

Read side: ``snapshot()`` (nested dict), ``render_prom()`` (Prometheus
text), single-metric probes ``counter(name)`` / ``gauge(name)`` /
``histogram(name)``, ``get_recorder().dump_jsonl(path)`` (the event
ring), and crash dumps written automatically on uncaught exceptions
(see ``recorder.install_excepthook``). ``reset()`` clears the hub AND
the ring — tests use it to scope assertions to a scripted session.

Well-known executor fast-path metrics (PR 4):

- ``compile_cache.disk_hit`` / ``disk_miss`` / ``corrupt`` / ``store``
  / ``store_error`` counters and ``compile_cache.deserialize_seconds``
  / ``serialize_seconds`` histograms — the persistent AOT compile
  cache's disk tier (``fluid.compile_cache``).
- ``executor.overlap_ratio`` gauge — fraction of feed-staging seconds
  that overlapped an in-flight step in the last pipelined run
  (``Executor.run_pipelined``); ``span.executor.stage_feed.seconds`` /
  ``span.reader.stage_feed.seconds`` histograms time the staging
  itself.

Well-known serving metrics (PR 5, ``paddle_tpu.serving``):

- ``serving.queue_wait_seconds`` / ``serving.batch_size`` /
  ``serving.batch_rows`` / ``serving.padding_waste`` /
  ``serving.request_seconds`` histograms — per coalesced micro-batch
  and per request through the ServingEngine.
- ``serving.shed`` / ``serving.deadline_miss`` counters — admission
  control rejects; every reject also records a flight-recorder event
  (kinds ``shed`` / ``deadline_miss``, source ``serving``).
- ``serving.queue_depth.<model>`` gauge, and
  ``predictor.compile_seconds`` histogram with ``compile_start`` /
  ``compile_done`` events (source ``predictor``) — absent entirely on
  a compile-cache warm start.

Well-known serving-fleet metrics (PR 7, ``serving.router``):

- ``serving.replicas_live`` gauge — replicas currently taking traffic;
  ``serving.rollout_state`` gauge — 0 idle / 1 rolling / 2 rolled-back.
- ``serving.failovers`` / ``serving.router_retry`` /
  ``serving.replica_dead`` counters — requests moved to a survivor,
  all-shed backoff rounds, and replicas declared dead (each with a
  flight-recorder event, source ``serving``).
- ``serving.dispatch_seconds`` histogram — router pick-and-submit cost;
  ``elastic.store_scan_cached`` / ``store_scan_full`` counters and the
  ``elastic.store_scan_seconds`` histogram expose the FileStore
  mtime-cache hit rate replica health polling rides on.

Well-known analysis metrics (PR 6, ``paddle_tpu.analysis``):

- ``analysis.verify_seconds`` histogram — cost of the static verify
  gate on each first compile of a signature (executor + predictor);
  ``analysis.findings`` counter — errors+warnings those gates reported.
- ``analysis_report`` events (sources ``executor`` / ``predictor``)
  carry the per-program finding summary; ``analysis_failed`` means the
  analyzer itself crashed (the run proceeds — the gate never blocks on
  its own bugs). ``GuardedExecutor`` retry events gain ``analysis`` /
  ``analysis_findings`` fields from the post-failure full analysis.
- ``scope_race`` events (source ``sanitizer``) — cross-thread Scope
  write violations when ``PADDLE_TPU_SCOPE_SANITIZER=on``.

Well-known cost-model metrics (PR 8, ``analysis.costs`` / ``.memory``):

- ``analysis.predicted_peak_hbm`` gauge — the liveness estimate of the
  peak live-set (bytes) for the last program the executor/predictor
  gate admitted; ``analysis.predicted_mfu`` gauge — the roofline MFU
  prediction (set at ``PADDLE_TPU_ANALYSIS=full``, when the cost pass
  runs). A predicted-OOM program raises before ``compile_start``, so
  these gauges always describe a program that was allowed to compile.
- ``serving.predicted_peak_hbm.<model>`` gauge — worst bucket-ladder
  peak the admission check priced at ``ServingEngine.warmup()``;
  ``bucket_rejected`` events (source ``serving``) record ladders that
  exceeded the HBM budget (the warmup raises before any compile).

Well-known decode-serving metrics (PR 9, ``serving.decode``):

- ``serving.decode.slot_utilization.<engine>`` gauge — live slots /
  total slots after each dispatch iteration (continuous batching keeps
  this near 1.0 under load); ``serving.decode.cache_occupancy.<engine>``
  gauge — filled KV rows / (slots × cache_len).
- ``serving.decode.prefill_seconds`` / ``step_seconds`` /
  ``ttft_seconds`` / ``request_seconds`` histograms — the two-program
  loop's dispatch costs plus time-to-first-token and whole-request
  latency.
- ``serving.decode.tokens`` / ``requests`` / ``prefills`` / ``steps``
  / ``retired`` / ``shed`` / ``deadline_miss`` / ``cancelled``
  counters — every lifecycle edge ``stats()`` reports, mirrored into
  the hub; rejects and client disconnects also land in the flight
  recorder with ``engine="decode"``.

Well-known gradient-communication metrics (PR 10, ``parallel/comms``):

- ``comm.bytes_sent`` counter — wire bytes one gradient sync moved
  across the dp group (per step, deterministic from the bucket plan);
  ``comm.bytes_saved`` counter — bytes the quantized path avoided vs
  the fp32 ring over the same padded payload.
- ``comm.compression_ratio`` gauge — fp32 bytes / actual wire bytes of
  the last sync (1.0 on the exact path, ~3.9 at block 256);
  ``comm.overlap_ratio`` gauge — fraction of comm bytes with
  backward-overlap opportunity (0.0 with one bucket or overlap off).
- ``comm.allreduce_seconds`` histogram — the COST-MODEL-predicted comm
  leg per step (wire bytes over the profile's ICI bandwidth,
  ``PADDLE_TPU_ICI_BW`` overridable), not a measurement: inside one
  fused jitted step the per-collective time is not separable host-side.
  Absent when no device profile knows the bandwidth.
- ``collective.dispatch.grad_sync`` counter — each bucketed sync
  dispatch through the FleetGuard collective gate, alongside the
  existing per-op ``collective.dispatch.<op>`` counters.

Well-known disaggregated-serving metrics (PR 12, ``serving.disagg``):

- ``serving.disagg.prefill_live`` / ``decode_live`` gauges — replicas
  of each phase taking traffic; ``serving.disagg.decode_sessions.<rid>``
  gauge — live sessions pinned to each decode replica (the session-
  affinity placement signal).
- ``serving.disagg.sessions`` / ``migrations`` / ``failed_streams`` /
  ``replica_dead`` / ``handoffs`` counters — session lifecycle:
  ``migrations`` counts re-prefill recoveries off dead decode
  replicas, and chaos drills assert ``failed_streams`` stays 0.
- ``serving.disagg.prefill_ttft_seconds`` histogram — queue wait +
  prefill on the prefill fleet (the TTFT SLO leg);
  ``serving.disagg.per_token_seconds`` (and ``.<tenant>``) histograms
  — inter-token gaps on the decode leg (the per-token-p99 SLO leg);
  ``serving.disagg.slo_miss_ttft`` / ``slo_miss_per_token`` counters
  score them against each tenant's targets.
- ``serving.disagg.tenant_live.<tenant>`` gauge and
  ``serving.disagg.tenant_sessions`` / ``tenant_shed`` counters — the
  per-tenant quota accounting behind 429s;
  ``serving.disagg.adopt_seconds`` histogram and
  ``serving.disagg.handoff_bytes.<engine>`` gauge price the KV handoff
  itself (int8 block-scaled wire ≈ 3.9x smaller than fp32).

Well-known KV-reuse + speculation metrics (``serving.spec`` /
``serving.prefix`` / ``serving.tier``):

- ``serving.spec.accept_rate`` (and ``.<engine>``) gauges — cumulative
  accepted draft tokens / proposed, the speculation economics dial
  (tokens-per-dispatch ≈ 1 + k * accept_rate);
  ``serving.spec.round_seconds`` histogram — one draft-propose +
  block-verify round; ``serving.decode.spec_rounds`` /
  ``spec_proposed`` / ``spec_accepted`` / ``spec_fallback_steps`` /
  ``draft_step_errors`` counters (fallbacks are cache-edge demotions
  to the plain step — correctness never depends on the draft).
- ``serving.prefix.hits`` / ``misses`` / ``inserts`` / ``evictions``
  counters and ``serving.prefix.entries`` / ``bytes`` gauges — the
  prefix pool's LRU economy; ``serving.decode.prefix_full_hits`` /
  ``delta_prefills`` counters split hits into zero-dispatch adoptions
  vs suffix-only delta prefills, and
  ``serving.decode.prefill_rows_computed`` / ``prefill_rows_saved``
  counters are the redundant-prefill FLOPs ledger (saved/(saved+
  computed) is the bench lane's headline).
- ``serving.tier.hibernated`` / ``resumed`` / ``evictions`` counters
  and ``serving.tier.sessions`` / ``bytes`` gauges — hibernated
  sessions parked in host RAM (sessions-per-chip = live slots + what
  fits the tier budget); ``serving.decode.hibernated`` / ``resumed``
  count the engine-side lifecycle.

Well-known retrieval metrics (``retrieval.*``, the RetrievalEngine +
ShardedEmbeddingTable from :mod:`paddle_tpu.retrieval`):

- ``retrieval.lookup_seconds`` / ``retrieval.search_seconds``
  histograms — one coalesced dispatch through the ep-sharded gather /
  the chunked brute-force top-k; ``retrieval.batch_rows`` /
  ``retrieval.padding_waste`` histograms — rows per dispatch and the
  pad rows the query-bucket ladder added (a fat waste tail means the
  ladder's rungs don't match the arriving batch sizes).
- ``retrieval.lookups`` / ``retrieval.searches`` / ``retrieval.
  lookup_rows`` / ``retrieval.search_queries`` counters — dispatches
  and per-row/per-query volume (lookup_rows also counts direct
  ``table.lookup()`` calls outside the engine).
- the shared ``serving.queue_depth.<model>`` gauge and
  ``serving.predicted_peak_hbm.<model>`` gauge (worst query-ladder
  rung from ``check_hbm_budget``) carry the same meaning as for the
  other engine kinds, so one dashboard covers all three.

Well-known concurrency/donation metrics (PR 13,
``analysis.concurrency`` / ``analysis.dataflow``):

- ``analysis.lock_graph_edges`` gauge — distinct ``held -> acquiring``
  edges in the armed lock-order graph (``PADDLE_TPU_LOCK_SANITIZER``);
  a growing value means new lock nestings are being exercised.
- ``sanitizer.violations`` counter — every recorded violation across
  BOTH runtime sanitizers: lock-order cycles (``potential-deadlock``),
  ``blocking-under-lock``, ``thread-leak``,
  ``cross-program-donated-alias`` (a zero-copy engine capture of a var
  a training dispatch donates), and scope write races.
- ``threads.leaked`` counter — threads still alive when a component's
  ``stop()``/``close()`` called ``check_stopped`` (counted even
  disarmed; the violation record itself requires the armed sanitizer).
- ``lock_violation`` events (source ``sanitizer``) carry the check
  name, lock names, and thread names of each concurrency violation
  into the flight recorder, next to the existing ``scope_race`` events.

Well-known distributed-tracing + fleet metrics (PR 14,
``observability.distributed``):

- ``trace.spans_exported`` / ``trace.export_errors`` counters — JSONL
  span records appended to ``$PADDLE_TPU_TRACE_DIR`` (one
  ``trace-<pid>.jsonl`` per process; merge them with
  ``python -m paddle_tpu.observability trace <dir>``) and append
  failures. Tracing is opt-in per request via the
  ``TraceContext.sampled`` bit (a ``traceparent`` header or
  ``"trace": true`` in a ``:generate`` body); unsampled requests skip
  every export site.
- ``fleet.replicas`` gauge — replicas merged into the last
  ``/metrics?scope=fleet`` view; ``fleet.<name>`` counter/gauge/
  histogram families — the FleetMetrics merge of per-replica beacon
  docs (counters sum, gauges labeled ``{replica="..."}``, reservoir
  histograms merged), e.g. ``fleet.requests``, ``fleet.tokens``,
  ``fleet.queue_depth{replica="decode-1"}``.
- ``fleet.slo_burn_ttft.<tenant>`` /
  ``fleet.slo_burn_per_token.<tenant>`` gauges — SLOMonitor burn
  rates: (fraction of recent observations over the tenant's
  ``ttft_slo_ms`` / ``per_token_slo_ms`` target) / budget; 1.0 means
  the error budget is being consumed exactly at the allowed rate.
- ``span.*.seconds`` histograms gain distributed siblings: spans
  created with ``ctx=`` still observe locally but also export
  trace records whose names carry the phase
  (``serving.http.request``, ``disagg.queue`` / ``.prefill`` /
  ``.handoff`` / ``.adopt``, ``decode.token``), which the collector
  folds into per-phase breakdowns.

Well-known perf-ledger metrics (PR 15, ``observability.ledger`` /
``.perf``):

- ``ledger.registered`` counter — executables recorded in the
  process-wide :class:`ExecutableLedger` (executor step compiles,
  dataset-scan bodies, Predictor engines — serving/decode warmups
  register through the predictor with their own ``kind`` tags — and
  compile-cache disk hits); ``ledger.partial`` counter — entries
  whose executable exposed neither ``cost_analysis()`` nor
  ``memory_analysis()`` (deserialized disk artifacts, backends
  without the API); ``ledger.disk_hits`` counter — entries whose
  source was the compile-cache disk tier.
- ``ledger.entries`` gauge — entries currently held;
  ``ledger.hbm_total_bytes`` gauge — XLA's HBM total (argument +
  output + temp + generated code - aliased) of the last registered
  executable.
- ``ledger.compile_seconds`` histogram — per-registration compile
  cost (absent on disk hits); ``ledger.measured_step_seconds``
  histogram — steady-state step times attached via
  ``note_measured`` (the measured column of the drift table).
- ``executable_registered`` events (source ``ledger``) carry the
  fingerprint prefix, kind, and source of each registration into the
  flight recorder; ``FlightRecorder.crash_dump`` appends the ledger
  tail + compile-cache hit/miss counters so post-mortems show what
  was compiled and resident at death.
- Render the predicted-vs-XLA-vs-measured drift per executable with
  ``python -m paddle_tpu.observability perf <dir|snapshot.json>``
  (bench ``--telemetry-out`` files embed the ledger snapshot under
  their ``"ledger"`` key).

Well-known autopilot metrics (PR 16, ``paddle_tpu.autopilot`` — the
self-healing control loop over the ledger/SLO/planner signals above):

- ``autopilot.ticks`` counter — control-loop passes;
  ``autopilot.tick_errors`` — ticks that raised (the daemon loop
  survives and counts them); ``autopilot.actions`` — decisions minted,
  with per-outcome siblings ``autopilot.proposed`` / ``.applied`` /
  ``.verified`` / ``.rolled_back`` / ``.rejected`` /
  ``.quarantined``.
- ``autopilot.calibrations`` counter — DeviceProfile refits from the
  ledger's measured step times; ``autopilot.rollbacks`` counter —
  applied re-plans reverted after a regressing verify measurement;
  ``autopilot.journal_errors`` counter — decision-journal appends that
  could not reach disk (the in-memory ring still holds them).
- ``autopilot.mode`` gauge — 0 off / 1 propose / 2 apply, refreshed
  every tick from ``PADDLE_TPU_AUTOPILOT``;
  ``autopilot.worst_burn`` gauge — the worst per-tenant SLO burn seen
  last tick; ``autopilot.worst_drift_pct`` gauge — the worst
  |measured vs calibrated-predicted| step drift;
  ``autopilot.calibrated_peak_flops`` gauge — the effective peak of
  the latest fit.
- ``autopilot_action`` events (source ``autopilot``) carry each
  decision's kind, trigger, mode, outcome, journal seq, and incident
  trace id into the flight recorder; the same decisions land
  append-only in the ``DecisionJournal`` and as ``autopilot.detect`` /
  ``.replan`` / ``.act`` / ``.apply`` / ``.verify`` spans on the
  request timeline.

Well-known data-integrity metrics (PR 17, ``paddle_tpu.integrity``):

- ``integrity.checkpoint_manifests_written`` counter — per-tensor
  digest manifests written alongside checkpoint saves;
  ``integrity.checkpoint_verified`` — restores whose tensors all
  matched; ``integrity.checkpoint_digest_mismatch`` — tensors that
  did not (restore raises an attributed ``IntegrityError``, consensus
  restore falls back a step); ``integrity.checkpoint_manifest_corrupt``
  — manifests present but unreadable.
  ``integrity.checkpoint_digest_seconds`` / ``checkpoint_verify_seconds``
  histograms price the digest passes (<5% of the save budget).
- ``integrity.handoff_digest_mismatch`` counter — KV handoffs whose
  sealed digest failed on adopt (the stream re-prefills via the
  migration path; ``failed_streams`` stays 0).
- ``integrity.sdc_replay_ok`` / ``sdc_replay_disagree`` counters and
  ``integrity.sdc_replay_seconds`` histogram — the SDC sentinel's
  sampled step replays (1-in-``PADDLE_TPU_SDC_CHECK_EVERY``, default
  128); ``integrity.sdc_vote_confirmed`` / ``sdc_vote_inconclusive``
  — cross-replica vote outcomes; ``integrity.replicas_quarantined``
  — confirmed liars pulled from rotation by the autopilot's
  ``quarantine_replica`` action.
- ``integrity.fault_corrupt_fired`` counter — armed ``corrupt=``
  fault-arm firings; ``compile_cache.corrupt_digest`` /
  ``corrupt_deserialize`` split the existing ``compile_cache.corrupt``
  total by which check caught the entry.
- ``integrity.jsonl_dropped`` counter — torn/unparseable lines skipped
  by the shared tolerant JSONL reader (decision journal, trace
  collector); ``integrity.mailbox_doc_torn`` / ``mailbox_doc_corrupt``
  — FileStore mailbox docs dropped for a torn write vs a failing
  ``_integrity`` stamp.
- ``integrity_violation`` events name the failing check
  (``manifest`` / ``digest`` / ``done-marker`` / ``kv_handoff`` /
  ``mailbox``) and, where known, the tensor — attribution rides the
  event, not just the counter.

Well-known run-health metrics (PR 18, ``observability.runhealth``):

- ``runhealth.steps`` counter — StepSeries records taken;
  ``runhealth.loss`` / ``runhealth.grad_norm`` /
  ``runhealth.loss_scale`` / ``runhealth.step_seconds`` gauges — the
  latest recorded convergence signals.
- ``runhealth.loss_spike`` / ``grad_explosion`` / ``nonfinite_loss``
  / ``plateau`` / ``throughput_sag`` counters — streaming anomaly
  detector firings; each also lands a flight-recorder event (source
  ``runhealth``) carrying the step and the trailing-window evidence.
- ``runhealth.goodput_fraction`` gauge — productive-step seconds /
  run wall-clock at the last ``GoodputAccount.stop()``; the full
  decomposition (``productive_step`` / ``compile`` / ``data_stall``
  / ``checkpoint`` / ``retry_backoff`` / ``restart_rework``) rides
  ``TrainGuard.train()``'s summary, crash dumps, and bench
  ``--telemetry-out`` docs (under ``"runhealth"``).
- ``amp.loss_scale`` gauge / ``amp.skipped_steps`` counter — the AMP
  decorator's dynamic loss scale and in-graph overflow skips,
  published once per guarded step (``GuardedExecutor`` with
  ``amp_optimizer=``).
- ``autopilot.train_rollbacks`` counter — verified
  ``rollback_lr_cut`` actions the autopilot TRAIN leg executed on
  confirmed divergence; ``autopilot.runhealth_errors`` — detector
  polls that raised.
- Render a run-health report or an A/B comparison with
  ``python -m paddle_tpu.observability run <dir|snapshot.json> [B]``.

Corruption fault grammar (``fluid.resilience``, chaos drills)::

    site:every=N:corrupt=MODE    # or site:at=N:corrupt=MODE

    site  | save    host->disk writes: checkpoint manifests,
          |         compile-cache entries
          | load    disk->host reads of the same artifacts
          | wire    the prefill->decode KV handoff payload
          | mailbox elastic FileStore doc writes
    MODE  | bitflip flip one bit mid-payload (silent corruption)
          | truncate keep the first half (short read/write)
          | torn    drop the tail (interrupted append)

``corrupt=`` arms only those four byte-path sites; parse rejects any
other site, a missing mode, or an unknown mode. All other sites keep
their existing arms (``exception`` / ``slow=SECONDS`` / ``hang`` ...).

This package is stdlib-only (no jax/numpy imports at module level), so
crash-path and supervisor code can use it without accelerator init.
"""
from . import distributed as _distributed
from . import ledger as _ledger_mod
from . import perf as _perf_mod
from . import recorder as _recorder
from . import telemetry as _telemetry
from . import tracing as _tracing
from .distributed import (  # noqa: F401
    TRACE_DIR_ENV, TRACE_PROC_ENV, TRACE_SAMPLE_ENV, FleetMetrics,
    SLOMonitor, TraceContext, chrome_trace, collect_trace, export_span,
    phase_breakdown, process_label, read_spans, replica_metrics_doc,
    sample_request, set_process_label, trace_dir,
)
from .ledger import ExecutableLedger, get_ledger  # noqa: F401
from .perf import (  # noqa: F401
    drift_rows, drift_summary, load_snapshot, render_drift_table,
)
from . import runhealth as _runhealth_mod
from .runhealth import (  # noqa: F401
    GoodputAccount, RunHealth, StepSeries, load_run,
    render_comparison, render_health_report,
)
from .recorder import (  # noqa: F401
    CRASH_DUMP_ENV, FlightRecorder, crash_dump_path, get_recorder,
    install_excepthook,
)
from .telemetry import (  # noqa: F401
    OFF, ON, TRACE, TELEMETRY_ENV, PROM_STYLE_ENV, Histogram,
    Telemetry, get_telemetry, mode,
)
from .tracing import active_spans, current_span, span  # noqa: F401

__all__ = [
    "Telemetry", "Histogram", "FlightRecorder", "get_telemetry",
    "get_recorder", "span", "active_spans", "current_span", "mode",
    "enabled", "trace_enabled", "inc", "observe", "set_gauge", "event",
    "counter", "gauge", "histogram",
    "snapshot", "render_prom", "reset", "install_excepthook",
    "crash_dump_path", "TELEMETRY_ENV", "CRASH_DUMP_ENV",
    "OFF", "ON", "TRACE",
    "TraceContext", "TRACE_DIR_ENV", "TRACE_PROC_ENV",
    "TRACE_SAMPLE_ENV", "trace_dir", "sample_request",
    "process_label", "set_process_label", "export_span", "read_spans",
    "chrome_trace", "collect_trace", "phase_breakdown", "FleetMetrics",
    "SLOMonitor", "replica_metrics_doc", "PROM_STYLE_ENV",
    "ExecutableLedger", "get_ledger", "drift_rows", "drift_summary",
    "load_snapshot", "render_drift_table",
    "StepSeries", "GoodputAccount", "RunHealth", "load_run",
    "render_health_report", "render_comparison",
]


def enabled():
    """True unless PADDLE_TPU_TELEMETRY=off."""
    return _telemetry.mode() != OFF


def trace_enabled():
    """True only in PADDLE_TPU_TELEMETRY=trace mode."""
    return _telemetry.mode() == TRACE


# -- mode-gated write helpers (the instrumentation surface) ----------------

def inc(name, n=1):
    if _telemetry.mode() == OFF:
        return
    _telemetry._hub.inc(name, n)


def observe(name, value):
    if _telemetry.mode() == OFF:
        return
    _telemetry._hub.observe(name, value)


def set_gauge(name, value):
    if _telemetry.mode() == OFF:
        return
    _telemetry._hub.set_gauge(name, value)


def event(kind, source=None, recorder=None, count=True, **fields):
    """Record a structured event into `recorder` (the global flight
    recorder when None) and bump the ``<source>.<kind>`` counter. The
    single entry point EventLog streams route through."""
    if _telemetry.mode() == OFF:
        return None
    if count:
        _telemetry._hub.inc(
            "%s.%s" % (source, kind) if source else kind)
    rec = recorder if recorder is not None else _recorder._global
    if source is not None:
        fields.setdefault("source", source)
    return rec.record(kind, **fields)


# -- read side --------------------------------------------------------------

def counter(name):
    """Current value of one counter (0 when never bumped) — the cheap
    single-metric probe tests and bench reporting use instead of a full
    snapshot()."""
    return _telemetry._hub.counter(name)


def gauge(name):
    """Current value of one gauge, or None when never set."""
    return _telemetry._hub.gauge(name)


def histogram(name):
    """Summary dict of one histogram, or None when never observed."""
    return _telemetry._hub.histogram(name)


def snapshot():
    return _telemetry._hub.snapshot()


def render_prom(style=None):
    return _telemetry._hub.render_prom(style=style)


def reset():
    """Clear the hub, the global event ring, the executable ledger,
    and the active run-health bundle (testing / session scoping). Does
    not uninstall the excepthook."""
    _telemetry._hub.reset()
    _recorder._global.clear()
    _ledger_mod._global.clear()
    _runhealth_mod.reset()
