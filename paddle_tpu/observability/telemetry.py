"""Process-wide telemetry hub: counters, gauges, histograms.

The hub is the single metrics blackboard every layer of the stack
reports through — the executor's compile-cache hits, the resilience
layer's retries, the elastic fleet's collective waits, the reader's
queue depth. Metric names are dot-separated lowercase paths
(``executor.cache_hit``, ``checkpoint.save_seconds``); ``snapshot()``
returns them as a nested dict and ``render_prom()`` as Prometheus
text exposition (dots become underscores, ``paddle_tpu_`` prefix).

The ``PADDLE_TPU_TELEMETRY`` env switch gates EVERY write:

    off    instrumentation sites are no-ops (one env-flag check, no
           allocation) — cheap enough to leave compiled in
    on     counters/gauges/histograms + flight-recorder events (default)
    trace  additionally records span start/stop events into the flight
           recorder and blocks on device outputs so the executor's
           device-compute phase measures true chip time

The switch is read live (one ``os.environ`` lookup per check), so a
test or a driver can flip it without restarting the process. This
module is stdlib-only — the bench supervisor and crash-path code can
import it without pulling in jax.
"""
import collections
import math
import os
import re
import threading

__all__ = [
    "Telemetry", "Histogram", "get_telemetry", "mode", "TELEMETRY_ENV",
    "OFF", "ON", "TRACE",
]

TELEMETRY_ENV = "PADDLE_TPU_TELEMETRY"

OFF, ON, TRACE = 0, 1, 2

_OFF_VALUES = frozenset({"off", "0", "false", "no", "none", "disabled"})


# last (raw env value, parsed mode): the env is still read LIVE on
# every call — only the string parse is cached, keyed on the exact raw
# value, so flips (including by monkeypatch) always take effect
_mode_cache = ("", ON)


def mode():
    """Resolve the live telemetry mode from the environment. Unset (and
    any unrecognised value) means ``on``."""
    global _mode_cache
    v = os.environ.get(TELEMETRY_ENV)
    if v is None:
        return ON
    cached = _mode_cache
    if v == cached[0]:
        return cached[1]
    s = v.strip().lower()
    m = OFF if s in _OFF_VALUES else TRACE if s == "trace" else ON
    _mode_cache = (v, m)
    return m


class Histogram:
    """Streaming count/sum/min/max plus a bounded reservoir of the most
    recent observations (deterministic — no sampling randomness) for
    percentile estimates. Memory is bounded by ``cap`` regardless of
    how many values are observed."""

    __slots__ = ("count", "sum", "min", "max", "_reservoir")

    def __init__(self, cap=512):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._reservoir = collections.deque(maxlen=int(cap))

    def observe(self, value):
        v = float(value)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self._reservoir.append(v)

    def quantile(self, q):
        vals = sorted(self._reservoir)
        if not vals:
            return None
        idx = min(len(vals) - 1, int(q * len(vals)))
        return vals[idx]

    def summary(self):
        if not self.count:
            return {"count": 0, "sum": 0.0}
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.sum / self.count,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name):
    return "paddle_tpu_" + _PROM_BAD.sub("_", name)


class Telemetry:
    """The hub. Thread-safe; all methods are cheap enough to call from
    hot paths once the mode gate (handled by the package-level helpers
    in ``paddle_tpu.observability``) has passed."""

    def __init__(self, reservoir_cap=512):
        self._lock = threading.Lock()
        self._reservoir_cap = int(reservoir_cap)
        self._counters = collections.Counter()
        self._gauges = {}
        self._hists = {}

    # -- writes ----------------------------------------------------------
    def inc(self, name, n=1):
        with self._lock:
            self._counters[name] += n

    def set_gauge(self, name, value):
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name, value):
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = Histogram(self._reservoir_cap)
            hist.observe(value)

    # -- reads -----------------------------------------------------------
    def counter(self, name):
        with self._lock:
            return self._counters.get(name, 0)

    def gauge(self, name):
        with self._lock:
            return self._gauges.get(name)

    def histogram(self, name):
        """The histogram summary dict for `name`, or None."""
        with self._lock:
            hist = self._hists.get(name)
            return hist.summary() if hist is not None else None

    def snapshot(self):
        """Nested dict of everything the hub holds right now."""
        with self._lock:
            return {
                "mode": {OFF: "off", ON: "on", TRACE: "trace"}[mode()],
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    name: hist.summary()
                    for name, hist in self._hists.items()
                },
            }

    def render_prom(self):
        """Prometheus text exposition (counters, gauges, and histogram
        summaries with quantile labels)."""
        lines = []
        with self._lock:
            for name in sorted(self._counters):
                pn = _prom_name(name)
                lines.append("# TYPE %s counter" % pn)
                lines.append("%s %d" % (pn, self._counters[name]))
            for name in sorted(self._gauges):
                pn = _prom_name(name)
                lines.append("# TYPE %s gauge" % pn)
                lines.append("%s %.9g" % (pn, self._gauges[name]))
            for name in sorted(self._hists):
                pn = _prom_name(name)
                hist = self._hists[name]
                lines.append("# TYPE %s summary" % pn)
                for q in (0.5, 0.9, 0.99):
                    val = hist.quantile(q)
                    if val is not None:
                        lines.append(
                            '%s{quantile="%s"} %.9g' % (pn, q, val))
                lines.append("%s_sum %.9g" % (pn, hist.sum))
                lines.append("%s_count %d" % (pn, hist.count))
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


_hub = Telemetry()


def get_telemetry():
    """The process-wide hub singleton."""
    return _hub
