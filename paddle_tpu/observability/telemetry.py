"""Process-wide telemetry hub: counters, gauges, histograms.

The hub is the single metrics blackboard every layer of the stack
reports through — the executor's compile-cache hits, the resilience
layer's retries, the elastic fleet's collective waits, the reader's
queue depth. Metric names are dot-separated lowercase paths
(``executor.cache_hit``, ``checkpoint.save_seconds``); ``snapshot()``
returns them as a nested dict and ``render_prom()`` as Prometheus
text exposition (dots become underscores, ``paddle_tpu_`` prefix).

The ``PADDLE_TPU_TELEMETRY`` env switch gates EVERY write:

    off    instrumentation sites are no-ops (one env-flag check, no
           allocation) — cheap enough to leave compiled in
    on     counters/gauges/histograms + flight-recorder events (default)
    trace  additionally records span start/stop events into the flight
           recorder and blocks on device outputs so the executor's
           device-compute phase measures true chip time

The switch is read live (one ``os.environ`` lookup per check), so a
test or a driver can flip it without restarting the process. This
module is stdlib-only — the bench supervisor and crash-path code can
import it without pulling in jax.
"""
import bisect
import collections
import math
import os
import re
import threading

__all__ = [
    "Telemetry", "Histogram", "get_telemetry", "mode", "TELEMETRY_ENV",
    "OFF", "ON", "TRACE", "PROM_STYLE_ENV", "DEFAULT_BUCKETS",
]

TELEMETRY_ENV = "PADDLE_TPU_TELEMETRY"

# ``render_prom`` histogram style: "histogram" (default) emits proper
# Prometheus ``_bucket{le=...}`` exposition; "summary" restores the
# pre-PR-14 quantile lines for lanes/dashboards that grep them
PROM_STYLE_ENV = "PADDLE_TPU_PROM_STYLE"

OFF, ON, TRACE = 0, 1, 2

_OFF_VALUES = frozenset({"off", "0", "false", "no", "none", "disabled"})


# last (raw env value, parsed mode): the env is still read LIVE on
# every call — only the string parse is cached, keyed on the exact raw
# value, so flips (including by monkeypatch) always take effect
_mode_cache = ("", ON)


def mode():
    """Resolve the live telemetry mode from the environment. Unset (and
    any unrecognised value) means ``on``."""
    global _mode_cache
    v = os.environ.get(TELEMETRY_ENV)
    if v is None:
        return ON
    cached = _mode_cache
    if v == cached[0]:
        return cached[1]
    s = v.strip().lower()
    m = OFF if s in _OFF_VALUES else TRACE if s == "trace" else ON
    _mode_cache = (v, m)
    return m


# log-spaced ``le`` bounds tuned for latencies in seconds (0.5 ms to
# 60 s); the final implicit bucket is +Inf. Streaming bucket counts are
# exact (unlike the bounded reservoir) so the Prometheus exposition
# survives arbitrarily long runs.
DEFAULT_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Histogram:
    """Streaming count/sum/min/max plus exact cumulative bucket counts
    (Prometheus ``le`` semantics over :data:`DEFAULT_BUCKETS`) plus a
    bounded reservoir of the most recent observations (deterministic —
    no sampling randomness) for percentile estimates. Memory is bounded
    by ``cap`` regardless of how many values are observed."""

    __slots__ = ("count", "sum", "min", "max", "_reservoir", "_buckets")

    def __init__(self, cap=512):
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._reservoir = collections.deque(maxlen=int(cap))
        # one count per bound in DEFAULT_BUCKETS, plus the +Inf overflow
        self._buckets = [0] * (len(DEFAULT_BUCKETS) + 1)

    def observe(self, value):
        v = float(value)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self._reservoir.append(v)
        self._buckets[bisect.bisect_left(DEFAULT_BUCKETS, v)] += 1

    def quantile(self, q):
        vals = sorted(self._reservoir)
        if not vals:
            return None
        idx = min(len(vals) - 1, int(q * len(vals)))
        return vals[idx]

    def summary(self):
        if not self.count:
            return {"count": 0, "sum": 0.0}
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.sum / self.count,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
        }

    # -- federation ------------------------------------------------------
    def export(self, reservoir_cap=64):
        """JSON-safe doc a replica publishes for fleet merging: exact
        count/sum/buckets plus the tail of the reservoir (capped so a
        heartbeat beacon stays small)."""
        tail = list(self._reservoir)
        if reservoir_cap is not None:
            tail = tail[-int(reservoir_cap):]
        doc = {"count": self.count, "sum": self.sum,
               "buckets": list(self._buckets), "reservoir": tail}
        if self.count:
            doc["min"] = self.min
            doc["max"] = self.max
        return doc

    @classmethod
    def from_docs(cls, docs, cap=512):
        """Merge :meth:`export` docs from several replicas into one
        histogram: counts/sums/buckets add, reservoirs concatenate
        (bounded by ``cap``), min/max widen."""
        merged = cls(cap=cap)
        for doc in docs:
            if not doc:
                continue
            merged.count += int(doc.get("count", 0))
            merged.sum += float(doc.get("sum", 0.0))
            mn, mx = doc.get("min"), doc.get("max")
            if mn is not None and mn < merged.min:
                merged.min = mn
            if mx is not None and mx > merged.max:
                merged.max = mx
            for i, n in enumerate(doc.get("buckets", ())):
                if i < len(merged._buckets):
                    merged._buckets[i] += int(n)
            merged._reservoir.extend(doc.get("reservoir", ()))
        return merged


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name):
    return "paddle_tpu_" + _PROM_BAD.sub("_", name)


class Telemetry:
    """The hub. Thread-safe; all methods are cheap enough to call from
    hot paths once the mode gate (handled by the package-level helpers
    in ``paddle_tpu.observability``) has passed."""

    def __init__(self, reservoir_cap=512):
        self._lock = threading.Lock()
        self._reservoir_cap = int(reservoir_cap)
        self._counters = collections.Counter()
        self._gauges = {}
        self._hists = {}

    # -- writes ----------------------------------------------------------
    def inc(self, name, n=1):
        with self._lock:
            self._counters[name] += n

    def set_gauge(self, name, value):
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name, value):
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = Histogram(self._reservoir_cap)
            hist.observe(value)

    # -- reads -----------------------------------------------------------
    def counter(self, name):
        with self._lock:
            return self._counters.get(name, 0)

    def gauge(self, name):
        with self._lock:
            return self._gauges.get(name)

    def histogram(self, name):
        """The histogram summary dict for `name`, or None."""
        with self._lock:
            hist = self._hists.get(name)
            return hist.summary() if hist is not None else None

    def reservoir(self, name):
        """The raw reservoir values (most recent observations) for
        `name`, or None. Used by the SLO monitor to score observed
        latencies against tenant targets."""
        with self._lock:
            hist = self._hists.get(name)
            return list(hist._reservoir) if hist is not None else None

    def snapshot(self):
        """Nested dict of everything the hub holds right now."""
        with self._lock:
            return {
                "mode": {OFF: "off", ON: "on", TRACE: "trace"}[mode()],
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    name: hist.summary()
                    for name, hist in self._hists.items()
                },
            }

    def render_prom(self, style=None):
        """Prometheus text exposition. Histograms render as proper
        ``_bucket{le=...}``/``_sum``/``_count`` exposition by default;
        ``style="summary"`` (or ``PADDLE_TPU_PROM_STYLE=summary``)
        restores the pre-PR-14 quantile lines under the same metric
        names for lanes that grep them."""
        if style is None:
            style = (os.environ.get(PROM_STYLE_ENV, "")
                     .strip().lower() or "histogram")
        lines = []
        with self._lock:
            for name in sorted(self._counters):
                pn = _prom_name(name)
                lines.append("# TYPE %s counter" % pn)
                lines.append("%s %d" % (pn, self._counters[name]))
            for name in sorted(self._gauges):
                pn = _prom_name(name)
                lines.append("# TYPE %s gauge" % pn)
                lines.append("%s %.9g" % (pn, self._gauges[name]))
            for name in sorted(self._hists):
                pn = _prom_name(name)
                hist = self._hists[name]
                if style == "summary":
                    lines.append("# TYPE %s summary" % pn)
                    for q in (0.5, 0.9, 0.99):
                        val = hist.quantile(q)
                        if val is not None:
                            lines.append(
                                '%s{quantile="%s"} %.9g' % (pn, q, val))
                else:
                    lines.append("# TYPE %s histogram" % pn)
                    cum = 0
                    for bound, n in zip(DEFAULT_BUCKETS, hist._buckets):
                        cum += n
                        lines.append('%s_bucket{le="%.12g"} %d'
                                     % (pn, bound, cum))
                    lines.append('%s_bucket{le="+Inf"} %d'
                                 % (pn, hist.count))
                lines.append("%s_sum %.9g" % (pn, hist.sum))
                lines.append("%s_count %d" % (pn, hist.count))
        return "\n".join(lines) + ("\n" if lines else "")

    def federation_doc(self, reservoir_cap=64, prefix=None):
        """The per-process payload a replica publishes for fleet
        merging (heartbeat ``extra=`` or the elastic FileStore):
        counters/gauges verbatim, histograms as :meth:`Histogram.export`
        docs. ``prefix`` filters metric names (e.g. ``"serving."``) so
        a beacon stays small."""
        def keep(name):
            return prefix is None or name.startswith(prefix)
        with self._lock:
            return {
                "counters": {k: v for k, v in self._counters.items()
                             if keep(k)},
                "gauges": {k: v for k, v in self._gauges.items()
                           if keep(k)},
                "histograms": {
                    k: h.export(reservoir_cap=reservoir_cap)
                    for k, h in self._hists.items() if keep(k)
                },
            }

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


_hub = Telemetry()


def get_telemetry():
    """The process-wide hub singleton."""
    return _hub
