"""Distributed request tracing + fleet metrics federation.

PR 3's spans, hub, and flight recorder are process-local; PRs 7-12
turned serving into a multi-process fleet (router, prefill replicas,
decode replicas, StoreReplica workers), so a single ``:generate``
request crosses 3+ processes and no single artifact shows where its
TTFT went. This module closes that gap in three pieces:

**Trace-context propagation.** :class:`TraceContext` is a
W3C-traceparent-style triple (32-hex ``trace_id``, 16-hex ``span_id``,
sampling bit) injected at the HTTP frontend (``traceparent`` header or
``"trace": true`` in the body), carried through router dispatch, the
``KVHandoff`` wire doc, StoreReplica req-mailboxes, and DecodeEngine
slot state. The sampling bit makes tracing opt-in per request: an
unsampled context costs one attribute store per hop, and the
``PADDLE_TPU_TELEMETRY=off`` path is unchanged.

**Trace export + collection.** Every process appends finished spans as
JSONL under ``$PADDLE_TPU_TRACE_DIR`` (one ``trace-<pid>.jsonl`` per
process). ``python -m paddle_tpu.observability trace <dir>`` merges
them into a Perfetto-loadable Chrome trace-event file: one track per
logical process (router / prefill-N / decode-N / worker), flow arrows
(``ph:"s"``/``"f"``) wherever a child span ran on a different track
than its parent (submit -> prefill -> handoff -> adopt -> first token),
and ``predicted_ms`` vs ``measured_ms`` args on spans whose site
attached a cost-model prediction (``analysis/costs.py``), so model
error is visible per request.

**Fleet metrics federation.** Replicas publish
:meth:`Telemetry.federation_doc` snapshots via heartbeat ``extra=``
(in-process) or the elastic FileStore (workers);
:class:`FleetMetrics` merges them — counters sum, gauges keep a
``{replica="..."}`` label, histogram reservoirs/buckets merge — and
renders behind ``/metrics?scope=fleet``. :class:`SLOMonitor` scores
observed TTFT / per-token latencies against ``TenantSpec`` targets and
publishes per-tenant burn-rate gauges (``fleet.slo_burn_*``) the
router can act on.

Stdlib-only at module level (crash-path and bench-supervisor safe).
"""
import json
import os
import threading
import time

from . import telemetry as _t

__all__ = [
    "TraceContext", "TRACE_DIR_ENV", "TRACE_PROC_ENV",
    "TRACE_SAMPLE_ENV", "sample_request",
    "trace_dir", "process_label", "set_process_label",
    "export_span", "read_spans", "chrome_trace", "collect_trace",
    "phase_breakdown", "FleetMetrics", "SLOMonitor",
]

# when set, sampled spans append JSONL records to this directory
TRACE_DIR_ENV = "PADDLE_TPU_TRACE_DIR"
# logical process label for this process's trace track (falls back to
# a label set via set_process_label(), then to "pid<pid>")
TRACE_PROC_ENV = "PADDLE_TPU_TRACE_PROC"
# fraction of frontend requests (without a traceparent of their own)
# to trace, e.g. 1.0 for everything, 0.01 for one in a hundred
TRACE_SAMPLE_ENV = "PADDLE_TPU_TRACE_SAMPLE"

_W3C_VERSION = "00"


class TraceContext:
    """W3C-traceparent-style trace context.

    ``trace_id`` names the whole request timeline (32 hex chars),
    ``span_id`` the span the next hop should parent to (16 hex), and
    ``sampled`` is the per-request opt-in bit. ``parent`` is the local
    parent span id (not propagated on the wire — the receiving side's
    parent IS ``span_id``)."""

    __slots__ = ("trace_id", "span_id", "sampled", "parent")

    def __init__(self, trace_id, span_id, sampled=True, parent=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = bool(sampled)
        self.parent = parent

    @classmethod
    def new(cls, sampled=True):
        return cls(os.urandom(16).hex(), os.urandom(8).hex(), sampled)

    def child(self):
        """A new span id under the same trace, parented to this one."""
        return TraceContext(self.trace_id, os.urandom(8).hex(),
                            self.sampled, parent=self.span_id)

    # -- HTTP header form ------------------------------------------------
    def to_header(self):
        return "%s-%s-%s-%02x" % (_W3C_VERSION, self.trace_id,
                                  self.span_id, 1 if self.sampled else 0)

    @classmethod
    def from_header(cls, header):
        """Parse a ``traceparent`` header; None on anything malformed
        (a bad header must never fail the request)."""
        if not header or not isinstance(header, str):
            return None
        parts = header.strip().split("-")
        if len(parts) != 4:
            return None
        _ver, trace_id, span_id, flags = parts
        if len(trace_id) != 32 or len(span_id) != 16:
            return None
        try:
            int(trace_id, 16), int(span_id, 16)
            sampled = bool(int(flags, 16) & 1)
        except ValueError:
            return None
        return cls(trace_id, span_id, sampled)

    # -- wire-doc form (KVHandoff, StoreReplica mailboxes) --------------
    def to_doc(self):
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "sampled": self.sampled}

    @classmethod
    def from_doc(cls, doc):
        if not isinstance(doc, dict):
            return None
        trace_id = doc.get("trace_id")
        span_id = doc.get("span_id")
        if not trace_id or not span_id:
            return None
        return cls(str(trace_id), str(span_id),
                   bool(doc.get("sampled", True)))

    def __repr__(self):
        return ("TraceContext(%s, %s, sampled=%s)"
                % (self.trace_id[:8], self.span_id[:8], self.sampled))


# -- span export ---------------------------------------------------------

_proc_label = None


def set_process_label(label):
    """Name this process's trace track (e.g. ``decode-1``). Engines
    running in one OS process each pass per-span ``proc=`` fields
    instead; this sets the default for spans that don't."""
    global _proc_label
    _proc_label = str(label) if label else None


def process_label():
    return (os.environ.get(TRACE_PROC_ENV) or _proc_label
            or "pid%d" % os.getpid())


def trace_dir():
    """The live span-export directory, or None (export disabled)."""
    return os.environ.get(TRACE_DIR_ENV) or None


_writer_lock = threading.Lock()
_writer = None  # (dir, pid, open file) — re-opened after fork


def _writer_file(d):
    global _writer
    pid = os.getpid()
    w = _writer
    if w is not None and w[0] == d and w[1] == pid:
        return w[2]
    if w is not None:
        try:
            w[2].close()
        except OSError:
            pass
    try:
        os.makedirs(d, exist_ok=True)
        f = open(os.path.join(d, "trace-%d.jsonl" % pid), "a",
                 encoding="utf-8")
    except OSError:
        _writer = None
        return None
    _writer = (d, pid, f)
    return f


_sample_lock = threading.Lock()
_sample_n = 0


def sample_request():
    """Deterministic stride sampler over ``$PADDLE_TPU_TRACE_SAMPLE``
    (the fraction of frontend requests to trace): returns a fresh
    sampled :class:`TraceContext` for admitted requests, None
    otherwise. Requires a trace dir — sampling with no export sink
    would pay tracing cost for nothing. The stride is deterministic
    (every ``1/rate``-th request), not random, so lanes and tests get
    reproducible trace counts."""
    global _sample_n
    if trace_dir() is None:
        return None
    try:
        rate = float(os.environ.get(TRACE_SAMPLE_ENV) or 0.0)
    except ValueError:
        return None
    if rate <= 0.0:
        return None
    rate = min(rate, 1.0)
    with _sample_lock:
        n = _sample_n
        _sample_n += 1
    if rate < 1.0 and int((n + 1) * rate) == int(n * rate):
        return None
    return TraceContext.new()


def export_span(name, ctx, wall0, dur, fields=None):
    """Append one finished span to this process's JSONL trace file.

    No-op unless ``$PADDLE_TPU_TRACE_DIR`` is set and ``ctx`` is a
    sampled context — callers on hot paths gate on the sampling bit
    before measuring, so the unsampled cost is one ``if``."""
    d = trace_dir()
    if d is None or ctx is None or not ctx.sampled:
        return False
    fields = {k: v for k, v in (fields or {}).items() if v is not None}
    proc = fields.pop("proc", None) or process_label()
    rec = {
        "trace": ctx.trace_id,
        "span": ctx.span_id,
        "parent": ctx.parent,
        "name": name,
        "proc": proc,
        "pid": os.getpid(),
        "tid": threading.current_thread().name,
        "t0": wall0,
        "dur": dur,
    }
    if fields:
        rec["args"] = fields
    line = json.dumps(rec, default=str)
    with _writer_lock:
        f = _writer_file(d)
        if f is None:
            return False
        try:
            f.write(line + "\n")
            f.flush()
        except OSError:
            if _t.mode() != _t.OFF:
                _t.get_telemetry().inc("trace.export_errors")
            return False
    if _t.mode() != _t.OFF:
        _t.get_telemetry().inc("trace.spans_exported")
    return True


# -- collector ------------------------------------------------------------

def read_spans(directory):
    """All span records under `directory` (every ``trace-*.jsonl``),
    skipping unparseable lines via the shared tolerant reader (a
    process killed mid-write leaves a torn tail — that must not sink
    the whole merge; skipped lines bump ``integrity.jsonl_dropped``)."""
    from ..integrity import jsonl as _jsonl

    spans = []
    dropped = 0
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return spans
    for fn in names:
        if not (fn.startswith("trace-") and fn.endswith(".jsonl")):
            continue
        records, bad = _jsonl.read_jsonl(os.path.join(directory, fn))
        dropped += bad
        spans.extend(r for r in records
                     if isinstance(r, dict) and "span" in r)
    if dropped:
        from . import inc as _inc

        _inc("integrity.jsonl_dropped", dropped)
    return spans


def _flow_id(trace, parent, span):
    # stable positive 31-bit id for a parent->child flow binding
    return hash((trace, parent, span)) & 0x7FFFFFFF


def chrome_trace(spans, trace_id=None):
    """Merge span records into a Chrome trace-event document
    (Perfetto-loadable): one synthetic pid per logical process track,
    one tid per thread, ``ph:"X"`` complete events, and ``ph:"s"``/
    ``"f"`` flow arrows wherever a span's parent ran on a different
    track. Spans carrying a ``predicted_s`` arg gain ``predicted_ms``
    vs ``measured_ms`` plus the cost-model error."""
    if trace_id is not None:
        spans = [s for s in spans if s.get("trace") == trace_id]
    spans = sorted(spans, key=lambda s: s.get("t0", 0.0))
    procs, tids = {}, {}
    events = []
    by_span = {}
    for s in spans:
        by_span[s.get("span")] = s

    def _pid(proc):
        if proc not in procs:
            procs[proc] = len(procs) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": procs[proc], "tid": 0,
                           "args": {"name": proc}})
        return procs[proc]

    def _tid(pid, tname):
        key = (pid, tname)
        if key not in tids:
            tids[key] = len([k for k in tids if k[0] == pid]) + 1
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tids[key],
                           "args": {"name": tname}})
        return tids[key]

    flows = 0
    for s in spans:
        proc = str(s.get("proc", "?"))
        pid = _pid(proc)
        tid = _tid(pid, str(s.get("tid", "main")))
        args = dict(s.get("args") or {})
        args["trace_id"] = s.get("trace")
        args["span_id"] = s.get("span")
        pred = args.get("predicted_s")
        if isinstance(pred, (int, float)):
            measured = float(s.get("dur", 0.0))
            args["predicted_ms"] = round(pred * 1e3, 3)
            args["measured_ms"] = round(measured * 1e3, 3)
            if pred > 0:
                args["cost_model_error_pct"] = round(
                    (measured - pred) / pred * 100.0, 1)
        ts = float(s.get("t0", 0.0)) * 1e6
        dur = max(float(s.get("dur", 0.0)) * 1e6, 0.001)
        events.append({"ph": "X", "name": str(s.get("name", "span")),
                       "cat": "span", "pid": pid, "tid": tid,
                       "ts": ts, "dur": dur, "args": args})
        parent = by_span.get(s.get("parent"))
        if parent is not None and parent.get("proc") != s.get("proc"):
            fid = _flow_id(s.get("trace"), parent.get("span"),
                           s.get("span"))
            ppid = _pid(str(parent.get("proc", "?")))
            ptid = _tid(ppid, str(parent.get("tid", "main")))
            pts = (float(parent.get("t0", 0.0))
                   + float(parent.get("dur", 0.0))) * 1e6
            events.append({"ph": "s", "name": "request_flow",
                           "cat": "flow", "id": fid, "pid": ppid,
                           "tid": ptid, "ts": min(pts, ts)})
            events.append({"ph": "f", "bp": "e", "name": "request_flow",
                           "cat": "flow", "id": fid, "pid": pid,
                           "tid": tid, "ts": ts})
            flows += 1
    traces = sorted({s.get("trace") for s in spans if s.get("trace")})
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "spans": len(spans),
            "flows": flows,
            "processes": sorted(procs),
            "traces": traces,
        },
    }


def collect_trace(directory, out=None, trace_id=None):
    """Read every per-process JSONL under `directory`, merge into one
    Chrome trace doc, optionally write it to `out` (atomic)."""
    doc = chrome_trace(read_spans(directory), trace_id=trace_id)
    if out:
        tmp = "%s.tmp.%d" % (out, os.getpid())
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, out)
    return doc


# request phases the bench banks per-phase latency for, in timeline
# order; keys match the span names the serving stack emits
PHASES = ("queue", "prefill", "handoff", "adopt", "decode")


def phase_breakdown(spans, trace_id=None):
    """{phase: {count, total_s, mean_s, max_s}} across span records,
    classifying spans whose name ends with a known phase suffix. The
    bench uses this to bank queue/prefill/handoff/adopt/decode
    latencies instead of only end-to-end TTFT."""
    if trace_id is not None:
        spans = [s for s in spans if s.get("trace") == trace_id]
    acc = {}
    for s in spans:
        name = str(s.get("name", ""))
        leaf = name.rsplit(".", 1)[-1]
        phase = leaf if leaf in PHASES else None
        if phase is None and leaf == "token":
            phase = "decode"
        if phase is None:
            continue
        d = float(s.get("dur", 0.0))
        st = acc.setdefault(phase, {"count": 0, "total_s": 0.0,
                                    "max_s": 0.0})
        st["count"] += 1
        st["total_s"] += d
        if d > st["max_s"]:
            st["max_s"] = d
    for st in acc.values():
        st["mean_s"] = st["total_s"] / st["count"]
    return acc


# -- fleet metrics federation --------------------------------------------

class FleetMetrics:
    """Merge per-replica metric docs into one fleet view.

    Replicas publish ``{"counters": ..., "gauges": ...,
    "histograms": ...}`` docs (:meth:`Telemetry.federation_doc` for
    worker processes; engine ``stats()``-derived docs for in-process
    replicas) on their heartbeat beacons. Merging: counters sum,
    gauges keep a per-replica label, histogram docs merge via
    :meth:`Histogram.from_docs`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._docs = {}  # replica label -> metrics doc

    def ingest(self, replica, doc):
        if not isinstance(doc, dict):
            return
        with self._lock:
            self._docs[str(replica)] = doc

    def ingest_beacons(self, table, key="metrics", prune=True):
        """Pull metric docs off a heartbeat ``table()`` snapshot —
        {worker: beacon} — where each beacon may carry a ``metrics``
        extra field. The table is the authoritative member set: with
        ``prune`` (the default) docs for replicas no longer in it are
        dropped, so removed/parked replicas stop emitting stale
        ``{replica=...}``-labeled gauges on ``/metrics``. A member
        whose beacon carries no metrics doc keeps its last one."""
        table = table or {}
        n = 0
        for worker, beacon in table.items():
            doc = beacon.get(key) if isinstance(beacon, dict) else None
            if doc:
                self.ingest(worker, doc)
                n += 1
        if prune:
            self.prune(table)
        return n

    def prune(self, members):
        """Drop docs whose replica label is not in ``members`` (any
        iterable of labels; matching uses the same ``str()`` form
        :meth:`ingest` stores under). Returns the dropped labels."""
        live = {str(m) for m in members}
        with self._lock:
            stale = [r for r in self._docs if r not in live]
            for r in stale:
                del self._docs[r]
        return stale

    def replicas(self):
        with self._lock:
            return sorted(self._docs)

    def merged(self):
        """One fleet-wide snapshot: summed counters, per-replica
        gauges, merged histogram summaries."""
        with self._lock:
            docs = dict(self._docs)
        counters = {}
        gauges = {}
        hist_docs = {}
        for replica in sorted(docs):
            doc = docs[replica]
            for k, v in (doc.get("counters") or {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    counters[k] = counters.get(k, 0) + v
            for k, v in (doc.get("gauges") or {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    gauges.setdefault(k, {})[replica] = v
            for k, v in (doc.get("histograms") or {}).items():
                hist_docs.setdefault(k, []).append(v)
        hists = {k: _t.Histogram.from_docs(v) for k, v in
                 hist_docs.items()}
        return {
            "replicas": sorted(docs),
            "counters": counters,
            "gauges": gauges,
            "histograms": {k: h.summary() for k, h in hists.items()},
            "_hist_objs": hists,
        }

    def counter_totals(self):
        return self.merged()["counters"]

    def render_prom(self, style=None):
        """Prometheus exposition of the merged fleet view. Names are
        prefixed ``fleet.`` so they never collide with the serving
        process's own hub metrics on the same ``/metrics`` page."""
        if style is None:
            style = (os.environ.get(_t.PROM_STYLE_ENV, "")
                     .strip().lower() or "histogram")
        m = self.merged()
        lines = []
        pn = _t._prom_name("fleet.replicas")
        lines.append("# TYPE %s gauge" % pn)
        lines.append("%s %d" % (pn, len(m["replicas"])))
        for name in sorted(m["counters"]):
            pn = _t._prom_name("fleet." + name)
            lines.append("# TYPE %s counter" % pn)
            lines.append("%s %.9g" % (pn, m["counters"][name]))
        for name in sorted(m["gauges"]):
            pn = _t._prom_name("fleet." + name)
            lines.append("# TYPE %s gauge" % pn)
            for replica in sorted(m["gauges"][name]):
                lines.append('%s{replica="%s"} %.9g'
                             % (pn, replica, m["gauges"][name][replica]))
        for name in sorted(m["_hist_objs"]):
            pn = _t._prom_name("fleet." + name)
            hist = m["_hist_objs"][name]
            if style == "summary":
                lines.append("# TYPE %s summary" % pn)
                for q in (0.5, 0.9, 0.99):
                    val = hist.quantile(q)
                    if val is not None:
                        lines.append('%s{quantile="%s"} %.9g'
                                     % (pn, q, val))
            else:
                lines.append("# TYPE %s histogram" % pn)
                cum = 0
                for bound, n in zip(_t.DEFAULT_BUCKETS, hist._buckets):
                    cum += n
                    lines.append('%s_bucket{le="%.12g"} %d'
                                 % (pn, bound, cum))
                lines.append('%s_bucket{le="+Inf"} %d'
                             % (pn, hist.count))
            lines.append("%s_sum %.9g" % (pn, hist.sum))
            lines.append("%s_count %d" % (pn, hist.count))
        return "\n".join(lines) + ("\n" if lines else "")


def replica_metrics_doc(stats, queue_depth=None, extra_gauges=None):
    """Build the per-replica federation doc an in-process replica
    publishes on its beacon: the numeric scalars of ``engine.stats()``
    as counters plus live gauges. (Worker processes publish their
    whole hub via :meth:`Telemetry.federation_doc` instead.)"""
    counters = {}
    for k, v in (stats or {}).items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        counters[str(k)] = v
    gauges = {}
    if queue_depth is not None:
        gauges["queue_depth"] = queue_depth
    for k, v in (extra_gauges or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            gauges[str(k)] = v
    return {"counters": counters, "gauges": gauges}


# -- SLO burn rates -------------------------------------------------------

class SLOMonitor:
    """Score observed per-tenant latencies against ``TenantSpec``
    targets and publish burn-rate gauges.

    Burn rate = (fraction of recent observations over the SLO) /
    ``budget`` — the standard error-budget framing: 1.0 means the
    tenant is burning its budget exactly as fast as allowed, >1 means
    the router should start shedding or re-prioritizing. Reads the
    reservoirs of ``serving.disagg.prefill_ttft_seconds.<tenant>`` and
    ``serving.disagg.per_token_seconds.<tenant>`` (or any merged fleet
    histogram handed to :meth:`tick`)."""

    TTFT_METRIC = "serving.disagg.prefill_ttft_seconds"
    PER_TOKEN_METRIC = "serving.disagg.per_token_seconds"

    def __init__(self, tenants, hub=None, budget=0.1):
        self._tenants = tenants
        self._hub = hub or _t.get_telemetry()
        self.budget = float(budget)
        if self.budget <= 0:
            raise ValueError("budget must be positive")

    def _burn(self, values, slo_ms):
        # a tenant with no target (absent/zero/negative SLO) or no
        # traffic this window is not burning budget: 0.0, never a
        # None/NaN that poisons gauges or autopilot thresholds
        if not values or slo_ms is None or slo_ms <= 0:
            return 0.0
        over = sum(1 for v in values if v * 1e3 > slo_ms)
        return (over / len(values)) / self.budget

    def tick(self, reservoirs=None, publish=True):
        """{tenant: {"ttft_burn": x, "per_token_burn": y}} — always
        finite floats; no-target and zero-traffic legs read 0.0.

        ``reservoirs`` optionally maps metric name -> list of observed
        seconds (e.g. from a merged fleet snapshot); by default the
        local hub's reservoirs are read. ``publish=True`` also sets
        ``fleet.slo_burn_ttft.<tenant>`` /
        ``fleet.slo_burn_per_token.<tenant>`` gauges."""
        def _res(name):
            if reservoirs is not None:
                return reservoirs.get(name)
            return self._hub.reservoir(name)

        out = {}
        for spec in self._tenants.specs():
            ttft = self._burn(_res("%s.%s" % (self.TTFT_METRIC,
                                              spec.name)),
                              spec.ttft_slo_ms)
            per_tok = self._burn(_res("%s.%s" % (self.PER_TOKEN_METRIC,
                                                 spec.name)),
                                 spec.per_token_slo_ms)
            out[spec.name] = {"ttft_burn": ttft,
                              "per_token_burn": per_tok}
            if publish and _t.mode() != _t.OFF:
                hub = _t.get_telemetry()
                hub.set_gauge("fleet.slo_burn_ttft.%s" % spec.name,
                              ttft)
                hub.set_gauge(
                    "fleet.slo_burn_per_token.%s" % spec.name,
                    per_tok)
        return out
