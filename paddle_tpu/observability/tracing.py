"""Nestable monotonic-clock spans with a thread-local stack.

``span("executor.run")`` is a context manager: entering pushes onto the
current thread's stack, exiting pops and observes the duration into the
telemetry hub as a ``span.<name>.seconds`` histogram. In ``trace`` mode
every exit additionally records a ``span`` event (name, seconds, depth,
parent) into the flight recorder so the JSONL stream carries the full
step timeline. With telemetry off the context manager is inert — no
clock read, no stack push, no allocation beyond the span object itself
(which instrumentation sites create unconditionally; it has __slots__
and a constructor that stores two attributes).

Per-thread stacks are registered in a process-wide table so the crash
dumper can report what every thread was inside when the process died
(``active_spans()``).

Spans optionally participate in **distributed traces**: pass a sampled
:class:`~paddle_tpu.observability.distributed.TraceContext` as
``ctx=`` and the span derives a child span id on entry (readable as
``.ctx`` for further propagation) and appends a JSONL record to
``$PADDLE_TPU_TRACE_DIR`` on exit. With no ctx (or an unsampled one)
the extra work is a single attribute store — the per-request sampling
bit keeps tracing opt-in.
"""
import threading
import time

from . import telemetry as _t

__all__ = ["span", "active_spans", "current_span"]

_tls = threading.local()
_registry_lock = threading.Lock()
_stacks = {}  # thread ident -> (thread name, stack list)


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        # registered for the thread's lifetime: active_spans() filters
        # empty stacks, and an ident reused by a later thread simply
        # overwrites this entry (fresh thread -> fresh thread-local)
        st = _tls.stack = []
        t = threading.current_thread()
        with _registry_lock:
            _stacks[t.ident] = (t.name, st)
    return st


class span:
    """``with span("executor.run", program=uid): ...``

    ``ctx=`` attaches a distributed :class:`TraceContext`; when it is
    sampled the span gets its own child span id (``.ctx``) and its
    exit is exported as a JSONL trace record."""

    __slots__ = ("name", "fields", "t0", "_live", "_mode", "_ctx",
                 "_wall0")

    def __init__(self, name, ctx=None, **fields):
        self.name = name
        self.fields = fields or None
        self.t0 = None
        self._live = False
        self._mode = _t.OFF
        self._ctx = ctx
        self._wall0 = None

    @property
    def ctx(self):
        """The context to propagate downstream: this span's own child
        context once entered (so downstream spans parent to it), else
        whatever was passed in."""
        return self._ctx

    def __enter__(self):
        m = _t.mode()
        self._mode = m
        if m == _t.OFF:
            return self
        self._live = True
        _stack().append(self)
        ctx = self._ctx
        if ctx is not None and ctx.sampled:
            self._ctx = ctx.child()
            self._wall0 = time.time()
        self.t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        if not self._live:
            return False
        dt = time.monotonic() - self.t0
        self._live = False
        st = _stack()
        # pop self even if an inner span leaked (exception paths)
        while st and st.pop() is not self:
            pass
        parent = st[-1].name if st else None
        _t.get_telemetry().observe("span.%s.seconds" % self.name, dt)
        ctx = self._ctx
        if ctx is not None and ctx.sampled and self._wall0 is not None:
            from . import distributed as _dist

            fields = dict(self.fields or {})
            if exc_type is not None:
                fields["error"] = exc_type.__name__
            _dist.export_span(self.name, ctx, self._wall0, dt, fields)
        if self._mode == _t.TRACE:
            from . import recorder as _r

            fields = dict(self.fields or {})
            if exc_type is not None:
                fields["error"] = exc_type.__name__
            _r.get_recorder().record(
                "span", name=self.name, seconds=round(dt, 9),
                depth=len(st) + 1, parent=parent, **fields)
        return False

    def elapsed(self):
        """Seconds since entry (live spans only)."""
        return time.monotonic() - self.t0 if self.t0 is not None else 0.0


def current_span():
    """The innermost live span on this thread, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None


def active_spans():
    """{thread name: [(span name, seconds elapsed), ...]} for every
    thread currently inside at least one span — outermost first. Used
    by the crash dumper to answer 'what was each thread doing'."""
    out = {}
    with _registry_lock:
        items = list(_stacks.items())
    for _ident, (tname, st) in items:
        frames = [(s.name, round(s.elapsed(), 6)) for s in list(st)
                  if s._live]
        if frames:
            out[tname] = frames
    return out
