"""Training-run health: convergence flight recorder + goodput account.

Production TPU training is judged on two curves the rest of the
observability stack never sees: the *convergence trajectory* (loss,
gradient norms, AMP loss-scale — is the run still learning, or
quietly diverging toward the first NaN?) and *goodput* — the fraction
of wall-clock spent on productive optimizer steps rather than
compiles, input stalls, checkpoint writes, retry backoff, or steps
re-executed after a crash-resume (time-to-accuracy, not step time, is
the metric that matters at pod scale). This module is both recorders
plus the streaming anomaly detectors that close the loop into the
autopilot's TRAIN leg:

- :class:`StepSeries` — a bounded in-memory ring of per-step records
  (loss, grad global-norm pre/post clip, param/update-norm ratio, lr,
  AMP loss-scale + skipped flag, and the step's wall time split into
  data-wait / compute / fetch from the executor's existing phase
  timings), with JSONL export read back through the PR-17 tolerant
  reader. Each record also feeds the streaming detectors: loss-spike
  z-score over a trailing window, grad-norm explosion vs the trailing
  median, non-finite loss, plateau, and throughput sag — every firing
  bumps a ``runhealth.*`` counter and lands a flight-recorder event.
- :class:`GoodputAccount` — decomposes run wall-clock into
  ``productive_step`` / ``compile`` / ``data_stall`` / ``checkpoint``
  / ``retry_backoff`` / ``restart_rework`` buckets and reports the
  goodput fraction. The instrumented layers feed it through the
  module-level :func:`goodput_note` hook (inert without an active
  account, like every other observability hook): the executor notes
  compile seconds, ``GuardedExecutor`` its backoff sleeps,
  ``TrainGuard`` feed waits + checkpoint writes + crash-resume rework
  (steps the previous process ran past its last checkpoint, recomputed
  from the prior run's StepSeries JSONL vs ``latest_step``), and the
  pipelined runner its consumer-side queue waits.
- :class:`RunHealth` — the bundle ``TrainGuard(runhealth=...)`` wires
  in; :meth:`RunHealth.diverging` is the signal the autopilot's TRAIN
  leg confirms (through ActionGate hysteresis) before proposing — or
  in apply mode executing — a journaled rollback-to-last-finite-
  checkpoint + lr-cut.

Render a run-health report (or an A/B run comparison) with::

    python -m paddle_tpu.observability run <dir|snapshot.json> [B]

Stdlib-only, like the rest of the package.
"""
import collections
import json
import math
import os
import threading
import time

from . import recorder as _rec
from . import telemetry as _t

__all__ = [
    "StepSeries", "GoodputAccount", "RunHealth",
    "activate", "deactivate", "active", "active_goodput",
    "set_active_goodput", "goodput_note", "note_exec_phases",
    "take_exec_phases", "crash_snapshot", "load_run", "health_rows",
    "render_health_report", "compare_rows", "render_comparison",
]

GOODPUT_BUCKETS = ("productive_step", "compile", "data_stall",
                   "checkpoint", "retry_backoff", "restart_rework")

# anomaly kinds the detectors can emit (== the runhealth.<kind>
# counter family and the flight-recorder event kinds, source
# "runhealth")
ANOMALY_KINDS = ("loss_spike", "grad_explosion", "nonfinite_loss",
                 "plateau", "throughput_sag")


def _inc(name, n=1):
    if _t.mode() != _t.OFF:
        _t._hub.inc(name, n)


def _gauge(name, value):
    if _t.mode() != _t.OFF:
        _t._hub.set_gauge(name, value)


def _event(kind, **fields):
    # mirror obs.event(source="runhealth") without importing the
    # package facade (this module is imported BY it)
    if _t.mode() == _t.OFF:
        return
    _t._hub.inc("runhealth.%s" % kind)
    fields.setdefault("source", "runhealth")
    _rec._global.record(kind, **fields)


def _finite(v):
    try:
        return math.isfinite(float(v))
    except (TypeError, ValueError, OverflowError):
        return False


def _median(xs):
    xs = sorted(xs)
    if not xs:
        return None
    n = len(xs)
    mid = xs[n // 2]
    return mid if n % 2 else (xs[n // 2 - 1] + mid) / 2.0


# ---------------------------------------------------------------------------
# the per-step convergence recorder
# ---------------------------------------------------------------------------


class StepSeries:
    """Bounded ring of per-step training-health records + streaming
    anomaly detectors.

    :meth:`record` takes whatever the caller could measure this step —
    every field is optional — appends one dict record to the ring,
    optionally appends it to a JSONL sidecar (read back through the
    tolerant reader, so a torn final line from a crash never poisons
    the resume-side rework accounting), publishes the ``runhealth.*``
    gauges, and runs the detectors:

    - **loss spike** — z-score of this loss against the trailing
      ``window`` losses exceeds ``spike_z`` (needs ``min_samples``
      history; the detectors never fire cold).
    - **grad explosion** — grad global-norm over ``explode_factor``
      x the trailing median grad norm.
    - **nonfinite loss** — NaN/Inf loss (the binary signal the
      GuardedExecutor skip guard already acts on; recorded here so
      the trajectory shows WHEN finiteness was lost).
    - **plateau** — over the last ``plateau_window`` steps the loss
      improved by less than ``plateau_rel`` (relative); re-fires at
      most once per window.
    - **throughput sag** — step wall time over ``sag_factor`` x the
      trailing median step time.

    Detector state lives locally (``anomalies`` counter + per-kind
    last-firing step), so :meth:`diverging` works even with
    ``PADDLE_TPU_TELEMETRY=off``; the hub/ring routing is mode-gated
    like every other instrument.
    """

    def __init__(self, maxlen=4096, window=32, min_samples=8,
                 spike_z=6.0, explode_factor=10.0, plateau_window=64,
                 plateau_rel=1e-4, sag_factor=3.0, jsonl_path=None,
                 flush_every=8):
        self._lock = threading.Lock()
        self.records = collections.deque(maxlen=int(maxlen))
        self.window = int(window)
        self.min_samples = max(2, int(min_samples))
        self.spike_z = float(spike_z)
        self.explode_factor = float(explode_factor)
        self.plateau_window = int(plateau_window)
        self.plateau_rel = float(plateau_rel)
        self.sag_factor = float(sag_factor)
        self.jsonl_path = str(jsonl_path) if jsonl_path else None
        self._flush_every = max(1, int(flush_every))
        self._pending = []
        self._jsonl_dir_ok = False
        self.total = 0               # records ever taken (ring may drop)
        self.anomalies = collections.Counter()
        self._last_anomaly_step = {}  # kind -> step it last fired at
        self._losses = collections.deque(maxlen=self.window)
        # running first/second moments of _losses so the z-score costs
        # O(1) per step instead of re-summing the window
        self._loss_sum = 0.0
        self._loss_sumsq = 0.0
        self._grad_norms = collections.deque(maxlen=self.window)
        self._step_times = collections.deque(maxlen=self.window)
        self._plateau_hist = collections.deque(
            maxlen=max(2, self.plateau_window))
        self._last_plateau_check = 0
        self._last_step = None

    # -- recording -------------------------------------------------------
    def record(self, step, loss=None, grad_norm=None,
               grad_norm_clipped=None, update_ratio=None, lr=None,
               loss_scale=None, amp_skipped=None, skipped=None,
               retries=None, data_wait_s=None, compute_s=None,
               fetch_s=None, step_s=None, **extra):
        """Record one training step; returns the record dict."""
        rec = {"step": int(step), "wall": time.time()}
        for key, v in (("loss", loss), ("grad_norm", grad_norm),
                       ("grad_norm_clipped", grad_norm_clipped),
                       ("update_ratio", update_ratio), ("lr", lr),
                       ("loss_scale", loss_scale),
                       ("amp_skipped", amp_skipped),
                       ("skipped", skipped), ("retries", retries),
                       ("data_wait_s", data_wait_s),
                       ("compute_s", compute_s), ("fetch_s", fetch_s),
                       ("step_s", step_s)):
            if v is not None:
                rec[key] = v
        rec.update(extra)
        with self._lock:
            self.records.append(rec)
            self.total += 1
            self._last_step = rec["step"]
            if self.jsonl_path:
                self._pending.append(rec)
                if len(self._pending) >= self._flush_every:
                    self._flush_locked()
        # resolve the telemetry mode ONCE per step: the env lookup is
        # measurable at per-step hook rates
        if _t.mode() != _t.OFF:
            hub = _t._hub
            hub.inc("runhealth.steps")
            if loss is not None and _finite(loss):
                hub.set_gauge("runhealth.loss", float(loss))
            if grad_norm is not None and _finite(grad_norm):
                hub.set_gauge("runhealth.grad_norm", float(grad_norm))
            if loss_scale is not None and _finite(loss_scale):
                hub.set_gauge("runhealth.loss_scale", float(loss_scale))
            if step_s is not None:
                hub.set_gauge("runhealth.step_seconds", float(step_s))
        self._detect(rec)
        return rec

    def _fire(self, kind, step, **fields):
        self.anomalies[kind] += 1
        self._last_anomaly_step[kind] = step
        _event(kind, step=step, **fields)

    def _detect(self, rec):
        step = rec["step"]
        loss = rec.get("loss")
        if loss is not None:
            if not _finite(loss):
                self._fire("nonfinite_loss", step)
            else:
                loss = float(loss)
                n = len(self._losses)
                if n >= self.min_samples:
                    mean = self._loss_sum / n
                    var = max(0.0, self._loss_sumsq / n - mean * mean)
                    # std floor: a perfectly flat window must not turn
                    # numeric dust into an infinite z-score
                    std = max(math.sqrt(var), 1e-3 * abs(mean), 1e-12)
                    z = (loss - mean) / std
                    if z > self.spike_z:
                        self._fire("loss_spike", step,
                                   z=round(z, 2), loss=loss,
                                   window_mean=round(mean, 6))
                if n == self._losses.maxlen:
                    old = self._losses[0]
                    self._loss_sum -= old
                    self._loss_sumsq -= old * old
                self._losses.append(loss)
                self._loss_sum += loss
                self._loss_sumsq += loss * loss
                self._plateau_hist.append(loss)
                if (len(self._plateau_hist) >= self._plateau_hist.maxlen
                        and step - self._last_plateau_check
                        >= self.plateau_window):
                    self._last_plateau_check = step
                    hist = list(self._plateau_hist)
                    q = max(1, len(hist) // 4)
                    first = _median(hist[:q])
                    lastm = _median(hist[-q:])
                    denom = max(abs(first), 1e-12)
                    if (first - lastm) / denom < self.plateau_rel:
                        self._fire("plateau", step,
                                   first=round(first, 6),
                                   last=round(lastm, 6))
        gn = rec.get("grad_norm")
        if gn is not None:
            if _finite(gn):
                gn = float(gn)
                if len(self._grad_norms) >= self.min_samples:
                    med = _median(self._grad_norms)
                    if med and gn > self.explode_factor * med:
                        self._fire("grad_explosion", step,
                                   grad_norm=gn,
                                   window_median=round(med, 6))
                self._grad_norms.append(gn)
            else:
                self._fire("grad_explosion", step, grad_norm="nonfinite")
        st = rec.get("step_s")
        if st is not None and _finite(st):
            st = float(st)
            if len(self._step_times) >= self.min_samples:
                med = _median(self._step_times)
                if med and st > self.sag_factor * med:
                    self._fire("throughput_sag", step,
                               step_s=round(st, 6),
                               window_median_s=round(med, 6))
            self._step_times.append(st)

    # -- the autopilot signal -------------------------------------------
    def diverging(self, recent=4):
        """The divergence signal: a dict naming the anomaly when a
        ``nonfinite_loss`` / ``loss_spike`` / ``grad_explosion`` fired
        within the last ``recent`` recorded steps, else None. The
        autopilot TRAIN leg confirms this over ActionGate hysteresis
        before touching the run."""
        last = self._last_step
        if last is None:
            return None
        for kind in ("nonfinite_loss", "loss_spike", "grad_explosion"):
            at = self._last_anomaly_step.get(kind)
            if at is not None and last - at < int(recent):
                return {"kind": kind, "step": at, "last_step": last}
        return None

    def reset_anomalies(self):
        """Forget detector history (after a rollback: the restored
        trajectory must re-baseline, not re-trip on pre-rollback
        ghosts). The ring and counters stay — they are the record."""
        self._last_anomaly_step.clear()
        self._losses.clear()
        self._loss_sum = 0.0
        self._loss_sumsq = 0.0
        self._grad_norms.clear()
        self._step_times.clear()
        self._plateau_hist.clear()

    # -- reads -----------------------------------------------------------
    def tail(self, n=None):
        with self._lock:
            recs = list(self.records)
        return recs if n is None else recs[-int(n):]

    def __len__(self):
        with self._lock:
            return len(self.records)

    def last(self):
        with self._lock:
            return self.records[-1] if self.records else None

    def snapshot(self):
        """Aggregate view (JSON-safe): counts, loss trajectory, mean
        step time + phase split, anomaly counters."""
        recs = self.tail()
        losses = [float(r["loss"]) for r in recs
                  if r.get("loss") is not None and _finite(r["loss"])]
        steps_s = [float(r["step_s"]) for r in recs
                   if r.get("step_s") is not None]

        def _mean(key):
            vs = [float(r[key]) for r in recs if r.get(key) is not None]
            return sum(vs) / len(vs) if vs else None

        return {
            "steps": self.total,
            "ring": len(recs),
            "first_step": recs[0]["step"] if recs else None,
            "last_step": recs[-1]["step"] if recs else None,
            "loss_first": losses[0] if losses else None,
            "loss_last": losses[-1] if losses else None,
            "loss_min": min(losses) if losses else None,
            "mean_step_s": (sum(steps_s) / len(steps_s)
                            if steps_s else None),
            "mean_data_wait_s": _mean("data_wait_s"),
            "mean_compute_s": _mean("compute_s"),
            "mean_fetch_s": _mean("fetch_s"),
            "skipped": sum(1 for r in recs if r.get("skipped")),
            "retries": sum(int(r.get("retries") or 0) for r in recs),
            "anomalies": dict(self.anomalies),
        }

    # -- JSONL persistence ----------------------------------------------
    def _flush_locked(self):
        if not self._pending or not self.jsonl_path:
            return
        lines = []
        for rec in self._pending:
            try:
                lines.append(json.dumps(rec))
            except (TypeError, ValueError):
                continue
        self._pending = []
        try:
            if not self._jsonl_dir_ok:
                d = os.path.dirname(os.path.abspath(self.jsonl_path))
                os.makedirs(d, exist_ok=True)
                self._jsonl_dir_ok = True
            with open(self.jsonl_path, "a", encoding="utf-8") as f:
                f.write("".join(line + "\n" for line in lines))
        except OSError:
            _inc("runhealth.jsonl_errors")

    def flush(self):
        """Drain buffered records to the JSONL sidecar (appends are
        batched every ``flush_every`` records so the per-step hook
        stays off the disk)."""
        with self._lock:
            self._flush_locked()

    def dump_jsonl(self, path):
        """Write the whole ring as JSONL (one record per line);
        returns the path."""
        recs = self.tail()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = "%s.tmp-%d" % (path, os.getpid())
        with open(tmp, "w", encoding="utf-8") as f:
            for rec in recs:
                f.write(json.dumps(rec))
                f.write("\n")
        os.replace(tmp, path)
        return path

    @staticmethod
    def load(path):
        """Read step records back from a JSONL file through the
        tolerant reader -> ``(records, dropped)``. A torn final line
        (the writer crashed mid-append) is skipped and counted, never
        raised."""
        from ..integrity import jsonl as _jsonl

        records, dropped = _jsonl.read_jsonl(path)
        if dropped:
            _inc("integrity.jsonl_dropped", dropped)
        records = [r for r in records
                   if isinstance(r, dict) and "step" in r]
        return records, dropped


# ---------------------------------------------------------------------------
# goodput accounting
# ---------------------------------------------------------------------------


class GoodputAccount:
    """Wall-clock decomposition of a training run.

    ``start()`` opens the accounting window; the instrumented layers
    attribute seconds into the buckets (:data:`GOODPUT_BUCKETS`) as
    they spend them; :meth:`snapshot` reports the decomposition, the
    residual the instrumentation could not attribute (loop overhead,
    event emission — the 5%-of-wall-clock budget the runhealth lane
    enforces), and ``goodput_fraction`` = productive seconds / wall.

    :meth:`step` is the attribution primitive: a context manager that
    measures one optimizer step and books its elapsed time as
    ``productive_step`` MINUS whatever overhead buckets were fed
    during the window (a compile or retry-backoff inside ``run()``
    must not be double-counted as productive compute).
    """

    _OVERHEAD_IN_STEP = ("compile", "retry_backoff", "data_stall")

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self.buckets = {b: 0.0 for b in GOODPUT_BUCKETS}
        self.rework_steps = 0
        self._t0 = None
        self._elapsed = 0.0          # closed windows (stop() latches)

    # -- the window ------------------------------------------------------
    def start(self):
        if self._t0 is None:
            self._t0 = self._clock()
        return self

    def stop(self):
        if self._t0 is not None:
            self._elapsed += self._clock() - self._t0
            self._t0 = None
        _gauge("runhealth.goodput_fraction", self.goodput_fraction())
        return self

    def wall(self):
        """Seconds of accounted wall-clock so far."""
        live = 0.0 if self._t0 is None else self._clock() - self._t0
        return self._elapsed + live

    # -- attribution -----------------------------------------------------
    def add(self, bucket, seconds, steps=None):
        if bucket not in self.buckets:
            raise ValueError("unknown goodput bucket %r (want one of %s)"
                             % (bucket, ", ".join(GOODPUT_BUCKETS)))
        with self._lock:
            self.buckets[bucket] += max(0.0, float(seconds))
            if bucket == "restart_rework" and steps:
                self.rework_steps += int(steps)

    def step(self):
        """Context manager booking one optimizer step as productive
        time net of in-step overhead attributions."""
        return _StepWindow(self)

    def _overhead_total(self):
        with self._lock:
            return sum(self.buckets[b] for b in self._OVERHEAD_IN_STEP)

    # -- reads -----------------------------------------------------------
    def total(self, bucket):
        with self._lock:
            return self.buckets[bucket]

    def goodput_fraction(self):
        """Productive-step seconds / accounted wall-clock (0.0 before
        any time has passed)."""
        w = self.wall()
        if w <= 0.0:
            return 0.0
        with self._lock:
            return min(1.0, self.buckets["productive_step"] / w)

    def snapshot(self):
        w = self.wall()
        with self._lock:
            buckets = {b: round(v, 6) for b, v in self.buckets.items()}
            rework_steps = self.rework_steps
        accounted = sum(buckets.values())
        return {
            "wall_s": round(w, 6),
            "buckets": buckets,
            "accounted_s": round(accounted, 6),
            "unaccounted_s": round(max(0.0, w - accounted), 6),
            "rework_steps": rework_steps,
            "goodput_fraction": round(self.goodput_fraction(), 6),
        }


class _StepWindow:
    def __init__(self, acct):
        self._acct = acct
        self._t0 = None
        self._over0 = 0.0

    def __enter__(self):
        self._t0 = self._acct._clock()
        self._over0 = self._acct._overhead_total()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = self._acct._clock() - self._t0
        overhead = self._acct._overhead_total() - self._over0
        if exc_type is None:
            self._acct.add("productive_step", max(0.0, dt - overhead))
        # a step that raised was not productive; its backoff/compile
        # attributions already landed in their own buckets
        return False


# ---------------------------------------------------------------------------
# the bundle + process-wide hooks
# ---------------------------------------------------------------------------


class RunHealth:
    """StepSeries + GoodputAccount, bundled for ``TrainGuard``.

    ``extra_fetches`` maps record-field names to graph Variables the
    TrainGuard should fetch each step and feed into the record — the
    hook for grad global-norms (pre/post clip), the param/update-norm
    ratio, or a schedule's lr Variable, which live in the graph and
    are only host-visible when fetched::

        rh = RunHealth(extra_fetches={"grad_norm": gnorm_var,
                                      "lr": lr_var})
        TrainGuard(exe, ..., runhealth=rh).train(1000)
    """

    def __init__(self, series=None, goodput=None, extra_fetches=None,
                 jsonl_path=None, **series_opts):
        if series is None:
            series = StepSeries(jsonl_path=jsonl_path, **series_opts)
        self.series = series
        self.goodput = goodput if goodput is not None else GoodputAccount()
        self.extra_fetches = dict(extra_fetches or {})

    def diverging(self, recent=4):
        return self.series.diverging(recent=recent)

    def snapshot(self):
        return {"series": self.series.snapshot(),
                "goodput": self.goodput.snapshot()}

    def dump(self, path):
        """Write the snapshot as one JSON doc (the ``run`` CLI and the
        A/B comparison read it back); returns the path."""
        doc = self.snapshot()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = "%s.tmp-%d" % (path, os.getpid())
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


_active = None           # RunHealth a TrainGuard activated
_active_goodput = None   # bare GoodputAccount (bench loops)


def activate(rh):
    """Make ``rh`` the process-active RunHealth: executor/pipeline/
    guard hooks feed its goodput account, and crash dumps carry its
    series tail. Returns the previous active bundle (restore it in a
    finally)."""
    global _active, _active_goodput
    prev = _active
    _active = rh
    _active_goodput = rh.goodput if rh is not None else None
    return prev


def deactivate(prev=None):
    global _active, _active_goodput
    _active = prev
    _active_goodput = prev.goodput if prev is not None else None


def active():
    return _active


def set_active_goodput(acct):
    """Goodput-only activation (bench loops that want the account
    without a step series). Returns the previous account."""
    global _active_goodput
    prev = _active_goodput
    _active_goodput = acct
    return prev


def active_goodput():
    return _active_goodput


def goodput_note(bucket, seconds, steps=None):
    """Attribute seconds into the active goodput account; inert (one
    global read) when none is active — safe on every hot path."""
    acct = _active_goodput
    if acct is not None:
        acct.add(bucket, seconds, steps=steps)


_exec_phases = None  # last Executor.run phase split (consumer thread)


def note_exec_phases(feed_convert_s=None, compute_s=None, fetch_s=None):
    """Executor.run's per-step phase split, parked for the step
    recorder (TrainGuard pops it right after the guarded run returns —
    both run on the driving thread, so a one-slot handoff is exact)."""
    global _exec_phases
    if _active is not None:
        _exec_phases = {"feed_convert_s": feed_convert_s,
                        "compute_s": compute_s, "fetch_s": fetch_s}


def take_exec_phases():
    global _exec_phases
    p, _exec_phases = _exec_phases, None
    return p


def crash_snapshot(tail=32):
    """What the flight recorder embeds in a crash dump: the active
    run's last-N step records + goodput decomposition (convergence
    state at death), or None when nothing is active."""
    if _active is not None:
        return {"series_tail": _active.series.tail(tail),
                "series": _active.series.snapshot(),
                "goodput": _active.goodput.snapshot()}
    if _active_goodput is not None:
        return {"goodput": _active_goodput.snapshot()}
    return None


def reset():
    """Drop the active bundle/account (obs.reset() test scoping)."""
    global _active, _active_goodput, _exec_phases
    _active = None
    _active_goodput = None
    _exec_phases = None


# ---------------------------------------------------------------------------
# report loading + rendering (the `run` CLI)
# ---------------------------------------------------------------------------


def _series_from_records(records):
    """A StepSeries snapshot recomputed from loaded JSONL records (the
    ring is gone; the lines are the record)."""
    s = StepSeries(maxlen=max(1, len(records)) + 1)
    for rec in sorted(records, key=lambda r: r.get("step", 0)):
        fields = {k: v for k, v in rec.items()
                  if k not in ("step", "wall")}
        s.record(rec.get("step", 0), **fields)
    return s.snapshot()


def _run_of_doc(doc):
    """Normalize one loaded JSON doc into a run dict
    ``{"series":..., "goodput":...}`` or None when not run-shaped.
    Accepts a ``RunHealth.snapshot()``/``dump()`` doc, a bench
    ``--telemetry-out`` file (rides under ``"runhealth"``), or a
    crash dump (same key)."""
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get("runhealth"), dict):
        doc = doc["runhealth"]
    if not isinstance(doc, dict):
        return None
    if "series" in doc or "goodput" in doc:
        out = {"series": doc.get("series"), "goodput": doc.get("goodput")}
        if isinstance(doc.get("series_tail"), list):
            out["series"] = out["series"] or _series_from_records(
                doc["series_tail"])
        return out
    return None


def load_run(path):
    """Load a run-health doc from `path`: a snapshot JSON
    (``RunHealth.dump()``, a bench ``--telemetry-out`` file, or a
    crash dump), a StepSeries JSONL, or a directory scanned for both
    (first run-shaped ``*.json`` wins; every ``*.jsonl`` merges into
    the series). Returns ``{"path", "series", "goodput"}`` — either
    side may be None when that evidence wasn't found."""
    run = {"path": str(path), "series": None, "goodput": None}
    if os.path.isdir(path):
        names = sorted(os.listdir(path))
        json_paths = [os.path.join(path, n) for n in names
                      if n.endswith(".json")]
        jsonl_paths = [os.path.join(path, n) for n in names
                       if n.endswith(".jsonl")]
    elif str(path).endswith(".jsonl"):
        json_paths, jsonl_paths = [], [path]
    else:
        json_paths, jsonl_paths = [path], []
    for p in json_paths:
        try:
            with open(p, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        got = _run_of_doc(doc)
        if got is not None:
            run["series"] = run["series"] or got.get("series")
            run["goodput"] = run["goodput"] or got.get("goodput")
    if run["series"] is None and jsonl_paths:
        records = []
        for p in jsonl_paths:
            recs, _dropped = StepSeries.load(p)
            records.extend(recs)
        if records:
            run["series"] = _series_from_records(records)
    return run


_HEALTH_ROWS = (
    # (label, section, key, format)
    ("steps", "series", "steps", "%d"),
    ("last step", "series", "last_step", "%d"),
    ("loss first", "series", "loss_first", "%.4f"),
    ("loss last", "series", "loss_last", "%.4f"),
    ("loss min", "series", "loss_min", "%.4f"),
    ("mean step ms", "series", "mean_step_s", "%.2f"),
    ("mean data-wait ms", "series", "mean_data_wait_s", "%.2f"),
    ("mean compute ms", "series", "mean_compute_s", "%.2f"),
    ("mean fetch ms", "series", "mean_fetch_s", "%.2f"),
    ("skipped steps", "series", "skipped", "%d"),
    ("retries", "series", "retries", "%d"),
    ("wall s", "goodput", "wall_s", "%.3f"),
    ("goodput fraction", "goodput", "goodput_fraction", "%.3f"),
)

_MS_KEYS = frozenset({"mean_step_s", "mean_data_wait_s",
                      "mean_compute_s", "mean_fetch_s"})


def _row_value(run, section, key):
    doc = run.get(section) or {}
    v = doc.get(key)
    if v is None:
        return None
    if key in _MS_KEYS:
        return 1e3 * float(v)
    return v


def health_rows(run):
    """Flatten a loaded run into ``(label, value, fmt)`` rows: the
    headline metrics, the goodput bucket decomposition, and the
    anomaly counters."""
    rows = [(label, _row_value(run, section, key), fmt)
            for label, section, key, fmt in _HEALTH_ROWS]
    gp = run.get("goodput") or {}
    buckets = gp.get("buckets") or {}
    wall = gp.get("wall_s") or 0.0
    for b in GOODPUT_BUCKETS:
        v = buckets.get(b)
        if v is None:
            continue
        pct = (" (%.1f%%)" % (100.0 * v / wall)) if wall else ""
        rows.append(("  %s s" % b.replace("_", "-"),
                     "%.3f%s" % (v, pct), "%s"))
    if gp.get("unaccounted_s") is not None and wall:
        rows.append(("  unaccounted s",
                     "%.3f (%.1f%%)" % (gp["unaccounted_s"],
                                        100.0 * gp["unaccounted_s"] / wall),
                     "%s"))
    anomalies = (run.get("series") or {}).get("anomalies") or {}
    for kind in ANOMALY_KINDS:
        n = anomalies.get(kind)
        if n:
            rows.append(("anomaly %s" % kind, n, "%d"))
    return rows


def render_health_report(run, title=None):
    """The run-health report text block for one loaded run."""
    out = ["run health: %s" % (title or run.get("path") or "-")]
    width = max(len(label) for label, _, _, _ in _HEALTH_ROWS) + 4
    for label, v, fmt in health_rows(run):
        out.append("  %s %s" % (label.ljust(width),
                                "-" if v is None else fmt % v))
    return "\n".join(out)


def compare_rows(run_a, run_b):
    """A/B comparison rows ``(label, a, b, delta_pct)`` over the
    numeric health metrics + goodput buckets of two loaded runs."""
    rows = []

    def _num(run, section, key):
        v = _row_value(run, section, key)
        try:
            return None if v is None else float(v)
        except (TypeError, ValueError):
            return None

    for label, section, key, fmt in _HEALTH_ROWS:
        a = _num(run_a, section, key)
        b = _num(run_b, section, key)
        if a is None and b is None:
            continue
        delta = (100.0 * (b - a) / a) if (a and b is not None) else None
        rows.append((label, a, b, delta, fmt))
    ga = (run_a.get("goodput") or {}).get("buckets") or {}
    gb = (run_b.get("goodput") or {}).get("buckets") or {}
    for bucket in GOODPUT_BUCKETS:
        a, b = ga.get(bucket), gb.get(bucket)
        if a is None and b is None:
            continue
        delta = (100.0 * (b - a) / a) if (a and b is not None) else None
        rows.append(("%s s" % bucket.replace("_", "-"), a, b, delta,
                     "%.3f"))
    return rows


def render_comparison(run_a, run_b, label_a="A", label_b="B"):
    """Aligned A-vs-B table (same renderer family as the PR-15 drift
    table: fixed columns, ``-`` for unknown cells)."""
    headers = ["metric", label_a, label_b, "delta%"]
    cells = []
    for label, a, b, delta, fmt in compare_rows(run_a, run_b):
        cells.append([
            label,
            "-" if a is None else fmt % a,
            "-" if b is None else fmt % b,
            "-" if delta is None else "%+.1f" % delta,
        ])
    widths = [max(len(h), *(len(row[i]) for row in cells))
              if cells else len(h) for i, h in enumerate(headers)]
    out = ["  ".join(h.ljust(widths[i]) for i, h in enumerate(headers))]
    out.append("  ".join("-" * w for w in widths))
    for row in cells:
        out.append("  ".join(row[i].ljust(widths[i])
                             for i in range(len(widths))))
    return "\n".join(out)
