"""Ring-buffer flight recorder + crash dumps.

The recorder absorbs every structured event stream in the process —
executor compile events, resilience retries/skips/saves, fleet
heartbeat transitions — into ONE bounded ring, each event stamped with
a monotonic timestamp so streams from different layers interleave in
true order. ``dump_jsonl()`` writes the ring on demand;
``install_excepthook()`` (installed automatically the first time an
enabled recorder records) writes the last N events, the active span
stacks, and the telemetry snapshot to a crash-dump file when an
uncaught exception kills the process or a thread — the black box you
read AFTER the run died, instead of re-running under a debugger.

Crash-dump path: ``PADDLE_TPU_CRASH_DUMP`` env var, else
``<tmpdir>/paddle_tpu_crash_<pid>.json``.
"""
import collections
import itertools
import json
import os
import sys
import tempfile
import threading
import time
import traceback

from . import telemetry as _t
from . import tracing as _tr

__all__ = [
    "FlightRecorder", "get_recorder", "install_excepthook",
    "crash_dump_path", "CRASH_DUMP_ENV",
]

CRASH_DUMP_ENV = "PADDLE_TPU_CRASH_DUMP"


def crash_dump_path(per_pid=False):
    """Where a crash dump would be written right now.

    ``per_pid=True`` derives a pid-suffixed variant of the
    ``$PADDLE_TPU_CRASH_DUMP`` override (``dump.json`` ->
    ``dump.<pid>.json``) so several crashing worker processes that
    inherited one env value don't clobber each other's dump. The
    default (unset env) path already embeds the pid. Idempotent: a
    path that already carries this pid's suffix is returned as-is."""
    base = os.environ.get(CRASH_DUMP_ENV)
    if not base:
        return os.path.join(
            tempfile.gettempdir(),
            "paddle_tpu_crash_%d.json" % os.getpid())
    if not per_pid:
        return base
    root, ext = os.path.splitext(base)
    tag = ".%d" % os.getpid()
    if root.endswith(tag):
        return base
    return root + tag + (ext or ".json")


def _san(v):
    """JSON-safe view of an event field (numpy scalars/arrays, device
    arrays, exceptions — anything may ride in an event)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_san(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _san(x) for k, x in v.items()}
    item = getattr(v, "item", None)
    if item is not None and getattr(v, "ndim", None) == 0:
        try:
            return item()
        except Exception:  # noqa: BLE001 — fall through to repr
            pass
    tolist = getattr(v, "tolist", None)
    if tolist is not None and getattr(v, "size", 1 << 30) <= 64:
        try:
            return tolist()
        except Exception:  # noqa: BLE001
            pass
    return repr(v)[:200]


class FlightRecorder:
    """Bounded ring of timestamped events.

    ``enabled=None`` (the global recorder) follows the live
    ``PADDLE_TPU_TELEMETRY`` mode; an explicitly constructed recorder
    defaults to ``enabled=True`` so wiring one into a TrainGuard /
    FleetGuard records regardless of the env switch.
    """

    def __init__(self, maxlen=4096, enabled=True):
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self.events = collections.deque(maxlen=int(maxlen))
        self._enabled = enabled

    def _live(self):
        if self._enabled is None:
            return _t.mode() != _t.OFF
        return bool(self._enabled)

    def record(self, kind, **fields):
        """Append one event; returns it (None when disabled)."""
        if not self._live():
            return None
        ev = {"seq": next(self._seq), "ts": time.monotonic(),
              "wall": time.time(), "kind": kind}
        ev.update(fields)
        with self._lock:
            self.events.append(ev)
        _maybe_install_excepthook()
        return ev

    def sink(self, source=None):
        """An ``EventLog``-style sink callback routing into this ring:
        ``log = EventLog(sink=recorder.sink("resilience"))``."""

        def _sink(ev):
            ev = dict(ev)
            kind = ev.pop("kind", "event")
            if source is not None:
                ev.setdefault("source", source)
            self.record(kind, **ev)

        return _sink

    def of(self, kind):
        with self._lock:
            return [ev for ev in self.events if ev["kind"] == kind]

    def tail(self, n=None):
        """The newest `n` events (all, when n is None), ordered by
        monotonic timestamp so multi-thread streams interleave true."""
        with self._lock:
            evs = sorted(self.events, key=lambda e: (e["ts"], e["seq"]))
        return evs if n is None else evs[-int(n):]

    def clear(self):
        with self._lock:
            self.events.clear()

    # -- dumps -----------------------------------------------------------
    def dump_jsonl(self, path):
        """Write every held event as one JSON object per line, ordered
        by monotonic timestamp. Returns the path."""
        evs = self.tail()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            for ev in evs:
                f.write(json.dumps({k: _san(v) for k, v in ev.items()}))
                f.write("\n")
        return path

    def crash_dump(self, path=None, exc=None):
        """Write the black box: last events + active spans + telemetry
        snapshot + the executable-ledger tail and compile-cache
        hit/miss counters (what was compiled and resident at death) +
        the active run's StepSeries tail and goodput decomposition
        (convergence state at death), plus the exception when given.
        Returns the path, or None if even the dump write failed (a
        crash path must not raise)."""
        path = path or crash_dump_path()
        doc = {
            "wall": time.time(),
            "pid": os.getpid(),
            "events": [{k: _san(v) for k, v in ev.items()}
                       for ev in self.tail()],
            "active_spans": _tr.active_spans(),
            "telemetry": _t.get_telemetry().snapshot(),
        }
        try:
            from . import ledger as _ledger

            doc["executables"] = _ledger.get_ledger().tail(16)
        except Exception:  # noqa: BLE001 — crash path must not raise
            doc["executables"] = []
        try:
            # convergence state at death: last-N StepSeries records +
            # the goodput decomposition of the active training run
            # (lazy import — runhealth imports this module)
            from . import runhealth as _rh

            doc["runhealth"] = _rh.crash_snapshot()
        except Exception:  # noqa: BLE001
            doc["runhealth"] = None
        try:
            hub = _t.get_telemetry()
            doc["compile_cache"] = {
                k: hub.counter("compile_cache." + k)
                for k in ("disk_hit", "disk_miss", "corrupt",
                          "corrupt_digest", "corrupt_deserialize",
                          "store", "store_error")}
        except Exception:  # noqa: BLE001
            doc["compile_cache"] = {}
        if exc is not None:
            doc["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc)[:2000],
                "traceback": "".join(traceback.format_exception(
                    type(exc), exc, exc.__traceback__))[-8000:],
            }
        try:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            tmp = "%s.tmp-%d" % (path, os.getpid())
            with open(tmp, "w") as f:
                json.dump(doc, f, default=_san)
            os.replace(tmp, path)
            return path
        except Exception:  # noqa: BLE001 — crash path must not raise
            return None


_global = FlightRecorder(enabled=None)


def get_recorder():
    """The process-wide flight recorder (follows the env mode)."""
    return _global


# ---------------------------------------------------------------------------
# excepthook
# ---------------------------------------------------------------------------

_hook_lock = threading.Lock()
_hook_installed = False


def install_excepthook():
    """Chain crash-dump writers onto ``sys.excepthook`` and
    ``threading.excepthook`` (idempotent). The previous hooks still run
    — the dump is written first, so a hook that exits hard can't lose
    it."""
    global _hook_installed
    with _hook_lock:
        if _hook_installed:
            return
        _hook_installed = True

        prev_sys = sys.excepthook

        def _sys_hook(exc_type, exc, tb):
            if exc is not None and exc.__traceback__ is None:
                exc = exc.with_traceback(tb)
            _global.crash_dump(exc=exc)
            prev_sys(exc_type, exc, tb)

        sys.excepthook = _sys_hook

        prev_thread = threading.excepthook

        def _thread_hook(args):
            if not issubclass(args.exc_type, SystemExit):
                _global.crash_dump(exc=args.exc_value)
            prev_thread(args)

        threading.excepthook = _thread_hook


def _maybe_install_excepthook():
    # flight-recorder contract: once an enabled recorder holds events,
    # an uncaught crash writes them out — no explicit opt-in needed
    if not _hook_installed:
        install_excepthook()
