"""Persistent AOT compile cache: a disk tier under the Executor's
in-memory executable LRU.

Cold compiles dominate short runs (BENCH_r05: the transformer b64
variant spends 24.4 s compiling vs 61.5 ms/step), and every new process
— a TrainGuard crash-resume, a repeat bench round, a re-queued job —
pays them again. This module makes the compile a one-time cost per
*machine*: after the in-memory LRU misses, the executor asks the disk
tier for the program's AOT artifact (the StableHLO module serialized
via ``jax.export``) before tracing anything; a hit deserializes in
milliseconds and emits **no** ``compile_start`` event.

Activation — either of:

- ``PADDLE_TPU_COMPILE_CACHE_DIR=/path`` in the environment, or
- :func:`activate` (``TrainGuard`` calls it to co-locate the cache with
  its checkpoint directory, see ``parallel.checkpoint.compile_cache_dir``).

Both also point jax's own persistent XLA compilation cache at a
``<dir>/xla`` subdirectory (best effort), so the *backend* compile of a
deserialized module is disk-cached too: the export blob skips
trace+lower, the XLA cache skips codegen, and a warm process pays only
the deserialize + executable load.

Cache entries are content-addressed: the key hashes the program's
*structural* fingerprint (op types/slots/attrs, var shapes/dtypes —
NOT the process-local ``Program._uid``) together with the feed/fetch/
state signature, the lowering platform, the device kind, and the
jax/jaxlib versions plus a format version — an upgrade simply misses
and re-fills. Writes are atomic (unique tmp + ``os.replace``) so two
processes sharing a directory never see torn blobs; a corrupt or
unreadable entry is evicted and falls back to a normal recompile.

Programs that cannot be fingerprinted stably (e.g. ``py_func`` ops
holding Python callables) or whose export fails (unexportable custom
calls) silently skip the disk tier — the in-memory LRU still works.

Consumers: ``executor.run`` and ``_run_dataset_scan`` (training step
executables), ``fluid.inference.Predictor`` (``kind="predict"``
entries, one per feed-shape signature), and through the predictor the
serving engine's shape-bucket warmup (``paddle_tpu.serving``) — a
restarted server deserializes its whole bucket ladder instead of
compiling.

Every entry is sealed in an integrity envelope
(:mod:`paddle_tpu.integrity.envelope`): a content digest is verified
*before* ``jax.export`` deserialization, so a bitflipped blob is caught
by the digest check rather than by whatever the deserializer happens to
notice. Both failure classes share the evict-and-recompile path but are
counted separately — ``compile_cache.corrupt_digest`` (envelope check
failed) vs ``compile_cache.corrupt_deserialize`` (digest fine, decoder
rejected it; points at a format/version skew, not disk rot) — with
``compile_cache.corrupt`` as the total. Reads and writes route through
the ``load`` / ``save`` corruption fault sites
(:func:`paddle_tpu.fluid.resilience.fault_corrupt`) for chaos drills.

Telemetry (``paddle_tpu.observability``): ``compile_cache.disk_hit`` /
``disk_miss`` / ``corrupt`` / ``corrupt_digest`` /
``corrupt_deserialize`` / ``store`` / ``store_error`` counters and
``compile_cache.deserialize_seconds`` / ``serialize_seconds``
histograms.
"""
import hashlib
import os
import threading
import time
import uuid
import warnings

import numpy as np

from .. import observability as obs

__all__ = [
    "CACHE_DIR_ENV", "Unfingerprintable", "activate", "cache_dir",
    "enabled", "entry_key", "fingerprint_or_none", "has", "load",
    "program_fingerprint", "store",
]

CACHE_DIR_ENV = "PADDLE_TPU_COMPILE_CACHE_DIR"
# v2: entries are sealed in an integrity envelope (digest-before-
# deserialize); v1 blobs simply miss under the new keys and re-fill.
_FORMAT_VERSION = 2
_SUFFIX = ".jaxexp"
_ENTRY_KIND = "compile-cache"

_lock = threading.Lock()
_default_dir = None     # programmatic activation (TrainGuard co-location)
_xla_cache_set = False
_warned_store = False


class Unfingerprintable(ValueError):
    """The program holds state that has no stable cross-process identity
    (a Python callable attr, an unknown attr type) — the disk tier is
    skipped for it."""


def cache_dir():
    """The active cache directory: the env var wins, then a programmatic
    :func:`activate`, else None (disk tier off)."""
    return os.environ.get(CACHE_DIR_ENV) or _default_dir


def enabled():
    return cache_dir() is not None


def activate(path, configure_xla_cache=True):
    """Programmatically enable the disk tier at `path` (the env var, when
    set, still wins — an operator override beats code defaults). Returns
    the previously configured default. Also points jax's persistent XLA
    compilation cache at ``<path>/xla`` (best effort, once per process)
    so backend compiles of deserialized modules are cached too."""
    global _default_dir
    with _lock:
        prev, _default_dir = _default_dir, (
            os.path.abspath(path) if path else None)
    if path and configure_xla_cache:
        _configure_xla_cache(os.path.join(os.path.abspath(path), "xla"))
    return prev


def _configure_xla_cache(path):
    global _xla_cache_set
    with _lock:
        if _xla_cache_set:
            return
        _xla_cache_set = True
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
    except Exception:  # noqa: BLE001 — the XLA cache is an optimization only
        pass


# ---------------------------------------------------------------------------
# fingerprinting
# ---------------------------------------------------------------------------

def _stable(v):
    """A stable textual identity for an op attr / var field. Raises
    Unfingerprintable for values with no cross-process identity."""
    if v is None or isinstance(v, (bool, int, str, bytes)):
        return repr(v)
    if isinstance(v, float):
        return repr(float(v))
    if isinstance(v, (np.bool_, np.integer, np.floating)):
        return repr(v.item())
    if isinstance(v, (list, tuple)):
        return "[%s]" % ",".join(_stable(x) for x in v)
    if isinstance(v, dict):
        return "{%s}" % ",".join(
            "%s:%s" % (repr(k), _stable(v[k]))
            for k in sorted(v, key=repr))
    if isinstance(v, np.ndarray):
        return "nd(%s,%s,%s)" % (
            v.shape, v.dtype,
            hashlib.sha256(np.ascontiguousarray(v).tobytes()).hexdigest())
    if isinstance(v, np.dtype):
        return "dtype(%s)" % v
    raise Unfingerprintable(
        "attr of type %s has no stable cross-process identity"
        % type(v).__name__)


def program_fingerprint(program):
    """Content hash of the program graph: op types, input/output slot
    wiring, attrs, and var metadata across every block. Stable across
    processes (unlike ``Program._uid``); cached on the program keyed by
    its ``_version`` so repeat misses don't re-walk the graph."""
    cached = getattr(program, "_fingerprint_cache", None)
    if cached is not None and cached[0] == program._version:
        return cached[1]
    h = hashlib.sha256()
    for blk in program.blocks:
        h.update(b"blk")
        for name in sorted(blk.vars):
            v = blk.vars[name]
            h.update(("v:%s|%s|%s|%s|%s|%s\n" % (
                name, v.shape, v.dtype, v.type, int(v.persistable),
                v.lod_level)).encode())
        for op in blk.ops:
            h.update(("o:%s\n" % op.type).encode())
            for slot in sorted(op.inputs):
                h.update(("i:%s=%s\n" % (slot, op.inputs[slot])).encode())
            for slot in sorted(op.outputs):
                h.update(("u:%s=%s\n" % (slot, op.outputs[slot])).encode())
            for k in sorted(op.attrs):
                if k.startswith("_"):
                    continue  # provenance/bookkeeping, not semantics
                h.update(("a:%s=%s\n" % (k, _stable(op.attrs[k]))).encode())
    fp = h.hexdigest()
    program._fingerprint_cache = (program._version, fp)
    return fp


def fingerprint_or_none(program):
    """:func:`program_fingerprint`, degraded to None instead of raising
    — the identity key observability consumers (the executable ledger)
    use, where an unfingerprintable program just means an anonymous
    entry, never a failed step."""
    try:
        return program_fingerprint(program)
    except Exception:  # noqa: BLE001 — ledger identity is best-effort
        return None


def _device_fingerprint():
    import jax
    import jaxlib

    d = jax.devices()[0]
    return "%s|%s|jax=%s|jaxlib=%s|fmt=%d" % (
        d.platform, getattr(d, "device_kind", ""), jax.__version__,
        jaxlib.__version__, _FORMAT_VERSION)


def entry_key(program, feed_names, fetch_names, feed_sig, state_sig,
              platform, kind="step"):
    """The content-addressed disk key for one compiled specialization.
    Raises :class:`Unfingerprintable` when the program can't be hashed
    stably (caller skips the disk tier)."""
    h = hashlib.sha256()
    h.update(program_fingerprint(program).encode())
    h.update(repr((kind, platform, list(feed_names), list(fetch_names),
                   feed_sig, state_sig)).encode())
    h.update(_device_fingerprint().encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# the disk tier
# ---------------------------------------------------------------------------

class _DiskEntry:
    """Adapter giving a deserialized ``jax.export.Exported`` the same
    call surface as an AOT-compiled executable: ``entry(state, feeds,
    rng) -> (fetches, new_state)``. Note: a deserialized call does not
    donate input buffers (export drops donation) — a minor memory/perf
    cost relative to the compile it skips."""

    __slots__ = ("_exported", "key")

    def __init__(self, exported, key):
        self._exported = exported
        self.key = key

    def __call__(self, *args):
        return self._exported.call(*args)


def _entry_path(key):
    return os.path.join(cache_dir(), key + _SUFFIX)


def has(key):
    """Whether an artifact for `key` is on disk, without deserializing
    it (and without touching the hit/miss counters) — the cheap probe
    warm-start reporting uses. False when the disk tier is off."""
    d = cache_dir()
    return d is not None and os.path.exists(_entry_path(key))


def _evict_corrupt(path, key, check, error):
    """Shared corrupt-entry path: count which check failed (the
    envelope digest vs the jax.export deserializer), event it, and
    evict so a recompile fills the entry back."""
    obs.inc("compile_cache.corrupt")
    obs.inc("compile_cache.corrupt_%s" % check)
    obs.event("compile_cache_corrupt", source="executor", count=False,
              key=key, check=check,
              error="%s: %s" % (type(error).__name__, error))
    try:
        os.remove(path)
    except OSError:
        pass


def load(key):
    """Fetch the compiled artifact for `key` from disk, or None. Hits
    verify the envelope digest, then deserialize via ``jax.export``;
    corrupt/unreadable entries are removed and treated as misses
    (recompile fills them back), counting which check caught them."""
    from ..integrity import envelope
    from .resilience import fault_corrupt

    d = cache_dir()
    if d is None:
        return None
    path = _entry_path(key)
    try:
        with open(path, "rb") as f:
            raw = fault_corrupt("load", f.read())
    except OSError:
        obs.inc("compile_cache.disk_miss")
        return None
    t0 = time.monotonic()
    try:
        blob = envelope.unseal_bytes(raw, kind=_ENTRY_KIND, path=path)
    except IOError as e:  # IntegrityError — digest caught it first
        _evict_corrupt(path, key, "digest", e)
        return None
    try:
        from jax import export as jax_export

        entry = _DiskEntry(jax_export.deserialize(blob), key)
    except Exception as e:  # noqa: BLE001 — corrupt entry == miss
        _evict_corrupt(path, key, "deserialize", e)
        return None
    dt = time.monotonic() - t0
    obs.inc("compile_cache.disk_hit")
    obs.observe("compile_cache.deserialize_seconds", dt)
    obs.event("compile_cache_hit", source="executor", count=False,
              key=key, seconds=round(dt, 6), bytes=len(blob))
    return entry


def store(key, jitted, args):
    """Serialize the jitted function's AOT lowering for `args` to disk
    under `key` (atomic tmp+rename; concurrent writers race benignly —
    last replace wins with identical content). Failures warn once and
    are otherwise ignored: the cache is an optimization, never a
    correctness dependency."""
    from ..integrity import envelope
    from .resilience import fault_corrupt

    global _warned_store
    d = cache_dir()
    if d is None:
        return False
    t0 = time.monotonic()
    try:
        from jax import export as jax_export

        blob = jax_export.export(jitted)(*args).serialize()
        sealed = fault_corrupt(
            "save", envelope.seal_bytes(blob, kind=_ENTRY_KIND))
        os.makedirs(d, exist_ok=True)
        path = _entry_path(key)
        tmp = "%s.tmp.%d.%s" % (path, os.getpid(), uuid.uuid4().hex[:8])
        with open(tmp, "wb") as f:
            f.write(sealed)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except Exception as e:  # noqa: BLE001 — never fail a step over the cache
        obs.inc("compile_cache.store_error")
        if not _warned_store:
            _warned_store = True
            warnings.warn(
                "compile cache store failed (%s: %s); this program will "
                "recompile in future processes" % (type(e).__name__, e))
        return False
    dt = time.monotonic() - t0
    obs.inc("compile_cache.store")
    obs.observe("compile_cache.serialize_seconds", dt)
    obs.event("compile_cache_store", source="executor", count=False,
              key=key, seconds=round(dt, 6), bytes=len(blob))
    return True
