"""fluid.distributed.fleet (ref: distributed/fleet.py — the earliest
MPI-era fleet wrapper around Downpour)."""

__all__ = ["Fleet"]


class Fleet(object):
    """ref fleet.py — init_worker/init_server over an MPI transport.
    Superseded twice even in the reference; here the working surfaces
    are fleet.parameter_server.pslib (Downpour tables as mesh-sharded
    embeddings) and the collective fleet. Every method points there."""

    _MSG = (
        "fluid.distributed.fleet is the retired MPI-era fleet; use "
        "fluid.incubate.fleet.parameter_server.pslib (sparse-table "
        "CTR training on the mesh) or "
        "fluid.incubate.fleet.collective (dp/tp/sp/ZeRO/LocalSGD)"
    )

    def init(self, *a, **kw):
        raise NotImplementedError(self._MSG)

    init_worker = init
    init_server = init
    stop_server = init
    run_server = init
    stop = init
