"""fluid.distributed.ps_instance (ref: distributed/ps_instance.py —
MPI-split pserver/trainer role assignment)."""

__all__ = ["PaddlePSInstance"]


class PaddlePSInstance(object):
    """ref ps_instance.py:17 — splits an MPI world into servers and
    workers. No MPI world and no server processes exist here: every
    process is a worker over the mesh (the chips hold the tables)."""

    def __init__(self, server_worker_mode=1, proc_per_node=2):
        raise NotImplementedError(
            "PaddlePSInstance carves an MPI world into pserver/trainer "
            "roles; on TPU all processes are workers over the mesh "
            "(tables live sharded in HBM). Use "
            "fleet.parameter_server.pslib (worker-only) or the "
            "collective fleet."
        )
