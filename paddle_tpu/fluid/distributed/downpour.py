"""DownpourSGD — the pre-fleet Downpour distributed optimizer
(ref: python/paddle/fluid/distributed/downpour.py:24-168).

Reference flow: find the distributed lookup table, register sparse +
dense (+ data-norm) tables on DownpourServer/Worker protobufs, append
backward, and SKIP the lookup_table ops on workers (pservers apply the
sparse updates asynchronously).

TPU mapping: same discovery and table registry (dict descs), but the
sparse table shards its vocab over the mesh and updates inside the
synchronous step, so ``worker_skipped_ops`` is empty and the returned
``ps_param`` is the dict desc. The update ops come from an inner
SGD optimizer at this class's learning rate — Downpour's async "window"
staleness has no synchronous counterpart and is recorded only.
"""
from ..distribute_lookup_table import (
    find_distributed_lookup_table,
    find_distributed_lookup_table_inputs,
    find_distributed_lookup_table_outputs,
)
from .node import DownpourServer, DownpourWorker

__all__ = ["DownpourSGD"]


class DownpourSGD(object):
    """ref downpour.py:24."""

    def __init__(self, learning_rate=0.001, window=1):
        self.learning_rate_ = learning_rate
        self.window_ = window
        self.type = "downpour"
        self.data_norm_name = [
            ".batch_size", ".batch_square_sum", ".batch_sum",
        ]

    def minimize(self, losses, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .. import optimizer as optimizer_mod

        if not isinstance(losses, list):
            raise ValueError("losses is a list, just like [model.cost]")
        program = losses[0].block.program
        table_name = find_distributed_lookup_table(program)
        server = DownpourServer()
        worker = DownpourWorker(self.window_)
        sparse_idx = 0
        if table_name is not None:
            slots = find_distributed_lookup_table_inputs(
                program, table_name)
            slots_emb = find_distributed_lookup_table_outputs(
                program, table_name)
            server.add_sparse_table(
                sparse_idx, self.learning_rate_, slots, slots_emb)
            worker.add_sparse_table(
                sparse_idx, self.learning_rate_, slots, slots_emb)

        param_grads_list = []
        dense_idx = 1
        for loss in losses:
            opt = optimizer_mod.SGD(self.learning_rate_)
            _, params_grads = opt.minimize(
                loss, startup_program, parameter_list, no_grad_set)
            params_grads = sorted(params_grads, key=lambda x: x[0].name)
            param_grads_list.append(params_grads)
            dense, dnorm = [], []
            for p, g in params_grads:
                (dnorm if any(p.name.endswith(s)
                              for s in self.data_norm_name)
                 else dense).append((p, g))
            server.add_dense_table(
                dense_idx, self.learning_rate_,
                [p for p, _ in dense], [g for _, g in dense])
            worker.add_dense_table(
                dense_idx, self.learning_rate_,
                [p for p, _ in dense], [g for _, g in dense])
            if dnorm:
                dense_idx += 1
                server.add_data_norm_table(
                    dense_idx, self.learning_rate_,
                    [p for p, _ in dnorm], [g for _, g in dnorm])
                worker.add_dense_table(
                    dense_idx, self.learning_rate_,
                    [p for p, _ in dnorm], [g for _, g in dnorm])
            dense_idx += 1

        ps_param = {
            "server_param": server.get_desc(),
            "trainer_param": worker.get_desc(),
        }
        # nothing is remote on TPU: lookup_table runs inside the step
        worker_skipped_ops = []
        opt_info = {
            "trainer": "DistMultiTrainer",
            "device_worker": "DownpourSGD",
            "optimizer": "DownpourSGD",
            "fleet_desc": ps_param,
            "worker_skipped_ops": worker_skipped_ops,
        }
        for loss in losses:
            loss.block.program._fleet_opt = opt_info
        return ps_param, param_grads_list
