"""ps_pb2 (ref: the brpc parameter-server protobuf wire format).

No brpc servers exist on TPU — table configs are plain dict descs (see
node.py in this package family). Any protobuf symbol access raises
with that pointer so ref-era scripts fail loudly, not mysteriously.
"""

__all__ = []


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    raise NotImplementedError(
        "ps_pb2.%s: the brpc pserver protobufs have no TPU counterpart "
        "— DownpourServer/DownpourWorker carry dict descs instead "
        "(get_desc()), and tables run as mesh-sharded embeddings" % name
    )
