"""Old-style Downpour table-config carriers
(ref: python/paddle/fluid/distributed/node.py:17-160 — the pre-pslib
positional API: add_sparse_table(table_id, learning_rate, slot_key_vars,
slot_value_vars)). Dict descs instead of brpc protobufs; see the pslib
node module for the sharded-embedding mapping these configs feed.
"""

__all__ = ["Server", "Worker", "DownpourServer", "DownpourWorker"]


class Server(object):
    def __init__(self):
        self._desc = {}

    def get_desc(self):
        return self._desc


class Worker(object):
    def __init__(self):
        self._desc = {}

    def get_desc(self):
        return self._desc


class DownpourServer(Server):
    """ref node.py:35."""

    def __init__(self):
        super().__init__()
        self._desc = {
            "service": {
                "server_class": "DownpourBrpcPsServer",
                "client_class": "DownpourBrpcPsClient",
                "service_class": "DownpourPsService",
            },
            "tables": {},
        }

    def add_sparse_table(self, table_id, learning_rate, slot_key_vars,
                         slot_value_vars):
        self._desc["tables"][int(table_id)] = {
            "type": "sparse",
            "table_class": "DownpourSparseTable",
            "accessor_class": "DownpourFeatureValueAccessor",
            "learning_rate": float(learning_rate),
            "slot_key": [getattr(v, "name", v)
                         for v in (slot_key_vars or [])],
            "slot_value": [getattr(v, "name", v)
                           for v in (slot_value_vars or [])],
        }

    def add_dense_table(self, table_id, learning_rate, param_var, grad_var):
        self._desc["tables"][int(table_id)] = {
            "type": "dense",
            "table_class": "DownpourDenseTable",
            "accessor_class": "DownpourDenseValueAccessor",
            "learning_rate": float(learning_rate),
            "params": [getattr(p, "name", p) for p in (param_var or [])],
            "grads": [getattr(g, "name", g) for g in (grad_var or [])],
        }

    def add_data_norm_table(self, table_id, learning_rate, param_var,
                            grad_var):
        self.add_dense_table(table_id, learning_rate, param_var, grad_var)
        self._desc["tables"][int(table_id)]["data_norm"] = True


class DownpourWorker(Worker):
    """ref node.py:122."""

    def __init__(self, window=1):
        super().__init__()
        self.window = window
        self._desc = {"tables": {}}

    def add_sparse_table(self, table_id, learning_rate, slot_key_vars,
                         slot_value_vars):
        self._desc["tables"][int(table_id)] = {
            "type": "sparse",
            "learning_rate": float(learning_rate),
            "slot_key": [getattr(v, "name", v)
                         for v in (slot_key_vars or [])],
            "slot_value": [getattr(v, "name", v)
                           for v in (slot_value_vars or [])],
        }

    def add_dense_table(self, table_id, learning_rate, param_vars,
                        grad_vars):
        self._desc["tables"][int(table_id)] = {
            "type": "dense",
            "learning_rate": float(learning_rate),
            "params": [getattr(p, "name", p) for p in (param_vars or [])],
            "grads": [getattr(g, "name", g) for g in (grad_vars or [])],
        }
