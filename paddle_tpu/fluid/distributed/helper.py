"""fluid.distributed.helper (ref: distributed/helper.py — FileSystem
hdfs config carrier + MPIHelper)."""

__all__ = ["FileSystem", "MPIHelper"]


class FileSystem(object):
    """HDFS client config carrier (ref helper.py:16). The config is
    real; actual transfers go through the loud-raising HDFSClient
    (contrib.utils.hdfs_utils) — object stores replace HDFS here."""

    def __init__(self, fs_type="afs", uri="afs://***", user=None,
                 passwd=None, hadoop_bin=""):
        assert user is not None
        assert passwd is not None
        assert hadoop_bin is not None
        self.fs_client = {
            "fs.default.name": uri,
            "hadoop.job.ugi": "%s,%s" % (user, passwd),
            "fs_type": fs_type,
            "hadoop_bin": hadoop_bin,
        }

    def get_desc(self):
        return self.fs_client


class MPIHelper(object):
    """ref helper.py:54 — mpi4py rank/size discovery. There is no MPI
    launcher here; ranks come from jax.distributed / PADDLE_TRAINER_ID
    env (paddle_tpu.distributed.launch)."""

    def __init__(self):
        raise NotImplementedError(
            "MPIHelper: no MPI runtime on TPU hosts — process identity "
            "comes from paddle_tpu.distributed.launch (jax.distributed: "
            "PROCESS_ID / NUM_PROCESSES / COORDINATOR_ADDRESS env)"
        )
