"""fluid.distributed — the pre-fleet Downpour API
(ref: python/paddle/fluid/distributed/__init__.py)."""
from .downpour import DownpourSGD  # noqa: F401
from .node import DownpourServer, DownpourWorker, Server, Worker  # noqa: F401
