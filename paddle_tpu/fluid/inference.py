"""Inference engine — TPU-native rebuild of the reference's native predictor
(ref: paddle/fluid/inference/api/analysis_predictor.cc + api_impl.cc).

The reference interprets the inference ProgramDesc op-by-op with an
analysis/optimization pass pipeline. Here the whole pruned inference program
lowers to ONE pure function that is **AOT-compiled** with `jax.jit(...).
lower(...).compile()` per feed-shape signature: first call pays the XLA
compile, every later call is a single device dispatch with params resident
in HBM (the reference's zero-copy feed/fetch maps to device-resident
weights + host feeds).

    predictor = Predictor.from_model(dirname)          # load_inference_model
    out, = predictor.run({"x": batch})

Also covers the reference's TensorRT-style engine notion: the "engine" is
the compiled XLA executable; `predictor.profile()` reports compile/run
stats.

Engines resolve through ``fluid.compile_cache``'s disk tier when it is
active (``PADDLE_TPU_COMPILE_CACHE_DIR`` or ``compile_cache.activate``):
a fresh process deserializes the AOT artifact per feed signature instead
of paying XLA — the warm-start substrate ``paddle_tpu.serving`` builds
its pre-warmed shape buckets on. ``_get_exec`` is thread-safe: concurrent
callers of one signature serialize on a per-signature lock (one compile),
while different signatures compile in parallel.
"""
import threading
import time

import numpy as np

from . import compile_cache, core
from .executor import (Executor, Scope, global_scope, _device_kind,
                       _ledger_predict, _ledger_register,
                       _publish_analysis_gauges)
from .lowering import build_step_fn
from .. import observability as obs

__all__ = ["Predictor", "create_paddle_predictor"]


class Predictor:
    """AOT-compiled predictor over a pruned inference Program."""

    def __init__(self, program, feed_names, fetch_vars, scope=None,
                 place=None, dtype_policy=None):
        import jax

        self._jax = jax
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [
            v.name if hasattr(v, "name") else v for v in fetch_vars
        ]
        self.place = place or core.default_place()
        scope = scope if scope is not None else global_scope()
        persist = {}
        for v in program.list_vars():
            if getattr(v, "persistable", False) and v.name in scope:
                arr = scope[v.name]
                if dtype_policy == "bfloat16" and np.issubdtype(
                    np.asarray(arr).dtype, np.floating
                ):
                    arr = jax.numpy.asarray(arr, jax.numpy.bfloat16)
                persist[v.name] = jax.device_put(arr)
        self._state = persist
        platform = "cpu" if isinstance(self.place, core.CPUPlace) else "tpu"
        self._verify(platform)
        step = build_step_fn(
            program, self.feed_names, self.fetch_names, is_test=True,
            platform=platform,
        )

        def fwd(state, feeds):
            fetches, _ = step(state, feeds, jax.random.PRNGKey(0))
            return fetches

        self._fwd = fwd
        self._platform = platform
        self._compiled = {}  # shape signature -> executable
        # executable-ledger kind for this predictor's entries; serving
        # engines overwrite it ("serving:<name>", "decode.step:<name>")
        # so the perf CLI attributes executables to their engine
        self.ledger_tag = "predict"
        self.compile_seconds = {}
        # check-then-compile must be atomic per signature: without the
        # locks, N concurrent first callers of one shape all pay (and
        # race to publish) the same XLA compile
        self._lock = threading.Lock()
        self._sig_locks = {}
        self._state_sig = tuple(sorted(
            (k, tuple(v.shape), str(v.dtype)) for k, v in persist.items()))
        # feed dtype coercion targets (mirrors Executor._prepare_feeds):
        # convert ONCE at the prepare step, never again downstream
        block = program.global_block()
        self._want_dtypes = {}
        for n in self.feed_names:
            want = None
            if block.has_var(n):
                var = block.var(n)
                if var.dtype is not None:
                    want = core.np_dtype(var.dtype)
            self._want_dtypes[n] = want

    def _verify(self, platform):
        """Static-analysis gate at construction, BEFORE the first engine
        compile: a broken saved model (dangling param, un-computable
        fetch) fails here with op-attributed diagnostics instead of deep
        inside XLA. ``PADDLE_TPU_ANALYSIS=off|verify|full`` selects the
        depth; analyzer crashes are swallowed (the gate must never break
        a healthy model)."""
        from ..analysis import analyzer as _analyzer

        level = _analyzer.mode()
        if level == "off":
            return
        t0 = time.monotonic()
        try:
            report = _analyzer.analyze(
                self.program, feed_names=self.feed_names,
                fetch_names=self.fetch_names,
                state_names=set(self._state.keys()),
                state_specs=self._state, platform=platform,
                level=level, is_test=True, device_kind=_device_kind())
        except Exception as e:  # noqa: BLE001 — analyzer bug, not user's
            obs.event("analysis_failed", source="predictor",
                      error="%s: %s" % (type(e).__name__, e))
            return
        obs.observe("analysis.verify_seconds", time.monotonic() - t0)
        _publish_analysis_gauges(report)
        _ledger_predict(self.program, report.meta)
        if report.diagnostics:
            obs.inc("analysis.findings", len(report.findings))
            obs.event("analysis_report", source="predictor", count=False,
                      level=level, summary=report.summary())
        report.raise_if_errors()

    @classmethod
    def from_model(cls, dirname, model_filename=None, params_filename=None,
                   **kw):
        """Load a save_inference_model directory (ref api: load + build).

        Params land in a **private scope** per predictor (unless an
        explicit ``scope=`` is passed): two loaded models with
        overlapping var names — every default-named ``fc_0.w_0``, every
        BN stat — must not clobber each other through the process-wide
        ``global_scope()``."""
        from .io import load_inference_model

        exe = Executor(core.CPUPlace())
        scope = kw.pop("scope", None)
        if scope is None:
            scope = Scope()
        program, feed_names, fetch_vars = load_inference_model(
            dirname, exe, model_filename, params_filename, scope=scope
        )
        return cls(program, feed_names, fetch_vars, scope=scope, **kw)

    def _prepare(self, feeds):
        """Normalize one request: dict (or feed_names-aligned list) ->
        ({name: array}, shape signature). Each feed is converted at most
        ONCE — committed device arrays pass through untouched instead of
        bouncing off the host — and coerced to the program's declared
        feed dtype."""
        if not isinstance(feeds, dict):
            feeds = dict(zip(self.feed_names, feeds))
        jax = self._jax
        prepared = {}
        for n in self.feed_names:
            v = feeds[n]
            want = self._want_dtypes.get(n)
            if isinstance(v, jax.Array):
                if want is not None and v.dtype != want:
                    v = v.astype(want)
            else:
                v = np.asarray(v)
                if want is not None and v.dtype != want:
                    v = v.astype(want)
            prepared[n] = v
        sig = tuple(
            (n, tuple(prepared[n].shape), str(prepared[n].dtype))
            for n in self.feed_names
        )
        return prepared, sig

    def _sig(self, feeds):
        return self._prepare(feeds)[1]

    def _get_exec(self, feeds):
        prepared, sig = self._prepare(feeds)
        return self._ensure_exec(sig, prepared)[0]

    def _ensure_exec(self, sig, prepared):
        """The executable for `sig`, building it if needed. Returns
        ``(executable, source)`` with source one of ``"memory"`` /
        ``"disk"`` (compile-cache tier hit, no XLA) / ``"compile"``."""
        ex = self._compiled.get(sig)
        if ex is not None:
            return ex, "memory"
        with self._lock:
            sig_lock = self._sig_locks.setdefault(sig, threading.Lock())
        with sig_lock:
            ex = self._compiled.get(sig)
            if ex is not None:  # lost the race: the winner already built it
                return ex, "memory"
            jax = self._jax
            source = "compile"
            disk_key = None
            if compile_cache.enabled():
                try:
                    disk_key = compile_cache.entry_key(
                        self.program, self.feed_names, self.fetch_names,
                        sig, self._state_sig, self._platform,
                        kind="predict")
                except compile_cache.Unfingerprintable:
                    disk_key = None
                else:
                    ex = compile_cache.load(disk_key)
                    if ex is not None:
                        source = "disk"
                        _ledger_register(self.program, self.ledger_tag,
                                         ex, "disk")
            if ex is None:
                obs.event("compile_start", source="predictor", count=False,
                          sig=repr(sig))
                t0 = time.monotonic()
                jitted = jax.jit(self._fwd)
                ex = jitted.lower(self._state, prepared).compile()
                dt = time.monotonic() - t0
                self.compile_seconds[sig] = dt
                obs.observe("predictor.compile_seconds", dt)
                obs.event("compile_done", source="predictor", count=False,
                          sig=repr(sig), seconds=round(dt, 6))
                _ledger_register(self.program, self.ledger_tag, ex,
                                 "compile", compile_seconds=dt,
                                 donated=())
                if disk_key is not None:
                    compile_cache.store(
                        disk_key, jitted, (self._state, prepared))
            with self._lock:
                self._compiled[sig] = ex
            return ex, source

    def warm(self, feeds):
        """Ensure the executable for this feed signature exists without
        dispatching it; returns where it came from (``"memory"`` /
        ``"disk"`` / ``"compile"``). The serving engine pre-warms its
        shape buckets through this at model-load time."""
        prepared, sig = self._prepare(feeds)
        return self._ensure_exec(sig, prepared)[1]

    def run(self, feeds, return_numpy=True):
        """feeds: dict name -> array (or list aligned with feed_names)."""
        prepared, sig = self._prepare(feeds)
        outs = self._ensure_exec(sig, prepared)[0](self._state, prepared)
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return list(outs)

    __call__ = run

    def profile(self):
        return {
            "n_engines": len(self._compiled),
            "compile_seconds": dict(self.compile_seconds),
            "n_params": len(self._state),
        }


class AnalysisConfig:
    """Deployment config (ref: paddle/fluid/inference/api/
    paddle_analysis_config.h via core.AnalysisConfig). The reference's
    IR analysis passes / TensorRT / MKLDNN toggles are replaced by XLA's
    own pass pipeline; device selection maps to the jit platform. Knobs
    that can't apply on this stack are accepted and recorded so
    deployment scripts run unchanged."""

    def __init__(self, model_dir=None, params_file=None):
        self.model_dir = model_dir
        self.params_file = params_file
        self._use_gpu = False
        self._device_id = 0
        self._switches = {}

    # -- device ----------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # "gpu" in deployment scripts means "the accelerator": TPU here
        self._use_gpu = True
        self._device_id = device_id

    def disable_gpu(self):
        self._use_gpu = False

    def use_gpu(self):
        return self._use_gpu

    def gpu_device_id(self):
        return self._device_id

    # -- accepted no-op switches (XLA subsumes these passes) -------------
    def switch_ir_optim(self, x=True):
        self._switches["ir_optim"] = x

    def enable_tensorrt_engine(self, **kw):
        self._switches["tensorrt"] = kw

    def enable_mkldnn(self):
        self._switches["mkldnn"] = True

    def switch_use_feed_fetch_ops(self, x=False):
        self._switches["feed_fetch_ops"] = x

    def switch_specify_input_names(self, x=True):
        self._switches["specify_input_names"] = x

    def set_cpu_math_library_num_threads(self, n):
        self._switches["cpu_threads"] = n


def create_paddle_predictor(config_or_dirname, **kw):
    """ref inference api: create_paddle_predictor(AnalysisConfig | dir)."""
    if isinstance(config_or_dirname, str):
        return Predictor.from_model(config_or_dirname, **kw)
    if isinstance(config_or_dirname, AnalysisConfig):
        cfg = config_or_dirname
        if not cfg.model_dir:
            raise ValueError("AnalysisConfig has no model_dir set")
        from . import core

        place = core.TPUPlace() if cfg.use_gpu() else core.CPUPlace()
        return Predictor.from_model(cfg.model_dir, place=place, **kw)
    raise TypeError(
        "pass an AnalysisConfig or a save_inference_model dirname"
    )
