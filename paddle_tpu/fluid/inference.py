"""Inference engine — TPU-native rebuild of the reference's native predictor
(ref: paddle/fluid/inference/api/analysis_predictor.cc + api_impl.cc).

The reference interprets the inference ProgramDesc op-by-op with an
analysis/optimization pass pipeline. Here the whole pruned inference program
lowers to ONE pure function that is **AOT-compiled** with `jax.jit(...).
lower(...).compile()` per feed-shape signature: first call pays the XLA
compile, every later call is a single device dispatch with params resident
in HBM (the reference's zero-copy feed/fetch maps to device-resident
weights + host feeds).

    predictor = Predictor.from_model(dirname)          # load_inference_model
    out, = predictor.run({"x": batch})

Also covers the reference's TensorRT-style engine notion: the "engine" is
the compiled XLA executable; `predictor.profile()` reports compile/run
stats.
"""
import time

import numpy as np

from . import core
from .executor import Executor, global_scope
from .lowering import build_step_fn

__all__ = ["Predictor", "create_paddle_predictor"]


class Predictor:
    """AOT-compiled predictor over a pruned inference Program."""

    def __init__(self, program, feed_names, fetch_vars, scope=None,
                 place=None, dtype_policy=None):
        import jax

        self._jax = jax
        self.program = program
        self.feed_names = list(feed_names)
        self.fetch_names = [
            v.name if hasattr(v, "name") else v for v in fetch_vars
        ]
        self.place = place or core.default_place()
        scope = scope if scope is not None else global_scope()
        persist = {}
        for v in program.list_vars():
            if getattr(v, "persistable", False) and v.name in scope:
                arr = scope[v.name]
                if dtype_policy == "bfloat16" and np.issubdtype(
                    np.asarray(arr).dtype, np.floating
                ):
                    arr = jax.numpy.asarray(arr, jax.numpy.bfloat16)
                persist[v.name] = jax.device_put(arr)
        self._state = persist
        platform = "cpu" if isinstance(self.place, core.CPUPlace) else "tpu"
        step = build_step_fn(
            program, self.feed_names, self.fetch_names, is_test=True,
            platform=platform,
        )

        def fwd(state, feeds):
            fetches, _ = step(state, feeds, jax.random.PRNGKey(0))
            return fetches

        self._fwd = fwd
        self._compiled = {}  # shape signature -> executable
        self.compile_seconds = {}

    @classmethod
    def from_model(cls, dirname, model_filename=None, params_filename=None,
                   **kw):
        """Load a save_inference_model directory (ref api: load + build)."""
        from .io import load_inference_model

        exe = Executor(core.CPUPlace())
        program, feed_names, fetch_vars = load_inference_model(
            dirname, exe, model_filename, params_filename
        )
        return cls(program, feed_names, fetch_vars, **kw)

    def _sig(self, feeds):
        return tuple(
            (n, tuple(np.asarray(feeds[n]).shape),
             str(np.asarray(feeds[n]).dtype))
            for n in self.feed_names
        )

    def _get_exec(self, feeds):
        sig = self._sig(feeds)
        ex = self._compiled.get(sig)
        if ex is None:
            jax = self._jax
            t0 = time.time()
            lowered = jax.jit(self._fwd).lower(self._state, feeds)
            ex = lowered.compile()
            self.compile_seconds[sig] = time.time() - t0
            self._compiled[sig] = ex
        return ex

    def run(self, feeds, return_numpy=True):
        """feeds: dict name -> array (or list aligned with feed_names)."""
        if not isinstance(feeds, dict):
            feeds = dict(zip(self.feed_names, feeds))
        feeds = {n: np.asarray(feeds[n]) for n in self.feed_names}
        outs = self._get_exec(feeds)(self._state, feeds)
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return outs

    __call__ = run

    def profile(self):
        return {
            "n_engines": len(self._compiled),
            "compile_seconds": dict(self.compile_seconds),
            "n_params": len(self._state),
        }


class AnalysisConfig:
    """Deployment config (ref: paddle/fluid/inference/api/
    paddle_analysis_config.h via core.AnalysisConfig). The reference's
    IR analysis passes / TensorRT / MKLDNN toggles are replaced by XLA's
    own pass pipeline; device selection maps to the jit platform. Knobs
    that can't apply on this stack are accepted and recorded so
    deployment scripts run unchanged."""

    def __init__(self, model_dir=None, params_file=None):
        self.model_dir = model_dir
        self.params_file = params_file
        self._use_gpu = False
        self._device_id = 0
        self._switches = {}

    # -- device ----------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # "gpu" in deployment scripts means "the accelerator": TPU here
        self._use_gpu = True
        self._device_id = device_id

    def disable_gpu(self):
        self._use_gpu = False

    def use_gpu(self):
        return self._use_gpu

    def gpu_device_id(self):
        return self._device_id

    # -- accepted no-op switches (XLA subsumes these passes) -------------
    def switch_ir_optim(self, x=True):
        self._switches["ir_optim"] = x

    def enable_tensorrt_engine(self, **kw):
        self._switches["tensorrt"] = kw

    def enable_mkldnn(self):
        self._switches["mkldnn"] = True

    def switch_use_feed_fetch_ops(self, x=False):
        self._switches["feed_fetch_ops"] = x

    def switch_specify_input_names(self, x=True):
        self._switches["specify_input_names"] = x

    def set_cpu_math_library_num_threads(self, n):
        self._switches["cpu_threads"] = n


def create_paddle_predictor(config_or_dirname, **kw):
    """ref inference api: create_paddle_predictor(AnalysisConfig | dir)."""
    if isinstance(config_or_dirname, str):
        return Predictor.from_model(config_or_dirname, **kw)
    if isinstance(config_or_dirname, AnalysisConfig):
        cfg = config_or_dirname
        if not cfg.model_dir:
            raise ValueError("AnalysisConfig has no model_dir set")
        from . import core

        place = core.TPUPlace() if cfg.use_gpu() else core.CPUPlace()
        return Predictor.from_model(cfg.model_dir, place=place, **kw)
    raise TypeError(
        "pass an AnalysisConfig or a save_inference_model dirname"
    )
