"""Executor + Scope.

TPU-native analogue of ref python/paddle/fluid/executor.py (Executor) and
paddle/fluid/framework/scope.cc. The Scope holds device-resident jax arrays;
Executor.run lowers the Program once per (program version, feed signature)
into a jitted step function with donated state, then replays it — so steady-
state training is a single XLA executable launch per iteration.
"""
import collections
import os
import time
import warnings

import numpy as np

import jax
import jax.numpy as jnp

from . import compile_cache
from . import core
from . import framework
from .framework import Program, Variable, default_main_program
from .lowering import OpLoweringError, build_step_fn
from .resilience import fault_check
from .. import observability as obs
from ..observability import runhealth as _runhealth
# stdlib-only runtime guard (PADDLE_TPU_SCOPE_SANITIZER); the hot-path
# cost with the sanitizer off is one module-bool check per Scope write
from ..analysis import concurrency as _conc
from ..analysis import sanitizer as _sanitizer

__all__ = ["Executor", "Scope", "global_scope", "scope_guard"]


def _device_kind():
    """The jax device kind the analysis gate prices against (None when
    devices are unavailable — the cost model then relies on the
    PADDLE_TPU_PEAK_FLOPS/HBM_BYTES/HBM_BW env overrides only)."""
    try:
        return getattr(jax.devices()[0], "device_kind", None)
    except Exception:  # noqa: BLE001 — no backend is not a gate failure
        return None


def _publish_analysis_gauges(report):
    """Mirror the analyzer's quantitative meta into the telemetry hub
    (documented in observability.__init__: analysis.predicted_*)."""
    peak = report.meta.get("predicted_peak_hbm_bytes")
    if peak is not None:
        obs.set_gauge("analysis.predicted_peak_hbm", peak)
    mfu = report.meta.get("predicted_mfu")
    if mfu is not None:
        obs.set_gauge("analysis.predicted_mfu", mfu)


def _ledger_register(program, kind, compiled, source,
                     compile_seconds=None, donated=None):
    """Register one executable in the process-wide ledger (best effort
    — the observatory must never break a step)."""
    try:
        obs.get_ledger().register(
            kind=kind,
            fingerprint=compile_cache.fingerprint_or_none(program),
            compiled=compiled, source=source,
            compile_seconds=compile_seconds, donated=donated)
    except Exception:  # noqa: BLE001 — ledger is observability only
        pass


def _ledger_predict(program, meta):
    """Attach the analyzer's prediction to the program's fingerprint so
    the ledger can report predicted-vs-XLA-vs-measured drift."""
    try:
        fp = compile_cache.fingerprint_or_none(program)
        if fp is not None:
            obs.get_ledger().note_prediction(fp, meta)
    except Exception:  # noqa: BLE001
        pass


class _TensorView:
    """Compat shim for `scope.find_var(name).get_tensor()` usage."""

    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get_tensor(self):
        return self._scope[self._name]

    def set(self, value, place=None):
        self._scope.set(self._name, value)

    def __array__(self, dtype=None):
        arr = np.asarray(self._scope[self._name])
        return arr.astype(dtype) if dtype else arr


class Scope:
    """name -> device array mapping (device-resident between runs)."""

    def __init__(self, parent=None):
        self._vars = {}
        self._parent = parent
        self._kids = []

    def set(self, name, value):
        self._vars[name] = value
        if _sanitizer._on:
            _sanitizer.record_write(self, name)

    def __getitem__(self, name):
        return self._vars[name]

    def __contains__(self, name):
        return name in self._vars

    def get(self, name, default=None):
        return self._vars.get(name, default)

    def keys(self):
        return self._vars.keys()

    def items(self):
        return self._vars.items()

    def pop(self, name, default=None):
        return self._vars.pop(name, default)

    def find_var(self, name):
        """Look up a var here or in any ancestor scope (ref
        framework/scope.cc Scope::FindVar parent-chain semantics)."""
        scope = self
        while scope is not None:
            if name in scope._vars:
                return _TensorView(scope, name)
            scope = scope._parent
        return None

    def find_value(self, name, default=None):
        """Parent-chain value lookup (FindVar semantics, raw value)."""
        scope = self
        while scope is not None:
            if name in scope._vars:
                return scope._vars[name]
            scope = scope._parent
        return default

    def update(self, name, value):
        """Write to the scope in the chain that owns `name` (the reference
        executor updates the variable FindVar resolves, not a shadow copy
        in the child scope); falls back to a local set for new names."""
        scope = self
        while scope is not None:
            if name in scope._vars:
                scope._vars[name] = value
                if _sanitizer._on:
                    _sanitizer.record_write(scope, name)
                return
            scope = scope._parent
        self._vars[name] = value
        if _sanitizer._on:
            _sanitizer.record_write(self, name)

    def var(self, name):
        return _TensorView(self, name)

    def new_scope(self):
        kid = Scope(parent=self)
        self._kids.append(kid)
        return kid

    def drop_kids(self):
        self._kids = []


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


class scope_guard:
    def __init__(self, scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack.append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _scope_stack.pop()


def _as_name(v):
    if isinstance(v, Variable):
        return v.name
    if isinstance(v, str):
        return v
    raise TypeError("fetch/feed entry must be Variable or str, got %r" % (v,))


_aot_warned = False


class Executor:
    """Runs Programs. `place` selects the XLA backend (TPUPlace/CPUPlace)."""

    def __init__(self, place=None):
        self.place = place if place is not None else core.default_place()
        # compiled-executable cache, LRU-bounded: every entry pins an
        # XLA executable (and its host-side constants); long-running
        # multi-program processes would otherwise grow without bound
        self._cache = collections.OrderedDict()
        self._cache_cap = int(
            os.environ.get("PADDLE_TPU_EXECUTOR_CACHE_CAP", 32)
        )
        self._run_counter = 0
        self._closed = False
        self._verified = set()  # signatures the analyzer already gated

    # ------------------------------------------------------------------
    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        feed_var_name="feed",
        fetch_var_name="fetch",
        scope=None,
        return_numpy=True,
        use_program_cache=True,
        use_prune=False,
    ):
        if self._closed:
            raise RuntimeError("Executor is closed")
        # fault-injection hook (resilience.FaultInjector): BEFORE the
        # reader pop so an injected run fault doesn't consume a batch —
        # a guarded retry re-runs the same step on the same data
        fault_check("run")
        program = program if program is not None else default_main_program()
        if not feed:
            # a started py_reader attached to the program supplies the
            # batch (ref: reader ops pulling from the C++ blocking queue);
            # raises core.EOFException at end of epoch. Checked BEFORE the
            # CompiledProgram/pipeline dispatch so every execution path
            # auto-feeds. CompiledProgram wraps the underlying Program.
            src = getattr(program, "_program", program)
            readers = getattr(src, "_py_readers", [])
            for reader in readers:
                batch = reader._next_feed()
                if batch is not None:
                    feed = dict(batch)
                    break
            else:
                self._check_unstarted_readers(src, readers)
        # CompiledProgram (data-parallel) delegates to its own runner
        if hasattr(program, "_executor_run"):
            return program._executor_run(
                self, feed, fetch_list, scope, return_numpy
            )
        # collective-transpiled programs (transpiler.collective) carry
        # their mesh runner; running the plain program runs it sharded
        dist = getattr(program, "_transpiled_dist", None)
        if dist is not None:
            return dist._executor_run(
                self, feed, fetch_list, scope, return_numpy
            )
        # PipelineOptimizer-annotated programs run the gpipe schedule
        info = getattr(program, "_parallel_info", None)
        if info and info.get("mode") == "pipeline" and not getattr(
            program, "_is_start_up_program", False
        ):
            from .pipeline_executor import run_pipeline_program

            return run_pipeline_program(
                self, program, feed or {}, fetch_list or [],
                scope if scope is not None else global_scope(),
                return_numpy,
            )
        scope = scope if scope is not None else global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [_as_name(f) for f in fetch_list]

        # run-health phase split: three monotonic reads per step when a
        # run-health bundle is active (TrainGuard pops the result right
        # after this call returns), zero timestamps otherwise
        rh_on = _runhealth.active() is not None
        t_feed0 = time.monotonic() if rh_on else 0.0
        with obs.span("executor.run"):
            with obs.span("executor.feed_convert"):
                feed_arrays = self._prepare_feeds(program, feed)
                state = self._gather_state(program, scope)
            t_feed1 = time.monotonic() if rh_on else 0.0

            sig = (
                program._uid,
                program._version,
                tuple(sorted((k, v.shape, str(v.dtype)) for k, v in feed_arrays.items())),
                tuple(fetch_names),
                tuple(sorted((k, v.shape, str(v.dtype)) for k, v in state.items())),
            )
            rng = self._next_rng(program)
            platform = "cpu" if isinstance(self.place, core.CPUPlace) else "tpu"
            entry = self._cache_lookup(sig) if use_program_cache else None
            if entry is None and sig not in self._verified:
                # first compile of this signature: gate it on the static
                # analyzer (PADDLE_TPU_ANALYSIS=off|verify|full) — a
                # broken program fails HERE with op-attributed
                # diagnostics instead of deep inside lowering/XLA
                self._verify_first_compile(
                    program, feed_arrays, state, fetch_names, platform)
                self._verified.add(sig)
            disk_key = None
            if entry is None and use_program_cache and compile_cache.enabled():
                # disk tier: a hit deserializes the AOT artifact in ms and
                # emits NO compile_start — warm processes skip the compile
                try:
                    disk_key = compile_cache.entry_key(
                        program, list(feed_arrays.keys()), fetch_names,
                        sig[2], sig[4], platform)
                except compile_cache.Unfingerprintable:
                    disk_key = None
                else:
                    entry = compile_cache.load(disk_key)
                    if entry is not None:
                        self._cache_store(sig, entry)
                        _ledger_register(program, "executor", entry,
                                         "disk")
            if entry is None:
                obs.inc("executor.cache_miss")
                obs.event("compile_start", source="executor", count=False,
                          program=program._uid, version=program._version)
                t_compile = time.monotonic()
                step = build_step_fn(
                    program, list(feed_arrays.keys()), fetch_names,
                    platform=platform,
                )
                jitted = jax.jit(step, donate_argnums=(0,))
                # AOT-compile: freezes one executable for this signature. Without
                # this, the donated state outputs come back in compiler-chosen
                # layouts, and the SECOND run would retrace+recompile the whole
                # module against those layouts (a full minutes-long compile for a
                # big model). The AOT executable instead relayouts inputs on
                # device, so run 2+ reuse the same binary.
                aot_ok = True
                try:
                    entry = jitted.lower(state, feed_arrays, rng).compile()
                except OpLoweringError:
                    raise  # user graph error (missing feed, bad shape, ...)
                except Exception as e:
                    global _aot_warned
                    aot_ok = False
                    if not _aot_warned:
                        _aot_warned = True
                        warnings.warn(
                            "AOT compile failed (%s: %s); falling back to traced "
                            "jit — expect one redundant recompile on the second "
                            "run of each program" % (type(e).__name__, e)
                        )
                    entry = jitted  # fall back to the tracing path
                if aot_ok and disk_key is not None:
                    # persist the AOT artifact so the NEXT process (crash
                    # resume, repeat bench) skips this compile entirely
                    compile_cache.store(
                        disk_key, jitted, (state, feed_arrays, rng))
                dt_compile = time.monotonic() - t_compile
                _runhealth.goodput_note("compile", dt_compile)
                obs.observe("executor.compile_seconds", dt_compile)
                obs.event("compile_done", source="executor", count=False,
                          program=program._uid, version=program._version,
                          seconds=round(dt_compile, 6))
                _ledger_register(program, "executor", entry, "compile",
                                 compile_seconds=dt_compile,
                                 donated=sorted(state.keys()))
                if use_program_cache:
                    self._cache_store(sig, entry)
            else:
                obs.inc("executor.cache_hit")

            if _conc._on:
                # dispatch donates the state buffers: flag captures of
                # them (serving engines sharing this scope) and any lock
                # held across the blocking device call
                from ..analysis import dataflow as _dataflow

                _dataflow.note_donation(scope, state)
                _conc.note_blocking("device.dispatch")
            t_comp0 = time.monotonic() if rh_on else 0.0
            with obs.span("executor.device_compute"):
                try:
                    fetches, new_state = entry(state, feed_arrays, rng)
                except Exception:
                    # cache-safe re-run: a failed dispatch may have consumed the
                    # donated state buffers or left the executable poisoned —
                    # evict so a guarded retry recompiles against fresh state
                    # instead of replaying a dead executable
                    if self._cache.pop(sig, None) is not None:
                        obs.inc("executor.cache_evict")
                    raise
                if obs.trace_enabled():
                    # trace mode: make the span measure true device time
                    # (dispatch is async; only block when asked — blocking
                    # every step would serialize the pipeline)
                    for v in fetches:
                        if hasattr(v, "block_until_ready"):
                            v.block_until_ready()
                    for v in new_state.values():
                        if hasattr(v, "block_until_ready"):
                            v.block_until_ready()

            t_comp1 = time.monotonic() if rh_on else 0.0
            with obs.span("executor.fetch"):
                for k, v in new_state.items():
                    scope.update(k, v)
                if return_numpy:
                    result = [np.asarray(v) for v in fetches]
                else:
                    result = list(fetches)
            if rh_on:
                _runhealth.note_exec_phases(
                    feed_convert_s=t_feed1 - t_feed0,
                    compute_s=t_comp1 - t_comp0,
                    fetch_s=time.monotonic() - t_comp1)
            return result

    # ------------------------------------------------------------------
    def run_pipelined(self, program=None, feeds=None, fetch_list=None,
                      scope=None, return_numpy=True, depth=None,
                      window=None):
        """Pipelined step loop: returns an iterable of per-step fetch
        lists where host-side feed conversion + device transfer for
        batch N+1 overlap device compute for batch N (double-buffered
        staging thread), and fetches materialize lazily behind a bounded
        in-flight window. ``feeds`` is an iterable of feed dicts, or
        None to pull from the program's started py_reader until EOF.
        Step results are bit-identical to calling :meth:`run` in a loop
        — same feed preparation, same PRNG sequence, same dispatch
        order. See :mod:`paddle_tpu.fluid.async_pipeline`."""
        from .async_pipeline import PipelinedRunner

        return PipelinedRunner(
            self, program, feeds, fetch_list, scope,
            return_numpy=return_numpy, depth=depth, window=window)

    # ------------------------------------------------------------------
    def _run_dataset_scan(self, program, feed, k, scope):
        """Run ``k`` program steps in ONE device dispatch: the feed
        holds k stacked minibatches (leading dim k*bs) and the jitted
        body is ``lax.scan`` over the single-step function. This is the
        TPU-native analogue of the reference's Hogwild worker threads —
        they amortize per-batch framework overhead across C++ threads
        (ref executor.py train_from_dataset); here one XLA launch
        amortizes the host dispatch across k sequential steps.
        Bit-identical to k sequential run() calls: scan is sequential
        and the per-step PRNG keys consume the same _next_rng counter
        sequence. Raises OpLoweringError if the program's state
        structure is not scan-stable (caller falls back to single
        steps)."""
        scope = scope if scope is not None else global_scope()
        feed_arrays = self._prepare_feeds(program, feed)
        state = self._gather_state(program, scope)
        stacked = {}
        for name, v in feed_arrays.items():
            if v.shape[0] % k:
                raise OpLoweringError(
                    "dataset scan: feed %r rows %d not divisible by "
                    "k=%d" % (name, v.shape[0], k))
            stacked[name] = v.reshape((k, v.shape[0] // k) + v.shape[1:])
        counter_before = self._run_counter
        rngs = jnp.stack([self._next_rng(program) for _ in range(k)])
        sig = (
            "dataset_scan", k, program._uid, program._version,
            tuple(sorted((n, v.shape, str(v.dtype))
                         for n, v in stacked.items())),
            tuple(sorted((n, v.shape, str(v.dtype))
                         for n, v in state.items())),
        )
        platform = "cpu" if isinstance(self.place, core.CPUPlace) \
            else "tpu"
        entry = self._cache_lookup(sig)
        disk_key = None
        if entry is None and compile_cache.enabled():
            try:
                disk_key = compile_cache.entry_key(
                    program, list(stacked.keys()), [], sig[4], sig[5],
                    platform, kind="dataset_scan:%d" % k)
            except compile_cache.Unfingerprintable:
                disk_key = None
            else:
                entry = compile_cache.load(disk_key)
                if entry is not None:
                    self._cache_store(sig, entry)
                    _ledger_register(program, "executor.scan", entry,
                                     "disk")
        if entry is None:
            obs.inc("executor.cache_miss")
            t_compile = time.monotonic()
            step = build_step_fn(program, list(feed_arrays.keys()), [],
                                 platform=platform)
            state_keys = frozenset(state.keys())

            def multi(st, feeds_k, rngs_k):
                def body(carry, xs):
                    fd, rng = xs
                    _, new_st = step(carry, fd, rng)
                    if frozenset(new_st.keys()) != state_keys:
                        # trace-time structure check: scan carries must
                        # be stable; warmup single-steps create lazy
                        # state before this path engages
                        raise OpLoweringError(
                            "dataset scan: state keys changed inside "
                            "the step (%r)" % sorted(
                                frozenset(new_st.keys()) ^ state_keys))
                    return new_st, ()

                out, _ = jax.lax.scan(body, st, (feeds_k, rngs_k))
                return out

            jitted = jax.jit(multi, donate_argnums=(0,))
            try:
                entry = jitted.lower(state, stacked, rngs).compile()
            except Exception as e:
                # ANY compile failure (structure check, XLA resource
                # exhaustion on the k-step module, ...) means "fall
                # back to single steps". Nothing ran and nothing was
                # donated, so rewind the PRNG counter — the caller's
                # single-step replay must consume the SAME k keys or
                # reproducibility silently breaks.
                self._run_counter = counter_before
                raise OpLoweringError(
                    "dataset scan compile failed (%s: %s)"
                    % (type(e).__name__, str(e)[:200]))
            if disk_key is not None:
                compile_cache.store(disk_key, jitted,
                                    (state, stacked, rngs))
            dt_compile = time.monotonic() - t_compile
            obs.observe("executor.compile_seconds", dt_compile)
            _ledger_register(program, "executor.scan", entry, "compile",
                             compile_seconds=dt_compile,
                             donated=sorted(state.keys()))
            self._cache_store(sig, entry)
        else:
            obs.inc("executor.cache_hit")
        if _conc._on:
            from ..analysis import dataflow as _dataflow

            _dataflow.note_donation(scope, state)
            _conc.note_blocking("device.dispatch")
        new_state = entry(state, stacked, rngs)
        for name, v in new_state.items():
            scope.update(name, v)

    def _prepare_feeds(self, program, feed):
        block = program.global_block()
        out = {}
        feed = dict(feed)
        # LoDTensor feeds expand into (padded array, @SEQ_LEN lengths);
        # plain-array feeds of lod_level>0 vars default to full lengths
        for name in list(feed.keys()):
            v = feed[name]
            seq_name = name + "@SEQ_LEN"
            if not block.has_var(seq_name) or seq_name in feed:
                continue
            if getattr(v, "seq_lens", None) is not None:
                feed[seq_name] = np.asarray(v.seq_lens, dtype="int32")
            else:
                arr = getattr(v, "_ndarray", v)
                # .shape avoids a host copy for device arrays; plain
                # list/tuple feeds still go through np.asarray
                shape = arr.shape if hasattr(arr, "shape") else \
                    np.asarray(arr).shape
                feed[seq_name] = np.full(
                    (shape[0],), shape[1], dtype="int32"
                )
        dev = self.place.jax_device()
        ready = {}   # already device-resident (or device-bound) values
        host = {}    # host arrays, transferred in ONE batched device_put
        for name, value in feed.items():
            value = getattr(value, "_ndarray", value)  # LoDTensor shim
            want = None
            if block.has_var(name):
                var = block.var(name)
                if var.dtype is not None:
                    want = core.np_dtype(var.dtype)
            if isinstance(value, jax.Array):
                # already-device-resident feeds skip the host round-trip
                # entirely: a committed array on the target device passes
                # through untouched — re-feeding the same batch costs
                # nothing, which matters when the chip is reached over a
                # network tunnel
                if want is not None and value.dtype != want:
                    value = value.astype(want)
                if getattr(value, "committed", False) \
                        and dev in value.devices():
                    ready[name] = value
                else:
                    ready[name] = jax.device_put(value, dev)
                continue
            arr = np.asarray(value)
            if want is not None and arr.dtype != want:
                arr = arr.astype(want)
            host[name] = arr
        if host:
            # one device_put for every host-side feed: batched transfers
            # amortize the per-call dispatch overhead vs per-tensor puts
            ready.update(jax.device_put(host, dev))
        for name in feed:  # preserve feed order (part of the cache sig)
            out[name] = ready[name]
        return out

    def _gather_state(self, program, scope):
        state = {}
        for v in program.global_block().vars.values():
            if not v.persistable:
                continue
            val = scope.find_value(v.name)
            if val is not None:
                state[v.name] = val
        return state

    def _next_rng(self, program):
        self._run_counter += 1
        seed = program.random_seed
        if seed == 0:
            seed = abs(hash(("paddle_tpu", program._uid))) % (2**31)
        return jax.random.PRNGKey(seed + 1000003 * self._run_counter)

    @staticmethod
    def _check_unstarted_readers(program, readers):
        """No feed given and no attached reader produced a batch: if a
        decorated-but-unstarted reader feeds vars the program's ops
        actually consume, fail HERE with the fix, instead of deep in
        lowering with a missing-value error."""
        idle = [r for r in readers
                if r._paddle_reader is not None and not r._started]
        if not idle:
            return
        consumed = set()
        for op in program.global_block().ops:
            consumed.update(op.input_arg_names)
        for r in idle:
            needed = [v.name for v in r._feed_list if v.name in consumed]
            if needed:
                raise core.ReaderNotStartedError(
                    "Executor.run got no feed and py_reader %r (feeding "
                    "%s) is not started — call reader.start() before "
                    "run(); after core.EOFException call reader.reset() "
                    "then reader.start() for the next epoch"
                    % (r._name, ", ".join(needed))
                )

    def close(self):
        """Release cached executables and flush pending async orbax
        checkpoint writes (parallel.checkpoint.finalize) so a process
        exiting right after a wait=False save can't lose it. Idempotent."""
        if self._closed:
            return
        self._cache.clear()
        self._closed = True
        from ..parallel import checkpoint as _ckpt

        try:
            _ckpt.finalize()
        except Exception as e:  # noqa: BLE001 — closing must not raise
            warnings.warn("checkpoint finalize on Executor.close failed: "
                          "%s: %s" % (type(e).__name__, e))

    # -- static-analysis gate (paddle_tpu.analysis) --------------------
    def _verify_first_compile(self, program, feed_arrays, state,
                              fetch_names, platform):
        """Run the static analyzer before the first compile of a
        signature. ``verify`` (the default) is a pure-python structural
        walk; ``full`` adds shape/dtype propagation + TPU-lint; ``off``
        restores the pre-analyzer executor exactly. Verifier errors —
        the program would provably fail at lowering — raise
        :class:`~paddle_tpu.analysis.ProgramVerifyError` before any XLA
        work; everything else flows to the telemetry hub + flight
        recorder. Analyzer *crashes* are swallowed (a gate must never be
        the thing that breaks a healthy run)."""
        from ..analysis import analyzer as _analyzer

        level = _analyzer.mode()
        if level == "off":
            return
        t0 = time.monotonic()
        try:
            report = _analyzer.analyze(
                program, feed_names=list(feed_arrays.keys()),
                fetch_names=fetch_names, state_names=set(state.keys()),
                feed_specs=feed_arrays, state_specs=state,
                platform=platform, level=level,
                device_kind=_device_kind())
        except Exception as e:  # noqa: BLE001 — analyzer bug, not user's
            obs.event("analysis_failed", source="executor",
                      error="%s: %s" % (type(e).__name__, e))
            return
        obs.observe("analysis.verify_seconds", time.monotonic() - t0)
        _publish_analysis_gauges(report)
        _ledger_predict(program, report.meta)
        if report.diagnostics:
            obs.inc("analysis.findings", len(report.findings))
            obs.event("analysis_report", source="executor", count=False,
                      program=program._uid, version=program._version,
                      level=level, summary=report.summary())
        report.raise_if_errors()

    # -- compiled-executable LRU (shared by run + dataset-scan paths) --
    def _cache_lookup(self, sig):
        entry = self._cache.get(sig)
        if entry is not None:
            self._cache.move_to_end(sig)
        return entry

    def _cache_store(self, sig, entry):
        self._cache[sig] = entry
        while len(self._cache) > self._cache_cap:
            self._cache.popitem(last=False)
            obs.inc("executor.cache_evict")

    # -- dataset trainer path (ref executor.py:1033,1103) --------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """Consume every batch of ``dataset`` through the jitted program
        step (ref executor.py train_from_dataset). The reference fans the
        work across C++ Hogwild threads; here `thread` tunes host-side
        parsing parallelism and batches stage through the native C++
        slot ring, while ONE XLA stream runs the step with donated
        params (see fluid/dataset.py module docstring)."""
        return self._run_from_dataset(
            program, dataset, scope, thread, False, debug, fetch_list,
            fetch_info, print_period, fetch_handler)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           fetch_handler=None):
        """Like train_from_dataset but runs a test-pruned clone: the
        backward op, optimizer updates, and anything dataflow-dependent
        on them are dropped (post-minimize forward/metric ops survive),
        mirroring the reference's infer-mode skip_ops."""
        return self._run_from_dataset(
            program, dataset, scope, thread, True, debug, fetch_list,
            fetch_info, print_period, fetch_handler)

    def _run_from_dataset(self, program, dataset, scope, thread, is_infer,
                          debug, fetch_list, fetch_info, print_period,
                          fetch_handler):
        from .data_feeder import DataFeeder  # noqa: F401 (via loader)
        from .reader import _GeneratorLoader
        from .trainer_factory import FetchHandlerMonitor, TrainerFactory

        if dataset is None:
            raise ValueError(
                "train/infer_from_dataset requires a dataset (build one "
                "with fluid.DatasetFactory().create_dataset())"
            )
        program = program if program is not None else default_main_program()
        program = getattr(program, "_program", program)  # CompiledProgram
        run_prog = self._strip_training_ops(program) if is_infer else program
        # trainer desc for parity/introspection (Hogwild contract)
        trainer = TrainerFactory()._create_trainer(
            getattr(program, "_fleet_opt", None))
        trainer.device_worker._set_infer(is_infer)
        trainer._set_thread(thread or dataset.thread_num)

        dataset._prepare_to_run()
        dataset._dynamic_adjust_before_train(thread or dataset.thread_num)
        monitor = None
        if fetch_handler is not None:
            monitor = FetchHandlerMonitor(
                scope or global_scope(), fetch_handler)
            monitor.start()
        fetch_vars = list(fetch_list or [])
        infos = list(fetch_info or [
            getattr(v, "name", str(v)) for v in fetch_vars])
        # Reuse one loader (and its native C++ pipe: mlock'd arena +
        # worker pool) per (dataset, feed signature, place) across
        # train_from_dataset calls — the pipe setup measured ~0.4s, and
        # a small dataset's epoch is shorter than that
        # (bench_experiments/ctr_breakdown.py). The cache lives ON the
        # dataset so its lifetime tracks the data, not the executor.
        cache_key = (
            tuple(v.name for v in dataset.use_vars),
            type(self.place).__name__,
        )
        cached = getattr(dataset, "_loader_cache", None)
        if cached is not None and cached[0] == cache_key:
            loader = cached[1]
            # the key matches on NAMES; refresh the Variable objects so
            # a same-named feed list from a different program can't
            # feed through stale dtype/shape/lod metadata
            loader._feed_list = list(dataset.use_vars)
        else:
            loader = _GeneratorLoader(
                feed_list=dataset.use_vars, capacity=8)
            dataset._loader_cache = (cache_key, loader)
        # k steps per device dispatch (lax.scan over the step body) when
        # nothing forces a per-step host round-trip; fetches, debug
        # mode, and mesh/pipeline runners keep the single-step loop
        scan_k = max(1, int(os.environ.get(
            "PADDLE_TPU_DATASET_STEPS_PER_CALL", "8")))
        plain_prog = not (hasattr(run_prog, "_executor_run")
                          or getattr(run_prog, "_transpiled_dist", None)
                          or getattr(run_prog, "_parallel_info", None))
        use_scan = (scan_k > 1 and not fetch_vars and not debug
                    and plain_prog
                    and all(v.lod_level == 0 for v in dataset.use_vars))
        bs = dataset.batch_size
        loader.set_sample_list_generator(
            lambda: dataset._batch_iterator(
                thread, rows=scan_k * bs if use_scan else None),
            places=self.place)
        step = 0
        # warmth is per (program, scope): the single-step warmup creates
        # lazily-materialized persistable STATE, which lives in the
        # scope — a fresh scope needs its own warmup even for a warm
        # program (else scan engages unwarmed, trips the structure
        # check, and both the fallback and the optimization misfire)
        flag_scope = scope if scope is not None else global_scope()
        warm_uids = getattr(flag_scope, "_dataset_scan_warm", None)
        if warm_uids is None:
            warm_uids = set()
            flag_scope._dataset_scan_warm = warm_uids
        scan_warm = run_prog._uid in warm_uids
        scan_ok = True
        try:
            for feed in loader():
                if use_scan:
                    nrows = next(iter(feed.values())).shape[0]
                    k = nrows // bs if nrows % bs == 0 else 0
                    if k > 1 and scan_warm and scan_ok:
                        try:
                            self._run_dataset_scan(run_prog, feed, k,
                                                   scope)
                            step += k
                            continue
                        except OpLoweringError:
                            scan_ok = False  # unstable state: fall back
                    # warmup (or fallback / ragged tail): replay the
                    # super-batch as bs-sized single steps — the warmup
                    # creates any lazily-materialized state so later
                    # scan carries are structure-stable
                    for lo in range(0, nrows, bs):
                        sub = {n: v[lo:lo + bs] for n, v in feed.items()}
                        self.run(run_prog, feed=sub, scope=scope)
                        step += 1
                    scan_warm = True
                    warm_uids.add(run_prog._uid)
                    continue
                step += 1
                want_fetch = fetch_vars and (
                    debug or step % print_period == 0)
                out = self.run(
                    run_prog, feed=feed,
                    fetch_list=fetch_vars if want_fetch else None,
                    scope=scope,
                )
                if want_fetch:
                    msg = ", ".join(
                        "%s=%s" % (i, np.asarray(v).reshape(-1)[:8])
                        for i, v in zip(infos, out)
                    )
                    print("[dataset step %d] %s" % (step, msg))
        finally:
            if monitor is not None:
                monitor.stop()
            dataset._dynamic_adjust_after_train()
            dataset._finish_to_run()
        return None

    # per-param update op types (mirror of the reference infer-mode
    # skip-ops list: grad + optimizer ops)
    _OPT_UPDATE_TYPES = frozenset({
        "sgd", "momentum", "dgc_momentum", "lars_momentum", "adagrad",
        "decayed_adagrad", "adadelta", "adam", "adamax", "rmsprop",
        "ftrl", "lamb", "dpsgd",
    })

    @classmethod
    def _strip_training_ops(cls, program):
        """Clone with the training ops removed: the symbolic `backward`
        op, per-param update ops, and anything dataflow-dependent on
        their outputs (clip/regularizer/loss-scaling ops consuming @GRAD
        vars). Forward/metric ops appended AFTER minimize() survive —
        the reference infer mode skips op types, it doesn't truncate."""
        pruned = program.clone()
        block = pruned.global_block()
        dead = set()
        defined = set()  # vars produced by kept ops so far
        kept = []
        for op in block.ops:
            drop = (
                op.type == "backward"
                or op.type in cls._OPT_UPDATE_TYPES
                or any(n in dead for n in op.input_arg_names)
            )
            if drop:
                ins = set(op.input_arg_names)
                for n in op.output_arg_names:
                    # only fresh vars die: in-place writes, vars a kept
                    # op already produced, and persistable vars (their
                    # startup-initialized value stays valid — e.g. an
                    # AMP loss-scaling var whose update op is dropped)
                    var = block.vars.get(n)
                    if (n not in ins and n not in defined
                            and not (var is not None and var.persistable)):
                        dead.add(n)
            else:
                kept.append(op)
                defined.update(op.output_arg_names)
                dead.difference_update(op.output_arg_names)
        if len(kept) != len(block.ops):
            block.ops = kept
            pruned._bump_version()
        return pruned
