"""Gradient clipping (ref: python/paddle/fluid/clip.py)."""
import copy

from . import framework
from .framework import Variable

__all__ = [
    "ErrorClipByValue",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "set_gradient_clip",
]


class BaseErrorClipAttr:
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _append_clip_op(self, block, grad_name):
        block.append_op(
            type="clip",
            inputs={"X": [grad_name]},
            outputs={"Out": [grad_name]},
            attrs={"min": self.min, "max": self.max},
        )


def error_clip_callback(block, context):
    pass


class BaseGradientClipAttr:
    def _process_context(self, context, param, grad):
        raise NotImplementedError

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        block = grad.block
        new_grad = block.create_var(
            name=grad.name + "@CLIP", dtype=param.dtype, shape=param.shape
        )
        block.append_op(
            type="clip",
            inputs={"X": [grad]},
            outputs={"Out": [new_grad]},
            attrs={"min": self.min, "max": self.max},
        )
        return param, new_grad


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        block = grad.block
        new_grad = block.create_var(
            name=grad.name + "@CLIP", dtype=param.dtype, shape=param.shape
        )
        block.append_op(
            type="clip_by_norm",
            inputs={"X": [grad]},
            outputs={"Out": [new_grad]},
            attrs={"max_norm": self.clip_norm},
        )
        return param, new_grad


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """Global-norm clipping across all grads (ref clip.py)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        if self.group_name not in context:
            context[self.group_name] = []
            context[self.group_name + "_clip_value"] = self.clip_norm
        context[self.group_name].append((param, grad))

    def _create_operators(self, param, grad):
        # actual ops created in append_gradient_clip_ops group pass
        return param, grad


def set_gradient_clip(clip, param_list=None, program=None):
    if not isinstance(clip, BaseGradientClipAttr):
        raise TypeError("clip must be BaseGradientClipAttr")
    program = program or framework.default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    param_list = [
        program.global_block().var(p) if isinstance(p, str) else p
        for p in param_list
    ]
    for param in param_list:
        param.gradient_clip_attr = copy.deepcopy(clip)


def _global_norm_clip_group(params_grads, clip_norm):
    """Append ops computing g *= clip_norm / max(global_norm, clip_norm)."""
    from .layers import nn, tensor

    block = params_grads[0][1].block
    sq_sums = []
    for _, g in params_grads:
        sq = block.create_var(dtype=g.dtype, shape=())
        block.append_op(
            type="squared_l2_norm", inputs={"X": [g]}, outputs={"Out": [sq]}
        )
        sq_sums.append(sq)
    total = block.create_var(dtype="float32", shape=())
    block.append_op(
        type="sum", inputs={"X": sq_sums}, outputs={"Out": [total]}
    )
    gnorm = block.create_var(dtype="float32", shape=())
    block.append_op(
        type="sqrt", inputs={"X": [total]}, outputs={"Out": [gnorm]}
    )
    clip_var = block.create_var(dtype="float32", shape=())
    block.append_op(
        type="fill_constant",
        outputs={"Out": [clip_var]},
        attrs={"shape": [], "dtype": "float32", "value": clip_norm},
    )
    denom = block.create_var(dtype="float32", shape=())
    block.append_op(
        type="elementwise_max",
        inputs={"X": [gnorm], "Y": [clip_var]},
        outputs={"Out": [denom]},
        attrs={"axis": -1},
    )
    scale_v = block.create_var(dtype="float32", shape=())
    block.append_op(
        type="elementwise_div",
        inputs={"X": [clip_var], "Y": [denom]},
        outputs={"Out": [scale_v]},
        attrs={"axis": -1},
    )
    out = []
    for p, g in params_grads:
        ng = block.create_var(
            name=g.name + "@GCLIP", dtype=g.dtype, shape=g.shape
        )
        block.append_op(
            type="elementwise_mul",
            inputs={"X": [g], "Y": [scale_v]},
            outputs={"Out": [ng]},
            attrs={"axis": -1},
        )
        out.append((p, ng))
    return out


def append_gradient_clip_ops(param_grads):
    context = {}
    clips = []
    for p, g in param_grads:
        if g is None:
            continue
        clip_attr = getattr(p, "gradient_clip_attr", None)
        if clip_attr is None:
            clip_attr = NullGradientClipAttr()
        clip_attr._process_context(context, p, g)
        clips.append((p, g, clip_attr))

    res = []
    handled_groups = {}
    for p, g, clip_attr in clips:
        if isinstance(clip_attr, GradientClipByGlobalNorm):
            if clip_attr.group_name not in handled_groups:
                group = context[clip_attr.group_name]
                handled_groups[clip_attr.group_name] = dict(
                    (pp.name, (pp, gg))
                    for pp, gg in _global_norm_clip_group(
                        group, clip_attr.clip_norm
                    )
                )
            res.append(handled_groups[clip_attr.group_name][p.name])
        else:
            res.append(clip_attr._create_operators(p, g))
    # params without grads pass through
    for p, g in param_grads:
        if g is None:
            res.append((p, g))
    return res


# The reference's own docstrings import the dygraph GradClip* classes
# from fluid.clip (ref dygraph_grad_clip.py:70) — alias them here so
# both import paths ported scripts use resolve.
def _grad_clip_aliases():
    from .dygraph_grad_clip import (
        GradClipByGlobalNorm, GradClipByNorm, GradClipByValue,
    )

    return GradClipByValue, GradClipByNorm, GradClipByGlobalNorm


def __getattr__(name):
    if name in ("GradClipByValue", "GradClipByNorm",
                "GradClipByGlobalNorm"):
        v, n, g = _grad_clip_aliases()
        return {"GradClipByValue": v, "GradClipByNorm": n,
                "GradClipByGlobalNorm": g}[name]
    raise AttributeError("module 'fluid.clip' has no attribute %r" % name)
