"""ref import path python/paddle/fluid/distribute_lookup_table.py; the
discovery lives in transpiler/distribute_lookup_table.py, plus the
inputs/outputs helpers the reference exposes here."""
from .transpiler.distribute_lookup_table import (  # noqa: F401
    LOOKUP_TABLE_TYPES,
    find_distributed_lookup_table,
)

__all__ = [
    "find_distributed_lookup_table",
    "find_distributed_lookup_table_inputs",
    "find_distributed_lookup_table_outputs",
]


def find_distributed_lookup_table_inputs(program, table_name):
    local_vars = program.current_block().vars
    inputs = []
    for op in program.global_block().ops:
        if op.type in LOOKUP_TABLE_TYPES and \
                table_name == op.input("W")[0]:
            inputs.extend(local_vars[name] for name in op.input("Ids"))
    return inputs


def find_distributed_lookup_table_outputs(program, table_name):
    local_vars = program.current_block().vars
    outputs = []
    for op in program.global_block().ops:
        if op.type in LOOKUP_TABLE_TYPES and \
                table_name == op.input("W")[0]:
            outputs.extend(local_vars[name] for name in op.output("Out"))
    return outputs
