"""Python-side streaming metrics (ref: python/paddle/fluid/metrics.py)."""
import numpy as np

__all__ = [
    "MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
    "ChunkEvaluator", "EditDistance", "DetectionMAP", "Auc",
]


def _is_number_or_matrix(x):
    return isinstance(x, (int, float, np.ndarray)) or np.isscalar(x)


class MetricBase:
    def __init__(self, name):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        states = {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith("_")
        }
        for attr, value in states.items():
            if isinstance(value, int):
                setattr(self, attr, 0)
            elif isinstance(value, float):
                setattr(self, attr, 0.0)
            elif isinstance(value, (np.ndarray, np.generic)):
                setattr(self, attr, np.zeros_like(value))
            else:
                setattr(self, attr, None)

    def get_config(self):
        return {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith("_")
        }

    def update(self, preds, labels):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError("metric must be MetricBase")
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").flatten()
        labels = np.asarray(labels).astype("int32").flatten()
        for p, l in zip(preds, labels):
            if p == 1:
                if l == 1:
                    self.tp += 1
                else:
                    self.fp += 1

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").flatten()
        labels = np.asarray(labels).astype("int32").flatten()
        for p, l in zip(preds, labels):
            if l == 1:
                if p == 1:
                    self.tp += 1
                else:
                    self.fn += 1

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else 0.0


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError("value must be number or ndarray")
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("weight is 0; call update first")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (
            float(self.num_correct_chunks) / self.num_infer_chunks
            if self.num_infer_chunks
            else 0.0
        )
        recall = (
            float(self.num_correct_chunks) / self.num_label_chunks
            if self.num_label_chunks
            else 0.0
        )
        f1 = (
            2 * precision * recall / (precision + recall)
            if self.num_correct_chunks
            else 0.0
        )
        return precision, recall, f1


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        self.total_distance += float(distances.sum())
        self.seq_num += int(seq_num)
        self.instance_error += int((distances > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data updated")
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        bins = num_thresholds + 1
        self._stat_pos = np.zeros(bins)
        self._stat_neg = np.zeros(bins)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).flatten()
        pos_prob = preds[:, -1] if preds.ndim == 2 else preds.flatten()
        bins = np.clip(
            (pos_prob * self._num_thresholds).astype(int),
            0,
            self._num_thresholds,
        )
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.5
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


class DetectionMAP:
    """Graph mAP evaluator (ref metrics.py DetectionMAP): builds the
    detection_map op over the NMS output + padded gt and streams an
    in-graph running MEAN of per-batch mAPs through persistable state
    (the reference pools detection statistics across batches instead —
    with similarly-sized batches the two converge; per-batch pooling is
    what the static-shape op computes).

    Usage mirrors the reference::

        m = fluid.metrics.DetectionMAP(nms_out, gt_label, gt_box,
                                       gt_difficult, class_num=21)
        cur_map, accum_map = m.get_map_var()
        ... exe.run(fetch_list=[cur_map, accum_map]) per batch ...
        m.reset(exe)    # new evaluation pass
    """

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral"):
        from . import unique_name
        from .layers import detection, tensor
        from .layers.nn import elementwise_add, elementwise_div

        if class_num is None:
            raise ValueError("DetectionMAP needs class_num")
        parts = [tensor.cast(gt_label, "float32"), gt_box]
        if gt_difficult is not None:
            parts.append(tensor.cast(gt_difficult, "float32"))
        label = tensor.concat(parts, axis=-1)
        self._cur_map = detection.detection_map(
            input, label, class_num, background_label=background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_version=ap_version,
        )
        # streaming state rides the jitted step like optimizer state
        self._accum_value = tensor.create_global_var(
            shape=[1], value=0.0, dtype="float32", persistable=True,
            name=unique_name.generate("map_accum_value"),
        )
        self._accum_count = tensor.create_global_var(
            shape=[1], value=0.0, dtype="float32", persistable=True,
            name=unique_name.generate("map_accum_count"),
        )
        block = self._cur_map.block
        new_value = elementwise_add(
            self._accum_value,
            tensor.cast(self._cur_map, "float32"),
        )
        one = tensor.fill_constant([1], "float32", 1.0)
        new_count = elementwise_add(self._accum_count, one)
        self._accum_map = elementwise_div(new_value, new_count)
        block.append_op(
            type="assign", inputs={"X": [new_value]},
            outputs={"Out": [self._accum_value]},
        )
        block.append_op(
            type="assign", inputs={"X": [new_count]},
            outputs={"Out": [self._accum_count]},
        )

    def get_map_var(self):
        return self._cur_map, self._accum_map

    def reset(self, executor, reset_program=None):
        from .executor import global_scope

        scope = global_scope()
        scope.update(self._accum_value.name,
                     np.zeros((1,), np.float32))
        scope.update(self._accum_count.name,
                     np.zeros((1,), np.float32))
