"""Draw main/startup programs as one graphviz graph
(ref: python/paddle/fluid/net_drawer.py)."""
import json

from .graphviz import Graph

__all__ = ["draw_graph"]

OP_STYLE = {"shape": "box", "color": "#0F9D58", "style": "rounded"}
VAR_STYLE = {"shape": "ellipse"}


def unique_id():
    counter = [0]

    def gen():
        counter[0] += 1
        return counter[0]

    return gen


def draw_node(op):
    return "%s" % op.type


def parse_graph(program, graph, var_dict, **kwargs):
    for block in program.blocks:
        for op in block.ops:
            op_node = graph.add_node(draw_node(op), prefix="op", **OP_STYLE)
            for ns in op.inputs.values():
                for n in ns:
                    if n not in var_dict:
                        var_dict[n] = graph.add_node(
                            n, prefix="var", **VAR_STYLE)
                    graph.add_edge(var_dict[n], op_node)
            for ns in op.outputs.values():
                for n in ns:
                    if n not in var_dict:
                        var_dict[n] = graph.add_node(
                            n, prefix="var", **VAR_STYLE)
                    graph.add_edge(op_node, var_dict[n])


def draw_graph(startup_program, main_program, **kwargs):
    filename = kwargs.pop("path", None) or (
        kwargs.pop("graph_attr", {}) or {}).get("path") or "network.dot"
    graph = Graph("network", layout="dot")
    var_dict = {}
    parse_graph(startup_program, graph, var_dict)
    parse_graph(main_program, graph, var_dict)
    graph.compile(filename)
    return graph
