"""Symbolic Program IR.

TPU-native analogue of the reference's Program/Block/Variable/Operator
(ref: python/paddle/fluid/framework.py:799,1684,2136,3554 and
paddle/fluid/framework/program_desc.cc). The key design delta: the reference
interprets this IR op-by-op through a C++ kernel registry; here the IR is a
pure *symbolic* record that the Executor lowers into ONE jax function and
compiles with XLA — whole-block fusion, static shapes, donated state.
"""
import collections
import contextlib
import copy
import itertools
import json
import re
import traceback

import numpy as np

from . import core
from . import unique_name

__all__ = [
    "Program",
    "Block",
    "Variable",
    "Operator",
    "Parameter",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "name_scope",
    "grad_var_name",
    "cpu_places",
    "cuda_places",
    "tpu_places",
    "in_dygraph_mode",
    "convert_np_dtype_to_dtype_",
]

GRAD_VAR_SUFFIX = "@GRAD"
ZERO_VAR_SUFFIX = "@ZERO"
CONTROL_DEP_VAR_PREFIX = "@DEPENDENCY"


def grad_var_name(var_name):
    return var_name + GRAD_VAR_SUFFIX


def convert_np_dtype_to_dtype_(np_dtype):
    return core.convert_dtype(np_dtype)


def dtype_is_floating(dtype):
    return core.convert_dtype(dtype) in (
        core.VarType.FP16,
        core.VarType.BF16,
        core.VarType.FP32,
        core.VarType.FP64,
    )


# ---------------------------------------------------------------------------
# dygraph mode switch
# ---------------------------------------------------------------------------
_dygraph_tracer_ = None
_dygraph_current_expected_place_ = None


def in_dygraph_mode():
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


@contextlib.contextmanager
def _dygraph_guard(tracer):
    global _dygraph_tracer_
    tmp = _dygraph_tracer_
    _dygraph_tracer_ = tracer
    try:
        yield
    finally:
        _dygraph_tracer_ = tmp


@contextlib.contextmanager
def _dygraph_place_guard(place):
    global _dygraph_current_expected_place_
    tmp = _dygraph_current_expected_place_
    _dygraph_current_expected_place_ = place
    try:
        yield
    finally:
        _dygraph_current_expected_place_ = tmp


def _current_expected_place():
    if _dygraph_current_expected_place_ is not None:
        return _dygraph_current_expected_place_
    return core.default_place()


def cpu_places(device_count=None):
    return [core.CPUPlace(i) for i in range(device_count or 1)]


def tpu_places(device_ids=None):
    import jax

    if device_ids is None:
        try:
            device_ids = range(len(jax.devices()))
        except RuntimeError:
            device_ids = [0]
    return [core.TPUPlace(i) for i in device_ids]


def cuda_places(device_ids=None):
    # Accelerator places — on this framework the accelerator is TPU.
    return tpu_places(device_ids)


def cuda_pinned_places(device_count=None):
    return [core.CUDAPinnedPlace(i) for i in range(device_count or 1)]


# ---------------------------------------------------------------------------
# name_scope
# ---------------------------------------------------------------------------
class NameScope:
    def __init__(self, name="", parent=None):
        self._children = {}
        self._name = name
        self._parent = parent

    def child(self, prefix):
        if prefix not in self._children:
            self._children[prefix] = [NameScope(prefix, self)]
        else:
            new_child = NameScope(
                prefix + "_%d" % len(self._children[prefix]), self
            )
            self._children[prefix].append(new_child)
        return self._children[prefix][-1]

    def parent(self):
        return self._parent

    def name(self):
        return self._name


_name_scope = NameScope()


@contextlib.contextmanager
def name_scope(prefix=None):
    global _name_scope
    _name_scope = _name_scope.child(prefix or "")
    try:
        yield
    finally:
        _name_scope = _name_scope.parent()


def _full_name_scope():
    global _name_scope
    scope = _name_scope
    name = ""
    while scope:
        name = scope.name() + "/" + name
        scope = scope.parent()
    return name


# ---------------------------------------------------------------------------
# Variable
# ---------------------------------------------------------------------------
class Variable:
    """A named symbolic tensor in a Block.

    Mirrors ref framework.py:799 Variable. Holds static metadata only —
    values live in the executor Scope (device-resident jax arrays).
    Shape may contain -1 (batch dims resolved at feed time).
    """

    def __init__(
        self,
        block,
        type=core.VarType.LOD_TENSOR,
        name=None,
        shape=None,
        dtype=None,
        lod_level=None,
        capacity=None,
        persistable=None,
        error_clip=None,
        stop_gradient=False,
        is_data=False,
        need_check_feed=False,
        belong_to_optimizer=False,
        **kwargs
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.type = type
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = core.convert_dtype(dtype) if dtype is not None else None
        self.lod_level = lod_level or 0
        self.persistable = bool(persistable)
        self.error_clip = error_clip
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.need_check_feed = need_check_feed
        self.belong_to_optimizer = belong_to_optimizer
        self.op = None  # producer op, set by Block.append_op

    # -- introspection -----------------------------------------------------
    def to_string(self, throw_on_error=True, with_details=False):
        return "var %s : shape%s dtype %s%s" % (
            self.name,
            self.shape,
            self.dtype,
            " persistable" if self.persistable else "",
        )

    __str__ = to_string

    def __repr__(self):
        return self.to_string()

    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def numel(self):
        n = 1
        for s in self.shape or ():
            n *= s
        return n

    def astype(self, dtype):
        from .layers import tensor as _tensor_layers

        return _tensor_layers.cast(self, dtype)

    # math_op_patch-style operator overloads are installed by
    # layers.math_op_patch.monkey_patch_variable() at fluid import time.


class Parameter(Variable):
    """Trainable persistable variable (ref framework.py:4507)."""

    def __init__(self, block, shape, dtype, **kwargs):
        if shape is None or dtype is None:
            raise ValueError("Parameter needs shape and dtype")
        for s in shape:
            if s <= 0:
                raise ValueError(
                    "Parameter shape must be positive, got %s" % (shape,)
                )
        kwargs.setdefault("persistable", True)
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        self.is_distributed = kwargs.pop("is_distributed", False)
        super().__init__(block, shape=shape, dtype=dtype, **kwargs)


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------
class Operator:
    """Symbolic op record: (type, inputs, outputs, attrs).

    Mirrors ref framework.py:1684. Inputs/outputs map slot name -> list of
    var *names*. Semantics live in paddle_tpu.ops.registry lowerings.
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.attrs = dict(attrs or {})
        self.inputs = self._canonicalize(inputs)
        self.outputs = self._canonicalize(outputs)
        # op provenance for failure diagnosis (ref records op_callstack
        # attr). Trim trailing framework-internal frames by file, not by
        # a fixed count: ops appended via block.append_op directly (no
        # LayerHelper hop) must still keep the caller's frame.
        stack = traceback.extract_stack(limit=10)
        while stack and stack[-1].filename.endswith(
                ("framework.py", "layer_helper.py")):
            stack.pop()
        self.callstack = stack
        self._is_backward = type.endswith("_grad") or type == "backward"

    @staticmethod
    def _canonicalize(io):
        out = {}
        for slot, vs in (io or {}).items():
            if vs is None:
                out[slot] = []
                continue
            if not isinstance(vs, (list, tuple)):
                vs = [vs]
            out[slot] = [v.name if isinstance(v, Variable) else v for v in vs]
        return out

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    @property
    def output_arg_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def attr(self, name):
        return self.attrs[name]

    def has_attr(self, name):
        return name in self.attrs

    def _set_attr(self, name, val):
        self.attrs[name] = val

    def all_attrs(self):
        return dict(self.attrs)

    def to_string(self, throw_on_error=True):
        return "{%s} = %s(%s) attrs:%s" % (
            ", ".join(self.output_arg_names),
            self.type,
            ", ".join(self.input_arg_names),
            {k: v for k, v in self.attrs.items() if not k.startswith("_")},
        )

    __str__ = to_string

    def __repr__(self):
        return self.to_string()


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------
class Block:
    """Sequence of ops + symbol table of vars (ref framework.py:2136)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = collections.OrderedDict()  # name -> Variable
        self.ops = []

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    # -- vars --------------------------------------------------------------
    def create_var(self, **kwargs):
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        self.program._bump_version()
        return var

    def create_parameter(self, **kwargs):
        param = Parameter(self, **kwargs)
        self.vars[param.name] = param
        self.program._bump_version()
        return param

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise ValueError(
                "var %s not in block %d of program" % (name, self.idx)
            )
        return v

    def has_var(self, name):
        return name in self.vars

    def _var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        raise ValueError("var %s not found in block tree" % name)

    def has_var_recursive(self, name):
        try:
            self._var_recursive(name)
            return True
        except ValueError:
            return False

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def _remove_var(self, name):
        self.vars.pop(name, None)
        self.program._bump_version()

    def _rename_var(self, old, new):
        v = self.vars.pop(old)
        v.name = new
        self.vars[new] = v
        for op in self.ops:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [new if n == old else n for n in names]
            for slot, names in op.outputs.items():
                op.outputs[slot] = [new if n == old else n for n in names]
        self.program._bump_version()
        return v

    # -- ops ---------------------------------------------------------------
    def append_op(self, type=None, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        for slot, names in op.outputs.items():
            for n in names:
                if n in self.vars:
                    self.vars[n].op = op
        self.program._bump_version()
        return op

    def _prepend_op(self, type=None, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def _insert_op(self, index, type=None, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def _remove_op(self, index):
        self.ops.pop(index)
        self.program._bump_version()

    def to_string(self, throw_on_error=True, with_details=False):
        lines = ["  block %d (parent %d):" % (self.idx, self.parent_idx)]
        for v in self.vars.values():
            lines.append("    " + v.to_string())
        for op in self.ops:
            lines.append("    " + op.to_string())
        return "\n".join(lines)

    __str__ = to_string


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------
class Program:
    """A whole model description: list of Blocks (ref framework.py:3554).

    The executor lowers block 0 (plus control-flow sub-blocks referenced by
    ops) into a single jitted function. ``_version`` invalidates the
    executor's compile cache whenever the graph mutates.
    """

    _uid_counter = itertools.count()

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        # monotonic identity for executor compile-cache keys: unlike
        # id(self), a UID is never reused after GC, so a new Program can
        # never replay a dead Program's stale executable
        self._uid = next(Program._uid_counter)
        self._version = 0
        self._seed_counter = 0
        self._is_start_up_program = False
        # marks set by append_backward / optimizers
        self._loss_name = None
        self._appending_grad_times = 0
        # distributed / compiled annotations
        self._sharding_spec = None
        self._parallel_info = None
        self._lr_schedulers = []

    # -- versioning (compile-cache key) ------------------------------------
    def _bump_version(self):
        self._version += 1

    @property
    def desc_version(self):
        return self._version

    # -- block management --------------------------------------------------
    def global_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None):
        new_idx = len(self.blocks)
        parent = (
            self.current_block_idx if parent_idx is None else parent_idx
        )
        self.blocks.append(Block(self, new_idx, parent))
        self.current_block_idx = new_idx
        self._bump_version()
        return self.current_block()

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    @contextlib.contextmanager
    def _block_guard(self, parent_idx=None):
        blk = self._create_block(parent_idx)
        try:
            yield blk
        finally:
            self._rollback()

    # -- introspection -----------------------------------------------------
    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    def all_parameters(self):
        params = []
        for blk in self.blocks:
            params.extend(blk.all_parameters())
        return params

    def to_string(self, throw_on_error=True, with_details=False):
        return "program:\n" + "\n".join(b.to_string() for b in self.blocks)

    __str__ = to_string

    def __repr__(self):
        return self.to_string()

    # -- clone / prune -----------------------------------------------------
    def clone(self, for_test=False):
        """Deep-copy the program. ``for_test=True`` marks inference mode:
        ops like dropout/batch_norm lower in eval mode."""
        p = Program()
        p.random_seed = self.random_seed
        p.blocks = []
        memo = {}
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            for name, v in blk.vars.items():
                nv = copy.copy(v)
                nv.block = nb
                nb.vars[name] = nv
            for op in blk.ops:
                nop = Operator(
                    nb,
                    op.type,
                    {k: list(v) for k, v in op.inputs.items()},
                    {k: list(v) for k, v in op.outputs.items()},
                    dict(op.attrs),
                )
                if for_test and "is_test" in _TEST_MODE_ATTR_OPS.get(
                    op.type, ()
                ):
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
            p.blocks.append(nb)
        p.current_block_idx = 0
        p._loss_name = None if for_test else self._loss_name
        p._lr_schedulers = list(self._lr_schedulers)
        # attached py_readers keep feeding clones (the reference's reader
        # ops live in the graph and survive clone; ours are program state)
        if getattr(self, "_py_readers", None):
            p._py_readers = list(self._py_readers)
        if for_test:
            # drop backward + optimizer ops, then iteratively drop any op
            # whose inputs can no longer be produced (regularizer/clip ops
            # consuming @GRAD vars, etc.)
            gb = p.global_block()
            kept = [
                op
                for op in gb.ops
                if not op._is_backward and op.type not in _OPTIMIZER_OP_TYPES
            ]
            available = {
                v.name
                for v in gb.vars.values()
                if v.persistable or v.is_data
            }
            final = []
            for op in kept:
                if all(n in available for n in op.input_arg_names):
                    final.append(op)
                    available.update(op.output_arg_names)
            gb.ops = final
        p._bump_version()
        return p

    def _prune(self, targets):
        """Backward-slice the global block to the ops needed for `targets`
        (ref framework.py Program._prune / prune_backward)."""
        p = self.clone(for_test=True)
        target_names = set()
        for t in targets:
            target_names.add(t.name if isinstance(t, Variable) else t)
        gb = p.global_block()

        # every attr that references a body block (while/scan/conditional_block
        # ops use sub_block; cond/ifelse use true_block/false_block)
        _BLOCK_ATTRS = ("sub_block", "true_block", "false_block")

        def _sub_blocks(op):
            return [
                p.block(op.attr(a)) for a in _BLOCK_ATTRS if op.has_attr(a)
            ]

        def _op_reads(op):
            """All names an op reads, including reads made by ops inside its
            sub-blocks (while/cond bodies reference global-block vars that
            never appear on the outer op's input list)."""
            reads = set(op.input_arg_names)
            for sub in _sub_blocks(op):
                sub_reads = set()
                produced = set()
                for sop in sub.ops:
                    sub_reads.update(_op_reads(sop) - produced)
                    produced.update(sop.output_arg_names)
                reads |= sub_reads - set(sub.vars)  # minus sub-block locals
            return reads

        needed = set(target_names)
        kept = []
        for op in reversed(gb.ops):
            if any(n in needed for n in op.output_arg_names):
                kept.append(op)
                needed.update(_op_reads(op))
        gb.ops = list(reversed(kept))
        # drop vars no op references (keep targets + data feeds, like the
        # reference's prune which rebuilds the block from the kept op set).
        # Ops carrying a sub_block (while/cond/...) reference global-block
        # vars — e.g. parameters of layers built inside the body — only from
        # within the sub-block's ops, so walk those recursively too.
        referenced = set(target_names)

        def _mark(ops):
            for op in ops:
                referenced.update(op.input_arg_names)
                referenced.update(op.output_arg_names)
                for sub in _sub_blocks(op):
                    _mark(sub.ops)

        _mark(gb.ops)
        for name in list(gb.vars):
            v = gb.vars[name]
            if name not in referenced and not getattr(v, "is_data", False):
                del gb.vars[name]
        p._bump_version()
        return p

    # -- serialization -----------------------------------------------------
    def to_json(self):
        def _attr(v):
            if isinstance(v, np.ndarray):
                return {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            if isinstance(v, Variable):
                return {"__var__": v.name}
            return v

        return json.dumps(
            {
                "random_seed": self.random_seed,
                "blocks": [
                    {
                        "idx": b.idx,
                        "parent_idx": b.parent_idx,
                        "vars": [
                            {
                                "name": v.name,
                                "shape": v.shape,
                                "dtype": v.dtype,
                                "persistable": v.persistable,
                                "stop_gradient": v.stop_gradient,
                                "lod_level": v.lod_level,
                                "is_data": v.is_data,
                                "is_parameter": isinstance(v, Parameter),
                                "trainable": getattr(v, "trainable", False),
                                "type": v.type,
                            }
                            for v in b.vars.values()
                        ],
                        "ops": [
                            {
                                "type": op.type,
                                "inputs": op.inputs,
                                "outputs": op.outputs,
                                "attrs": {
                                    k: _attr(v)
                                    for k, v in op.attrs.items()
                                    if not k.startswith("_")
                                },
                            }
                            for op in b.ops
                        ],
                    }
                    for b in self.blocks
                ],
            }
        )

    @staticmethod
    def from_json(text):
        def _unattr(v):
            if isinstance(v, dict) and "__ndarray__" in v:
                return np.array(v["__ndarray__"], dtype=v["dtype"])
            return v

        data = json.loads(text)
        p = Program()
        p.random_seed = data["random_seed"]
        p.blocks = []
        for bd in data["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            for vd in bd["vars"]:
                kw = dict(
                    name=vd["name"],
                    shape=vd["shape"],
                    dtype=vd["dtype"],
                    persistable=vd["persistable"],
                    stop_gradient=vd["stop_gradient"],
                    lod_level=vd["lod_level"],
                    is_data=vd["is_data"],
                    type=vd["type"],
                )
                if vd.get("is_parameter"):
                    b.create_parameter(trainable=vd.get("trainable", True), **kw)
                else:
                    b.vars[vd["name"]] = Variable(b, **kw)
            for od in bd["ops"]:
                b.ops.append(
                    Operator(
                        b,
                        od["type"],
                        od["inputs"],
                        od["outputs"],
                        {k: _unattr(v) for k, v in od["attrs"].items()},
                    )
                )
            p.blocks.append(b)
        p.current_block_idx = 0
        p._bump_version()
        return p


# ops whose clone(for_test=True) should set is_test
_TEST_MODE_ATTR_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
    "instance_norm": ("is_test",),
    "data_norm": ("is_test",),
    "lrn": ("is_test",),
}

_OPTIMIZER_OP_TYPES = frozenset(
    [
        "sgd",
        "momentum",
        "lars_momentum",
        "adagrad",
        "decayed_adagrad",
        "adadelta",
        "adam",
        "adamax",
        "rmsprop",
        "ftrl",
        "lamb",
        "dpsgd",
        "increment_step",
        "global_norm_clip",
    ]
)


# ---------------------------------------------------------------------------
# default programs
# ---------------------------------------------------------------------------
_main_program_ = Program()
_startup_program_ = Program()
_startup_program_._is_start_up_program = True


def default_main_program():
    return _main_program_


def default_startup_program():
    return _startup_program_


def switch_main_program(program):
    global _main_program_
    prev = _main_program_
    _main_program_ = program
    return prev


def switch_startup_program(program):
    global _startup_program_
    prev = _startup_program_
    _startup_program_ = program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


def _get_paddle_place(place):
    return place


def is_compiled_with_cuda():
    """Always False: this build targets TPU via XLA, not CUDA
    (ref framework.py:265). Scripts branching on it fall through to the
    portable path, which compiles for whatever backend jax exposes."""
    return core.is_compiled_with_cuda()


_VERSION_PAT = re.compile(r"^\d+(\.\d+){0,3}([.-].*)?$")


def require_version(min_version, max_version=None):
    """Check the installed framework version lies in
    [min_version, max_version] (ref framework.py:66). Raises on syntax or
    range violations, returns None when satisfied."""
    for name, arg in (("min_version", min_version),
                      ("max_version", max_version)):
        if arg is None:
            continue
        if not isinstance(arg, str):
            raise TypeError(
                "%s must be str, but received %s." % (name, type(arg)))
        if not _VERSION_PAT.match(arg):
            raise ValueError(
                "%s (%s) should have format like '1.5.2.0'." % (name, arg))

    from .. import __version__

    def _key(v):
        # '0.2.0-rc1': numeric base, then the pre-release suffix; a
        # suffixed build orders BEFORE its clean release, suffixes order
        # lexically among themselves (rc1 < rc2)
        base, sep, suffix = v.partition("-")
        nums = [int(p) if p.isdigit() else 0 for p in base.split(".")[:4]]
        while len(nums) < 4:
            nums.append(0)
        nums.append(0 if sep else 1)
        nums.append(suffix)
        return nums

    if max_version is not None and _key(min_version) > _key(max_version):
        raise ValueError(
            "please make sure min_version (%s) <= max_version (%s)."
            % (min_version, max_version))

    installed = _key(__version__)
    if installed < _key(min_version):
        raise Exception(
            "PaddleTPU version %s is installed, but version >= %s is "
            "required." % (__version__, min_version))
    if max_version is not None and installed > _key(max_version):
        raise Exception(
            "PaddleTPU version %s is installed, but version <= %s is "
            "required." % (__version__, max_version))


def load_op_library(lib_filename):
    """Load a shared library of custom ops (ref framework.py:4938). The
    TPU build's custom-op path is a Python registration API
    (paddle_tpu.ops.register_lowering) — C++ op .so files target the CUDA
    runtime and cannot carry XLA lowerings, so this raises with guidance
    instead of silently accepting a no-op library."""
    raise NotImplementedError(
        "load_op_library loads CUDA/CPU op kernels; on the TPU build "
        "register a jax lowering instead: "
        "paddle_tpu.ops.register_lowering('%s', fn). The library file was "
        "not loaded." % lib_filename)


__all__ += ["is_compiled_with_cuda", "require_version", "load_op_library"]
