"""ref import path python/paddle/fluid/layer_helper_base.py; the helper
hierarchy is flattened into fluid/layer_helper.py here (one class covers
both roles — weight-norm reparam included)."""
from .layer_helper import LayerHelper as LayerHelperBase  # noqa: F401

__all__ = ["LayerHelperBase"]
