"""Deprecation decorator (ref: python/paddle/fluid/annotations.py)."""
import functools
import sys

__all__ = ["deprecated"]


def deprecated(since, instead, extra_message=""):
    def decorator(func):
        err_msg = (
            "API %s is deprecated since %s. Please use %s instead."
            % (func.__name__, since, instead)
        )
        if extra_message:
            err_msg += "\n" + extra_message

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            print(err_msg, file=sys.stderr)
            return func(*args, **kwargs)

        wrapper.__doc__ = (wrapper.__doc__ or "") + "\n    " + err_msg
        return wrapper

    return decorator
