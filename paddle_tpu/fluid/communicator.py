"""Communicator (ref: python/paddle/fluid/communicator.py).

The reference's Communicator is a C++ background thread pool pushing
gradients to / pulling parameters from parameter servers during ASYNC
training. On TPU there is no async pserver channel to service: gradients
ride synchronous ICI collectives inserted by XLA inside the jitted step,
so there is nothing for a background communicator to do. The class keeps
the reference lifecycle (start/stop/is_running) as state so fleet
scripts that manage one run unchanged, and warns once that the work
happens in-graph.
"""
import warnings

__all__ = ["Communicator"]


class Communicator:
    def __init__(self, program, vars_info=None, trainers=None,
                 geo_sgd_need_push_nums=None):
        self._program = program
        # geo-SGD shard metadata, kept for introspection parity (the
        # sync step subsumes delta pushing — see GeoSgdTranspiler)
        self._vars_info = vars_info
        self._trainers = trainers
        self._geo_sgd_need_push_nums = geo_sgd_need_push_nums
        self._running = False
        self._warned = False

    def start(self):
        if not self._warned:
            warnings.warn(
                "Communicator.start(): async pserver push/pull is "
                "replaced by synchronous ICI collectives compiled into "
                "the training step on TPU; the communicator is "
                "lifecycle-only here", stacklevel=2)
            self._warned = True
        self._running = True

    def stop(self):
        self._running = False

    def is_running(self):
        return self._running
