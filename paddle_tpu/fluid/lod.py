"""LoDTensor: variable-length sequence batches
(ref: paddle/fluid/framework/lod_tensor.cc, python/paddle/fluid/lod_tensor.py).

TPU-native redesign: instead of ragged level-of-detail offsets interpreted by
C++ kernels, sequences are stored **dense-padded** with a companion
``seq_lens`` vector — static shapes XLA can tile, with masking/segment ops
recovering the ragged semantics (see layers/sequence_lod.py).
"""
import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor", "create_random_int_lodtensor"]


class LoDTensor:
    """Dense-padded batch + per-sequence lengths."""

    def __init__(self, data=None, recursive_seq_lens=None):
        self._ndarray = None if data is None else np.asarray(data)
        self._recursive_seq_lens = recursive_seq_lens or []
        self.seq_lens = None
        if recursive_seq_lens:
            self.seq_lens = np.asarray(recursive_seq_lens[-1], dtype=np.int32)

    # -- construction ---------------------------------------------------
    @staticmethod
    def from_sequences(seqs, pad_value=0):
        """Build a padded (batch, max_len, ...) tensor + lengths from a list
        of per-sample arrays of shape (len_i, ...)."""
        seqs = [np.asarray(s) for s in seqs]
        lens = np.array([s.shape[0] for s in seqs], dtype=np.int32)
        max_len = int(lens.max()) if len(lens) else 0
        trailing = seqs[0].shape[1:] if seqs else ()
        out = np.full(
            (len(seqs), max_len) + tuple(trailing),
            pad_value,
            dtype=seqs[0].dtype if seqs else np.float32,
        )
        for i, s in enumerate(seqs):
            out[i, : s.shape[0]] = s
        t = LoDTensor(out, [lens.tolist()])
        return t

    def set(self, data, place=None):
        self._ndarray = np.asarray(data)

    def set_recursive_sequence_lengths(self, lens):
        self._recursive_seq_lens = lens
        if lens:
            self.seq_lens = np.asarray(lens[-1], dtype=np.int32)

    def recursive_sequence_lengths(self):
        return self._recursive_seq_lens

    def lod(self):
        # offsets form: [0, l1, l1+l2, ...]
        out = []
        for level in self._recursive_seq_lens:
            offs = [0]
            for l in level:
                offs.append(offs[-1] + l)
            out.append(offs)
        return out

    def set_lod(self, lod):
        lens = [[b - a for a, b in zip(l[:-1], l[1:])] for l in lod]
        self.set_recursive_sequence_lengths(lens)

    def shape(self):
        return self._ndarray.shape if self._ndarray is not None else ()

    def __array__(self, dtype=None):
        arr = self._ndarray
        return arr.astype(dtype) if dtype is not None else arr

    def __repr__(self):
        return "LoDTensor(shape=%s, seq_lens=%s)" % (
            None if self._ndarray is None else self._ndarray.shape,
            None if self.seq_lens is None else self.seq_lens.tolist(),
        )


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """ref python/paddle/fluid/lod_tensor.py:create_lod_tensor. Accepts a
    flat (sum_len, ...) array + lens, returns padded LoDTensor."""
    data = np.asarray(data)
    lens = list(recursive_seq_lens[-1])
    seqs = []
    off = 0
    for l in lens:
        seqs.append(data[off : off + l])
        off += l
    return LoDTensor.from_sequences(seqs)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place, low,
                                high):
    lens = list(recursive_seq_lens[-1])
    total = sum(lens)
    data = np.random.randint(
        low, high + 1, size=[total] + list(base_shape)
    ).astype("int64")
    return create_lod_tensor(data, recursive_seq_lens, place)
