"""Thread-local scope stack helpers
(ref: python/paddle/fluid/default_scope_funcs.py), over the python
Scope from fluid.executor."""
import threading

__tl_scope__ = threading.local()

__all__ = [
    "get_cur_scope", "enter_local_scope", "leave_local_scope", "var",
    "find_var", "scoped_function",
]


def get_cur_scope():
    stack = getattr(__tl_scope__, "cur_scope", None)
    if stack is None:
        __tl_scope__.cur_scope = []
    if not __tl_scope__.cur_scope:
        from .executor import Scope

        __tl_scope__.cur_scope.append(Scope())
    return __tl_scope__.cur_scope[-1]


def enter_local_scope():
    cur = get_cur_scope()
    __tl_scope__.cur_scope.append(cur.new_scope())


def leave_local_scope():
    __tl_scope__.cur_scope.pop()


def var(name):
    return get_cur_scope().var(name)


def find_var(name):
    return get_cur_scope().find_var(name)


def scoped_function(func):
    """Run func inside a fresh local scope."""
    enter_local_scope()
    try:
        func()
    finally:
        leave_local_scope()
