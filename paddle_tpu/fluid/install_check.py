"""Installation smoke check (ref: python/paddle/fluid/install_check.py).

``fluid.install_check.run_check()`` trains a tiny linear model one step
in dygraph mode and, when more than one device is visible, also jits a
data-parallel step over the mesh — the TPU analogue of the reference's
single-card + ParallelExecutor checks.
"""
import numpy as np

__all__ = ["run_check"]


def run_check():
    import jax

    from . import dygraph, optimizer
    from .dygraph import Linear, to_variable

    with dygraph.guard():
        m = Linear(2, 4)
        x = to_variable(np.random.uniform(-1, 1, (2, 2)).astype("float32"))
        from .dygraph.tracer import call_op

        loss = call_op("mean", {"X": [m(x)]})
        loss.backward()
        optimizer.SGD(learning_rate=0.01).minimize(
            loss, parameter_list=m.parameters())
    n_dev = len(jax.devices())
    if n_dev > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        xs = jax.device_put(
            np.ones((n_dev * 2, 2), np.float32),
            NamedSharding(mesh, P("dp", None)))
        w = jax.device_put(np.ones((2, 4), np.float32),
                           NamedSharding(mesh, P(None, None)))

        @jax.jit
        def step(x, w):
            return (x @ w).mean()

        float(step(xs, w))
        print("Your paddle_tpu works well on MULTIPLE devices (%d)."
              % n_dev)
    print("Your paddle_tpu is installed successfully! Device count: %d"
          % n_dev)
