"""Parameter initializers (ref: python/paddle/fluid/initializer.py).

Each initializer appends an op to the *startup program* block that produces
the parameter value; the startup program is itself lowered and jitted, so
initialization runs on-device from a threaded PRNG key.
"""
import math

import numpy as np

from . import framework
from .framework import default_startup_program

__all__ = [
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "Bilinear",
    "MSRA",
    "NumpyArrayInitializer",
    "ConstantInitializer",
    "UniformInitializer",
    "NormalInitializer",
    "TruncatedNormalInitializer",
    "XavierInitializer",
    "BilinearInitializer",
    "MSRAInitializer",
    "force_init_on_cpu",
    "init_on_cpu",
]


def force_init_on_cpu():
    return False


class init_on_cpu:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _compute_fans(var):
        shape = var.shape
        if len(shape) < 2:
            fan_in = fan_out = int(shape[0]) if shape else 1
        else:
            receptive = 1
            for s in shape[2:]:
                receptive *= int(s)
            fan_in = int(shape[1]) * receptive
            fan_out = int(shape[0]) * receptive
        return fan_in, fan_out


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self._value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "value": float(self._value),
            },
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low = low
        self._high = high
        self._seed = seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": self._low,
                "max": self._high,
                "seed": self._seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean = loc
        self._std_dev = scale
        self._seed = seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self._mean,
                "std": self._std_dev,
                "seed": self._seed,
            },
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean = loc
        self._std_dev = scale
        self._seed = seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self._mean,
                "std": self._std_dev,
                "seed": self._seed,
            },
        )


class XavierInitializer(Initializer):
    """Glorot init (ref initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self._uniform = uniform
        self._fan_in = fan_in
        self._fan_out = fan_out
        self._seed = seed

    def __call__(self, var, block):
        f_in, f_out = self._compute_fans(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        fan_out = f_out if self._fan_out is None else self._fan_out
        if self._uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class MSRAInitializer(Initializer):
    """Kaiming init (ref initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self._uniform = uniform
        self._fan_in = fan_in
        self._seed = seed

    def __call__(self, var, block):
        f_in, _ = self._compute_fans(var)
        fan_in = f_in if self._fan_in is None else self._fan_in
        if self._uniform:
            limit = math.sqrt(6.0 / fan_in)
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = math.sqrt(2.0 / fan_in)
        return NormalInitializer(0.0, std, self._seed)(var, block)


class BilinearInitializer(Initializer):
    """For upsampling deconv weights (ref initializer.py BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("BilinearInitializer needs 4-D weight")
        weight = np.zeros(shape, dtype="float32")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        size = shape[2] * shape[3]
        for i in range(np.prod(shape)):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            idx = np.unravel_index(i, shape)
            weight[idx] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return NumpyArrayInitializer(weight)(var, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self._value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type="assign_value",
            outputs={"Out": [var.name]},
            attrs={
                "dtype": var.dtype,
                "shape": list(self._value.shape),
                "values": self._value.reshape(-1).tolist(),
            },
        )


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
