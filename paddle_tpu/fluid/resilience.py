"""Resilient training runtime: fault injection, guarded execution,
auto-checkpoint/resume.

A production TPU run dies for reasons that have nothing to do with the
model: a transient XLA/runtime error on one host, a NaN loss from a bad
batch or an overflowed fp16 step, a crashed reader feeder thread, a
preemption mid-save. The reference framework spreads its answer across
the trainer (checkpoint notify + restart) and the loss-scaling op; here
the pieces already exist individually — ``Executor.run`` (one jitted
step), the py_reader producer thread (layers/io.py), orbax step-managed
checkpoints (parallel/checkpoint.py), AMP dynamic loss scaling with
in-graph skip gates (contrib/mixed_precision) — and this module ties
them into a survivable loop:

- **FaultInjector** — deterministic, env-driven fault injection
  (``PADDLE_TPU_FAULT_SPEC``) at the ``run`` / ``feed`` / ``save`` /
  ``fetch`` sites, so every recovery path below is testable in CI
  without flaky sleeps or monkeypatching.
- **GuardedExecutor / run_guarded()** — ``Executor.run`` plus bounded
  retry with exponential backoff + deterministic jitter for transient
  errors, an optional wall-clock watchdog per run, and a non-finite
  fetch guard that skips NaN/Inf steps (cooperating with AMP dynamic
  loss scaling, whose skip-gate already made the update a no-op) and
  raises after N consecutive bad steps.
- **TrainGuard** — a loop driver wiring periodic orbax
  auto-checkpointing with crash-resume from ``latest_step``, py_reader
  feeder-thread restart, epoch rollover on EOF, and a structured event
  log (step/retry/skip/save/restore/reader_restart) for observability.

Fault spec grammar (clauses joined by ``;`` or ``,``)::

    PADDLE_TPU_FAULT_SPEC="run:every=7:RuntimeError;fetch:at=5:nan"

    clause   := site ":" trigger ":" action
    site     := "run" | "feed" | "save" | "fetch"
              | "collective" | "barrier" | "heartbeat"
              | "dispatch" | "replica"
              | "load" | "wire" | "mailbox"
    trigger  := "every=" N | "at=" N      (N counts checks at that site,
                                           1-based)
    action   := exception class name (builtins or "EOFException"),
                "nan" (site "fetch" only: corrupt the first fetched
                float into NaN), "slow" (sleep
                PADDLE_TPU_FAULT_SLOW_S seconds, default 0.25 — the
                straggler/slow-replica drill), "slow=" SECONDS
                (per-clause duration, e.g. ``dispatch:every=1:slow=0.05``
                — degrade one site without re-pacing every other slow
                clause in the spec), or "corrupt=" MODE (byte-path
                corruption: MODE is "bitflip" | "truncate" | "torn",
                sites "save" | "load" | "wire" | "mailbox" only —
                ``wire:at=1:corrupt=bitflip`` flips a bit in the next
                KV handoff so the digest-verification/remediation path
                is drillable; see paddle_tpu/integrity/)

The fleet-level sites (see ``parallel/elastic.py``): ``collective``
fires in the collective-op lowerings (``ops/collective_ops.py``) and
the store-backed all-reduce, ``barrier`` in ``Fleet.barrier_worker`` /
the elastic rendezvous paths, ``heartbeat`` in the beacon writer — so a
"worker goes silent mid-run" drill is one env var away.

The serving-fleet sites (see ``serving/router.py``): ``dispatch``
fires in the router's per-attempt dispatch path and in the decode
engine's step loop (``DecodeEngine._step`` — so
``dispatch:every=1:slow=0.05`` seeds a decode-replica slowdown, the
autopilot chaos drill), ``replica`` in each replica's admission path — replica kill is ``replica:at=N:RuntimeError``
(the router fails over), replica slow is ``replica:every=N:slow`` (the
straggler classifier demotes it), and partition is a ``heartbeat``
fault on one replica's beater (beacons stop while the engine lives).

With the env var unset and no injector installed, the hooks are inert
(one dict lookup per site check).
"""
import collections
import os
import random
import re
import threading
import time

import numpy as np

from . import core
from .lowering import OpLoweringError
from .. import observability as obs
from ..observability import runhealth as _runhealth

__all__ = [
    "FaultInjector", "FaultSpecError", "GuardedExecutor", "TrainGuard",
    "EventLog", "StepReport", "StepTimeoutError", "NonFiniteError",
    "CollectiveTimeoutError", "collective_deadline", "collective_check",
    "deadline_remaining", "fault_check", "fault_nonfinite", "run_guarded",
    "fault_corrupt", "fault_corrupt_mode", "corrupt_bytes",
    "corrupt_array",
]

FAULT_SPEC_ENV = "PADDLE_TPU_FAULT_SPEC"


class FaultSpecError(ValueError):
    """Malformed PADDLE_TPU_FAULT_SPEC."""


class StepTimeoutError(RuntimeError):
    """A guarded run exceeded its wall-clock budget. Not retried by
    default: the stuck dispatch may still hold donated buffers, so a
    blind re-run could race it — surface to the driver instead."""


class NonFiniteError(FloatingPointError):
    """Raised after N consecutive non-finite (NaN/Inf) guarded steps."""


class CollectiveTimeoutError(RuntimeError):
    """A collective/barrier path exceeded its deadline. Never retried
    blindly: the peer that missed the rendezvous may be dead, and
    re-entering the same collective would hang again — the caller
    (FleetGuard) must first re-establish fleet membership."""


# ---------------------------------------------------------------------------
# collective deadlines
# ---------------------------------------------------------------------------
#
# A hung peer turns every collective into an infinite wait. The deadline
# is carried in a thread-local so each simulated worker (thread) or real
# process scopes its own budget; the two enforcement points are
# (1) host-side waits (store barriers / all-reduce rendezvous in
# parallel/elastic.py poll against it), and (2) the collective-op
# lowerings in ops/collective_ops.py, which check it at trace/dispatch
# time before handing the program to XLA — once a compiled step is on
# the chip only the runtime can interrupt it, so the guarantee is "no
# *host* wait outlives the deadline, and no new collective is issued
# after expiry".

_deadline_tls = threading.local()


class collective_deadline:
    """Context manager arming a wall-clock deadline (seconds) for every
    collective/barrier check on this thread. Nesting keeps the TIGHTER
    (earlier) deadline. ``seconds=None`` is a no-op context."""

    def __init__(self, seconds):
        self._seconds = seconds
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_deadline_tls, "at", None)
        if self._seconds is not None:
            at = time.monotonic() + float(self._seconds)
            if self._prev is not None:
                at = min(at, self._prev)
            _deadline_tls.at = at
        return self

    def __exit__(self, *exc):
        _deadline_tls.at = self._prev
        return False


def deadline_remaining():
    """Seconds left on this thread's collective deadline, or None when
    no deadline is armed. Never negative (expired == 0.0)."""
    at = getattr(_deadline_tls, "at", None)
    if at is None:
        return None
    return max(0.0, at - time.monotonic())


def collective_check(what, site="collective"):
    """One guard call per collective entry point: counts a fault-spec
    check at `site` (raising any injected fault) and raises
    :class:`CollectiveTimeoutError` when this thread's armed deadline
    has expired. `what` names the op/path for the error message."""
    fault_check(site)
    remaining = deadline_remaining()
    if remaining is not None and remaining <= 0.0:
        raise CollectiveTimeoutError(
            "collective deadline expired before %s could be issued "
            "(a peer is presumed hung/dead; re-establish fleet "
            "membership before retrying)" % (what,)
        )


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

_NAN_ACTION = "nan"
_SLOW_ACTION = "slow"
_SLOW_S_ENV = "PADDLE_TPU_FAULT_SLOW_S"
_CORRUPT_ACTION = "corrupt"
CORRUPT_MODES = frozenset({"bitflip", "truncate", "torn"})
CORRUPT_SITES = frozenset({"save", "load", "wire", "mailbox"})


def _slow_seconds():
    try:
        return max(0.0, float(os.environ.get(_SLOW_S_ENV, 0.25)))
    except (TypeError, ValueError):
        return 0.25


_CLAUSE_RE = re.compile(
    r"^(?P<site>[a-z_]+):(?P<mode>every|at)=(?P<n>\d+)"
    r":(?P<action>\w+)(?:=(?P<arg>[A-Za-z0-9.]+))?$"
)


class _Clause:
    __slots__ = ("site", "mode", "n", "action_name", "exc", "slow_s",
                 "corrupt_mode", "checks", "fires")

    def __init__(self, site, mode, n, action_name, exc, slow_s=None,
                 corrupt_mode=None):
        self.site = site
        self.mode = mode
        self.n = n
        self.action_name = action_name
        self.exc = exc  # exception class, or None for the "nan" action
        self.slow_s = slow_s  # per-clause 'slow' duration override
        self.corrupt_mode = corrupt_mode  # bitflip | truncate | torn
        self.checks = 0
        self.fires = 0

    def poke(self):
        """Count one check at this clause's site; True when it fires."""
        self.checks += 1
        if self.mode == "every":
            hit = self.checks % self.n == 0
        else:
            hit = self.checks == self.n
        if hit:
            self.fires += 1
        return hit


def _resolve_exception(name):
    import builtins

    if name == "EOFException":
        return core.EOFException
    exc = getattr(builtins, name, None)
    if isinstance(exc, type) and issubclass(exc, BaseException):
        return exc
    raise FaultSpecError(
        "unknown fault action %r (want a builtin exception name, "
        "'EOFException', 'slow', or 'nan' for the fetch site)" % name
    )


class FaultInjector:
    """Deterministic fault injection at named runtime sites.

    Activated either programmatically (``FaultInjector.install(spec)``,
    paired with ``uninstall()``) or by setting ``PADDLE_TPU_FAULT_SPEC``
    in the environment. Each site check increments per-clause counters,
    so ``every=N`` fires on the Nth, 2Nth, ... check and ``at=N`` fires
    exactly once. Counters live on the injector instance: reinstalling
    (or changing the env spec) starts fresh.
    """

    SITES = frozenset({"run", "feed", "save", "fetch",
                       "collective", "barrier", "heartbeat",
                       "dispatch", "replica",
                       "load", "wire", "mailbox"})

    _installed = None   # programmatic injector, wins over the env var
    _env_cached = None  # injector parsed from the env spec, counters live

    def __init__(self, spec):
        self.spec = spec
        self.clauses = []
        by_site = collections.defaultdict(list)
        for raw in re.split(r"[;,]", spec):
            raw = raw.strip()
            if not raw:
                continue
            m = _CLAUSE_RE.match(raw)
            if m is None:
                raise FaultSpecError(
                    "bad fault clause %r (want site:every=N:Action or "
                    "site:at=N:Action)" % raw
                )
            site, mode, n, action, arg = (
                m.group("site"), m.group("mode"), int(m.group("n")),
                m.group("action"), m.group("arg"),
            )
            if site not in self.SITES:
                raise FaultSpecError(
                    "unknown fault site %r (known: %s)"
                    % (site, ", ".join(sorted(self.SITES)))
                )
            if n <= 0:
                raise FaultSpecError("fault trigger count must be >= 1")
            if arg is not None and action not in (_SLOW_ACTION,
                                                  _CORRUPT_ACTION):
                raise FaultSpecError(
                    "action argument %r only applies to 'slow' "
                    "(slow=SECONDS) or 'corrupt' (corrupt=MODE), "
                    "not %r" % (arg, action))
            slow_s = None
            corrupt_mode = None
            if action == _NAN_ACTION:
                if site != "fetch":
                    raise FaultSpecError(
                        "action 'nan' only applies to site 'fetch'")
                exc = None
            elif action == _SLOW_ACTION:
                exc = None  # sleeps instead of raising (straggler drill)
                if arg is not None:
                    try:
                        slow_s = float(arg)
                    except ValueError:
                        raise FaultSpecError(
                            "bad slow duration %r (want seconds, e.g. "
                            "dispatch:every=1:slow=0.05)" % arg)
                    if slow_s < 0:
                        raise FaultSpecError(
                            "slow duration must be >= 0, got %r" % arg)
            elif action == _CORRUPT_ACTION:
                exc = None  # mutates payload bytes instead of raising
                if site not in CORRUPT_SITES:
                    raise FaultSpecError(
                        "action 'corrupt' only applies to byte-path "
                        "sites (%s), not %r"
                        % (", ".join(sorted(CORRUPT_SITES)), site))
                if arg is None:
                    raise FaultSpecError(
                        "action 'corrupt' needs a mode "
                        "(corrupt=bitflip|truncate|torn)")
                if arg not in CORRUPT_MODES:
                    raise FaultSpecError(
                        "bad corrupt mode %r (want %s)"
                        % (arg, "|".join(sorted(CORRUPT_MODES))))
                corrupt_mode = arg
            else:
                exc = _resolve_exception(action)
            clause = _Clause(site, mode, n, action, exc, slow_s=slow_s,
                             corrupt_mode=corrupt_mode)
            self.clauses.append(clause)
            by_site[site].append(clause)
        if not self.clauses:
            raise FaultSpecError("empty fault spec %r" % spec)
        self._by_site = dict(by_site)

    # -- activation ------------------------------------------------------
    @classmethod
    def install(cls, spec):
        """Activate programmatically (tests); returns the injector."""
        inj = cls(spec) if isinstance(spec, str) else spec
        cls._installed = inj
        return inj

    @classmethod
    def uninstall(cls):
        cls._installed = None
        cls._env_cached = None

    @classmethod
    def active(cls):
        """The live injector, or None. Env activation caches per spec
        string so clause counters persist across checks."""
        if cls._installed is not None:
            return cls._installed
        spec = os.environ.get(FAULT_SPEC_ENV)
        if not spec:
            return None
        if cls._env_cached is None or cls._env_cached.spec != spec:
            cls._env_cached = cls(spec)
        return cls._env_cached

    # -- firing ----------------------------------------------------------
    def check(self, site):
        """Count a check at `site`; raise the first triggered exception
        clause, or return True if a 'nan' clause fired. A triggered
        'slow' clause sleeps in place — its per-clause ``slow=SECONDS``
        duration when given, else PADDLE_TPU_FAULT_SLOW_S — so the
        checked path stalls but survives."""
        nan_fired = False
        fire = None
        for clause in self._by_site.get(site, ()):
            if clause.action_name == _CORRUPT_ACTION:
                # corrupt clauses fire only where payload bytes flow
                # (fault_corrupt); counting them here would skew their
                # trigger schedule against the byte-path call sites.
                continue
            if clause.poke():
                if clause.action_name == _SLOW_ACTION:
                    time.sleep(clause.slow_s
                               if clause.slow_s is not None
                               else _slow_seconds())
                elif clause.exc is None:
                    nan_fired = True
                elif fire is None:
                    fire = clause
        if fire is not None:
            raise fire.exc(
                "injected fault: site=%s check=%d spec=%r"
                % (site, fire.checks, self.spec)
            )
        return nan_fired

    def corrupt_mode(self, site):
        """Count a byte-path check at `site`; the fired corrupt
        clause's mode ('bitflip' | 'truncate' | 'torn'), or None."""
        mode = None
        for clause in self._by_site.get(site, ()):
            if clause.action_name != _CORRUPT_ACTION:
                continue
            if clause.poke() and mode is None:
                mode = clause.corrupt_mode
        if mode is not None:
            obs.inc("integrity.fault_corrupt_fired")
            obs.event("fault_corrupt", source="resilience",
                      site=site, mode=mode)
        return mode

    def stats(self):
        """Per-clause counters for assertions/observability."""
        return [
            {"site": c.site, "mode": c.mode, "n": c.n,
             "action": c.action_name, "checks": c.checks, "fires": c.fires}
            for c in self.clauses
        ]


def fault_check(site):
    """Hook called from instrumented sites (Executor.run, py_reader
    _next_feed, checkpoint save). No-op unless an injector is active."""
    inj = FaultInjector.active()
    if inj is not None:
        inj.check(site)


def fault_nonfinite(site="fetch"):
    """True when a 'nan' clause fires at `site` (GuardedExecutor uses
    this to corrupt a fetched loss, testing the non-finite guard)."""
    inj = FaultInjector.active()
    return bool(inj is not None and inj.check(site))


def fault_corrupt_mode(site):
    """The corrupt mode fired at a byte-path `site` this check, or
    None. Callers with non-bytes payloads (in-memory KV handoffs) use
    this with :func:`corrupt_array`; byte writers use
    :func:`fault_corrupt` directly."""
    inj = FaultInjector.active()
    if inj is None:
        return None
    return inj.corrupt_mode(site)


def corrupt_bytes(mode, data):
    """Deterministically corrupt a bytes payload: 'bitflip' flips one
    bit in the middle byte, 'truncate' keeps only the first half,
    'torn' drops a short tail (a partially flushed write)."""
    data = bytes(data)
    if not data:
        return data
    if mode == "bitflip":
        buf = bytearray(data)
        buf[len(buf) // 2] ^= 0x01
        return bytes(buf)
    if mode == "truncate":
        return data[:len(data) // 2]
    if mode == "torn":
        return data[:len(data) - max(1, len(data) // 8)]
    raise ValueError("unknown corrupt mode %r" % (mode,))


def corrupt_array(mode, arr):
    """Shape-preserving array corruption for in-memory transports
    (the object must stay well-formed; the content digest still
    catches it): 'bitflip' flips one bit, 'truncate' zeroes the
    second half of the flattened payload, 'torn' zeroes a short
    tail."""
    a = np.array(np.asarray(arr), copy=True)
    if a.size == 0:
        return a
    raw = bytearray(a.tobytes())
    if mode == "bitflip":
        raw[len(raw) // 2] ^= 0x01
    elif mode == "truncate":
        half = len(raw) // 2
        raw[half:] = b"\x00" * (len(raw) - half)
    elif mode == "torn":
        tail = max(1, len(raw) // 8)
        raw[len(raw) - tail:] = b"\x00" * tail
    else:
        raise ValueError("unknown corrupt mode %r" % (mode,))
    return np.frombuffer(bytes(raw), a.dtype).reshape(a.shape)


def fault_corrupt(site, data):
    """Route a bytes payload through any armed corrupt clause at
    `site`; returns the (possibly corrupted) bytes. Inert without an
    injector — one dict lookup like every other site hook."""
    mode = fault_corrupt_mode(site)
    if mode is None:
        return data
    return corrupt_bytes(mode, data)


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------


class EventLog:
    """Bounded structured event log + per-kind counters. Events are
    plain dicts with a 'kind' key; an optional sink callback sees each
    event as it is emitted (wire it to print/logging).

    Every emit also routes through the process-wide telemetry hub
    (``paddle_tpu.observability``): the event lands in the flight
    recorder (`recorder`, the global ring when None — so resilience,
    fleet, and executor streams interleave in ONE monotonic-ordered
    JSONL dump) and bumps the ``<source>.<kind>`` counter. With
    ``PADDLE_TPU_TELEMETRY=off`` the routing is a no-op and only the
    local deque/counters fill. Pass ``_forward=False`` when re-emitting
    an event that already went through the hub at its origin (e.g. a
    GuardedExecutor retry relayed into a TrainGuard's log) so nothing
    double-counts."""

    def __init__(self, maxlen=10000, sink=None, recorder=None,
                 source=None):
        self.events = collections.deque(maxlen=maxlen)
        self.counters = collections.Counter()
        self._sink = sink
        self._recorder = recorder
        self._source = source
        self._seq = 0

    def emit(self, kind, _forward=True, **fields):
        self._seq += 1
        ev = dict(kind=kind, seq=self._seq, **fields)
        self.counters[kind] += 1
        self.events.append(ev)
        if self._sink is not None:
            self._sink(ev)
        if _forward:
            obs.event(kind, source=self._source,
                      recorder=self._recorder, **fields)
        return ev

    def last_seq(self):
        """Sequence number of the newest event (0 before any emit).
        Monotonic across ring rollover — feed it back as ``since_seq``
        to poll incrementally."""
        return self._seq

    def of(self, kind, since_seq=None):
        """Events of `kind`, oldest first. With ``since_seq`` only
        events emitted AFTER that sequence number are returned — and,
        because events land in seq order, the scan walks backwards and
        stops at the watermark instead of rescanning the whole bounded
        ring on every poll. Events that rolled off the deque before the
        watermark are gone either way (the ring is bounded); a stale
        watermark never raises, it just returns what survived."""
        if since_seq is None:
            return [ev for ev in self.events if ev["kind"] == kind]
        out = []
        for ev in reversed(self.events):
            if ev["seq"] <= since_seq:
                break
            if ev["kind"] == kind:
                out.append(ev)
        out.reverse()
        return out


# ---------------------------------------------------------------------------
# guarded execution
# ---------------------------------------------------------------------------


class StepReport(list):
    """The fetch list returned by a guarded run, with step metadata.
    Subclasses list so existing unpack-the-fetches call sites keep
    working: ``loss, = guarded.run(...)``."""

    skipped = False      # non-finite step, update assumed skipped/ignored
    managed = False      # AMP dynamic loss scaling owned the skip
    retries = 0          # transient failures retried away for this step
    nonfinite = False


def _default_transients():
    # OSError covers ConnectionError/TimeoutError; RuntimeError is what
    # jax/XLA raise for runtime-side failures. OpLoweringError (a
    # RuntimeError subclass) is a *graph* error and is never retried.
    return (RuntimeError, OSError)


class GuardedExecutor:
    """``Executor.run`` with bounded retry, a wall-clock watchdog, and a
    non-finite fetch guard. Drop-in: ``run()`` takes the Executor.run
    signature and returns the fetch list (a :class:`StepReport`).

    - Transient errors (`transient_types`, default RuntimeError+OSError)
      are retried up to `max_retries` times with exponential backoff
      (`backoff_base * 2**attempt`, capped at `backoff_max`) plus
      deterministic jitter. ``core.EOFException``, ``OpLoweringError``
      and ``StepTimeoutError`` are never retried.
    - With `timeout` set, each attempt runs under a watchdog thread and
      raises :class:`StepTimeoutError` at expiry (the stuck dispatch
      thread is abandoned — daemonized — and the error is not retried).
    - Fetched float arrays are checked for NaN/Inf. A bad step is
      counted and *skipped* (``report.skipped``) — cooperating with AMP
      dynamic loss scaling, whose in-graph skip gate already kept the
      params/optimizer state untouched — until
      `max_consecutive_nonfinite` consecutive bad steps, which raise
      :class:`NonFiniteError`. Pass ``nonfinite_action="raise"`` to
      fail on the first bad step instead.
    """

    NEVER_RETRY = (core.EOFException, core.ReaderNotStartedError,
                   OpLoweringError, StepTimeoutError, FaultSpecError,
                   CollectiveTimeoutError)

    def __init__(self, executor, max_retries=3, backoff_base=0.05,
                 backoff_max=2.0, jitter=0.25, timeout=None,
                 nonfinite_action="skip", max_consecutive_nonfinite=5,
                 transient_types=None, amp_optimizer=None, on_event=None,
                 seed=0, recorder=None):
        if nonfinite_action not in ("skip", "raise"):
            raise ValueError(
                "nonfinite_action must be 'skip' or 'raise', got %r"
                % (nonfinite_action,))
        self._exe = executor
        self._recorder = recorder
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.timeout = timeout
        self.nonfinite_action = nonfinite_action
        self.max_consecutive_nonfinite = int(max_consecutive_nonfinite)
        self.transient_types = tuple(
            transient_types if transient_types is not None
            else _default_transients())
        self.amp_optimizer = amp_optimizer
        self.counters = collections.Counter()
        self._on_event = on_event
        self._consecutive_nonfinite = 0
        self._rng = random.Random(seed)

    # -- events ----------------------------------------------------------
    def _emit(self, kind, **fields):
        self.counters[kind] += 1
        # hub routing happens HERE, at the origin; relays into a
        # TrainGuard/FleetGuard EventLog re-emit with _forward=False
        obs.event(kind, source="guard", recorder=self._recorder,
                  **fields)
        if self._on_event is not None:
            self._on_event(dict(kind=kind, **fields))

    # -- pieces ----------------------------------------------------------
    def _retryable(self, exc):
        return (isinstance(exc, self.transient_types)
                and not isinstance(exc, self.NEVER_RETRY))

    def _backoff(self, attempt):
        delay = min(self.backoff_max,
                    self.backoff_base * (2.0 ** (attempt - 1)))
        return delay * (1.0 + self.jitter * self._rng.random())

    def _invoke(self, args, kwargs):
        if not self.timeout:
            return self._exe.run(*args, **kwargs)
        box = {}
        done = threading.Event()

        def _worker():
            try:
                box["result"] = self._exe.run(*args, **kwargs)
            except BaseException as e:  # relayed to the caller below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(
            target=_worker, daemon=True, name="paddle_tpu-guarded-run")
        t.start()
        if not done.wait(self.timeout):
            self._emit("timeout", timeout=self.timeout)
            raise StepTimeoutError(
                "Executor.run exceeded %.3fs wall-clock budget (the "
                "dispatch thread was abandoned; its donated state may "
                "be unusable — restore from the last checkpoint before "
                "re-running)" % self.timeout
            )
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _analyze_failure(self, program, feed, fetch_list):
        """Full static analysis of the failed step's program; returns
        extra fields for the retry event ({} when analysis is off or
        anything goes wrong — diagnosis must never mask the original
        error or block the retry)."""
        try:
            from ..analysis import analyzer as _analyzer

            if _analyzer.mode() == "off":
                return {}
            from .framework import default_main_program

            prog = program if program is not None \
                else default_main_program()
            prog = getattr(prog, "_program", prog)  # CompiledProgram
            fetch_names = [f.name if hasattr(f, "name") else str(f)
                           for f in (fetch_list or [])]
            place = getattr(self._exe, "place", None)
            report = _analyzer.analyze(
                prog, feed_names=list(feed or {}),
                fetch_names=fetch_names,
                platform="cpu" if isinstance(place, core.CPUPlace)
                else "tpu",
                level="full")
            extra = {"analysis": report.summary()}
            finds = report.findings
            if finds:
                extra["analysis_findings"] = [str(d) for d in finds[:4]]
            return extra
        except Exception:  # noqa: BLE001 — best-effort diagnosis only
            return {}

    def _amp_managed(self):
        opt = self.amp_optimizer
        return bool(opt is not None
                    and getattr(opt, "get_finite_flag", None)
                    and opt.get_finite_flag() is not None)

    @staticmethod
    def _nonfinite(fetches):
        for v in fetches:
            if hasattr(v, "block_until_ready"):
                # device array (return_numpy=False path): reduce on
                # device and transfer ONE scalar instead of
                # materializing the whole fetch host-side
                if getattr(v.dtype, "kind", None) == "f":
                    import jax.numpy as jnp

                    if not bool(jnp.isfinite(v).all()):
                        return True
                continue
            arr = np.asarray(v)
            if arr.dtype.kind == "f" and not np.isfinite(arr).all():
                return True
        return False

    # -- the guarded run -------------------------------------------------
    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        """Executor.run under the guard. ``return_numpy=False`` passes
        through: the StepReport then holds lazy device handles (no
        per-step host materialization) and the non-finite guard checks
        them with a device-side reduction instead of a full fetch."""
        attempt = 0
        while True:
            try:
                fetches = self._invoke(
                    (program,), dict(feed=feed, fetch_list=fetch_list,
                                     return_numpy=return_numpy,
                                     **kwargs))
                break
            except self.NEVER_RETRY:
                raise
            except self.transient_types as e:
                attempt += 1
                if attempt > self.max_retries:
                    raise
                delay = self._backoff(attempt)
                extra = {}
                if attempt == 1:
                    # first failure of this step: re-run the FULL static
                    # analyzer and attach attributed diagnostics to the
                    # retry event — a "transient" failure rooted in a
                    # program hazard (donated buffer also fetched, host
                    # sync inside a scan, ...) surfaces on the first
                    # retry instead of after the budget burns out
                    extra = self._analyze_failure(program, feed,
                                                  fetch_list)
                self._emit("retry", attempt=attempt, delay=delay,
                           error="%s: %s" % (type(e).__name__, e),
                           **extra)
                _runhealth.goodput_note("retry_backoff", delay)
                time.sleep(delay)

        report = StepReport(fetches if fetches is not None else [])
        report.retries = attempt
        if fault_nonfinite("fetch") and len(report):
            # injected NaN loss: corrupt the first fetch so the guard
            # below exercises the real skip path end-to-end
            first = np.asarray(report[0])
            report[0] = np.full(
                first.shape,
                np.nan,
                dtype=first.dtype if first.dtype.kind == "f" else "float32",
            )
        if self._nonfinite(report):
            report.nonfinite = True
            self._consecutive_nonfinite += 1
            bad = self._consecutive_nonfinite
            if (self.nonfinite_action == "raise"
                    or bad >= self.max_consecutive_nonfinite):
                raise NonFiniteError(
                    "non-finite fetch on %d consecutive step(s) "
                    "(threshold %d) — the run has diverged"
                    % (bad, self.max_consecutive_nonfinite)
                )
            report.skipped = True
            report.managed = self._amp_managed()
            self._emit("skip", consecutive=bad, managed=report.managed)
        else:
            self._consecutive_nonfinite = 0
        if self.amp_optimizer is not None:
            # loss-scale telemetry at the origin: one gauge read per
            # step, plus the skipped-steps counter when AMP's in-graph
            # gate owned this skip
            publish = getattr(self.amp_optimizer,
                              "publish_step_telemetry", None)
            if publish is not None:
                try:
                    publish(scope=kwargs.get("scope"),
                            skipped=report.skipped and report.managed)
                except Exception:  # noqa: BLE001 — telemetry only
                    pass
        return report

    def reset_nonfinite_streak(self):
        """Forget consecutive non-finite history (call after restoring
        state from a checkpoint — the streak belonged to the rolled-back
        trajectory)."""
        self._consecutive_nonfinite = 0


def run_guarded(executor, program=None, feed=None, fetch_list=None,
                scope=None, **guard_opts):
    """One-shot convenience: ``GuardedExecutor(executor, **opts).run(...)``."""
    guard = GuardedExecutor(executor, **guard_opts)
    kwargs = {} if scope is None else {"scope": scope}
    return guard.run(program, feed=feed, fetch_list=fetch_list, **kwargs)


# ---------------------------------------------------------------------------
# the loop driver
# ---------------------------------------------------------------------------


class TrainGuard:
    """Fault-tolerant training loop: guarded steps + periodic orbax
    auto-checkpointing + crash-resume + reader restart.

    ::

        guard = TrainGuard(exe, program=prog, ckpt_dir=dirname,
                           fetch_list=[loss], feed_fn=make_feed,
                           save_every=50)
        summary = guard.train(num_steps=1000)

    Steps are 1-based; checkpoint step K means "step K completed". On
    ``train()``, if `ckpt_dir` holds checkpoints (a previous run
    crashed), state is restored from ``latest_step`` and training
    resumes at the next step — completed steps are not re-run. Batches
    come from `feed_fn(step)` or, when None, from a started py_reader
    attached to the program (pass the reader objects via `readers` so
    dead feeder threads can be restarted and EOF rolls the epoch over).

    The event log records ``restore``/``step``/``retry``/``skip``/
    ``save``/``eof``/``reader_restart``/``final`` events with bounded
    memory; ``summary["counters"]`` aggregates them.
    """

    def __init__(self, executor, program=None, ckpt_dir=None,
                 fetch_list=None, feed_fn=None, readers=None,
                 save_every=0, final_save=True, resume=True, scope=None,
                 reader_restarts=2, restart_on_eof=True, max_to_keep=None,
                 save_wait=True, on_event=None, log_maxlen=10000,
                 recorder=None, compile_cache=False, stage_to_device=False,
                 runhealth=None, lr_var=None, **guard_opts):
        self._exe = executor
        self._program = program
        self._ckpt_dir = ckpt_dir
        # compile_cache=True co-locates a persistent AOT compile cache
        # with the checkpoints (parallel.checkpoint.compile_cache_dir):
        # a crash-resumed process then skips the cold recompile the same
        # way it skips completed steps. A string names an explicit cache
        # dir; PADDLE_TPU_COMPILE_CACHE_DIR in the env always wins.
        if compile_cache:
            from . import compile_cache as _cc

            if isinstance(compile_cache, str):
                cache_path = compile_cache
            else:
                from ..parallel import checkpoint as _ckpt_mod

                if not ckpt_dir:
                    raise ValueError(
                        "TrainGuard(compile_cache=True) needs ckpt_dir "
                        "to co-locate the cache (or pass an explicit "
                        "cache path string)")
                cache_path = _ckpt_mod.compile_cache_dir(ckpt_dir)
            _cc.activate(cache_path, configure_xla_cache=False)
        self._stage_to_device = bool(stage_to_device)
        self._fetch_list = fetch_list
        self._feed_fn = feed_fn
        self._readers = list(readers or [])
        self._save_every = int(save_every)
        self._final_save = final_save
        self._resume = resume
        self._scope = scope
        self._reader_restarts = int(reader_restarts)
        self._restart_on_eof = restart_on_eof
        self._max_to_keep = max_to_keep
        self._save_wait = save_wait
        # run-health observatory (observability/runhealth.py): when a
        # RunHealth bundle is passed, train() activates it, records a
        # StepSeries entry per step (loss, retries, AMP state, the
        # executor's phase split), and feeds its GoodputAccount
        # (feed-wait, checkpoint, retry-backoff, crash-resume rework).
        # lr_var names the learning-rate Variable (or its name) that
        # rollback_to_last_finite's lr-cut scales in the scope.
        self.runhealth = runhealth
        self._lr_var = lr_var
        self.log = EventLog(maxlen=log_maxlen, sink=on_event,
                            recorder=recorder, source="resilience")
        self.guard = GuardedExecutor(
            executor, on_event=self._relay, recorder=recorder,
            **guard_opts)

    def _relay(self, ev):
        # already hub-routed by GuardedExecutor._emit at the origin
        self.log.emit(ev.pop("kind"), _forward=False, **ev)

    # -- checkpoint plumbing --------------------------------------------
    def _resolve(self):
        from .executor import global_scope
        from .framework import default_main_program

        program = self._program if self._program is not None \
            else default_main_program()
        scope = self._scope if self._scope is not None else global_scope()
        return program, scope

    def _maybe_resume(self, program, scope):
        """Restore from the newest checkpoint; returns the last
        completed step (0 when starting fresh)."""
        if not (self._resume and self._ckpt_dir):
            return 0
        from ..parallel import checkpoint as ckpt

        step = ckpt.latest_step(self._ckpt_dir)
        if step is None:
            return 0
        t0 = time.monotonic()
        state = ckpt.load_checkpoint(self._ckpt_dir, step=step)
        src = getattr(program, "_program", program)
        restored = 0
        for v in src.list_vars():
            if v.persistable and v.name in state:
                scope.update(v.name, state[v.name])
                restored += 1
        self.log.emit("restore", step=step, vars=restored,
                      dirname=self._ckpt_dir,
                      seconds=round(time.monotonic() - t0, 6))
        self._account_rework(step)
        # warm-start invalidation: batches staged (host or device-side)
        # before the restore belong to the pre-crash stream position —
        # restart started readers so nothing stale is consumed. Emitted
        # as its own event kind so it never burns the reader_restarts
        # failure budget.
        started = [r for r in self._readers
                   if getattr(r, "_started", False)]
        if started:
            for r in started:
                r.restart()
            self.log.emit("staging_invalidate", step=step,
                          reason="resume", readers=len(started))
        return int(step)

    def _account_rework(self, resumed_step):
        """Goodput restart-rework: steps the crashed run completed past
        ``latest_step`` are re-executed after this resume — their wall
        time (recovered from the previous run's StepSeries JSONL, read
        through the tolerant reader so a torn crash-time line is
        skipped, not fatal) is charged to the ``restart_rework``
        bucket."""
        rh = self.runhealth
        if rh is None or not rh.series.jsonl_path:
            return
        try:
            records, _dropped = rh.series.load(rh.series.jsonl_path)
        except OSError:
            return
        lost = {}
        for rec in records:
            try:
                s = int(rec["step"])
            except (TypeError, ValueError):
                continue
            if s > resumed_step:
                lost[s] = float(rec.get("step_s") or 0.0)
        if lost:
            rh.goodput.add("restart_rework", sum(lost.values()),
                           steps=len(lost))
            self.log.emit("restart_rework", resumed_step=resumed_step,
                          steps=len(lost),
                          seconds=round(sum(lost.values()), 6))

    def save(self, step, program=None, scope=None):
        """Checkpoint the program's persistable state as `step`."""
        if program is None or scope is None:
            rprogram, rscope = self._resolve()
            program = program or rprogram
            scope = scope or rscope
        from ..parallel import checkpoint as ckpt

        src = getattr(program, "_program", program)
        state = self._exe._gather_state(src, scope)
        t0 = time.monotonic()
        ckpt.save_checkpoint(
            self._ckpt_dir, state, step=int(step),
            max_to_keep=self._max_to_keep, wait=self._save_wait)
        dt = time.monotonic() - t0
        _runhealth.goodput_note("checkpoint", dt)
        self.log.emit("save", step=int(step), vars=len(state),
                      seconds=round(dt, 6))

    def _restart_readers(self, step, reason):
        for r in self._readers:
            r.reset()
            r.start()
        self.log.emit("reader_restart", step=step, reason=reason,
                      readers=len(self._readers))

    # -- the loop --------------------------------------------------------
    def train(self, num_steps):
        """Run steps until `num_steps` have completed (counting steps
        finished by a previous crashed run). Returns a summary dict."""
        program, scope = self._resolve()
        if self._stage_to_device:
            # overlap host→device batch transfer with device compute
            # (layers/io.py device staging; generation-bound, so the
            # reader restarts below also invalidate staged batches)
            for r in self._readers:
                stage = getattr(r, "prefetch_to_device", None)
                if stage is not None:
                    stage(self._exe.place)
        rh = self.runhealth
        if rh is None:
            return self._train_loop(num_steps, program, scope)
        # run-health active: the goodput window spans the whole call
        # (resume/restore included), the executor/guard hooks feed the
        # account, and every step lands one StepSeries record
        prev = _runhealth.activate(rh)
        rh.goodput.start()
        try:
            return self._train_loop(num_steps, program, scope)
        finally:
            rh.goodput.stop()
            rh.series.flush()
            _runhealth.deactivate(prev)

    def _record_step(self, step, report, data_wait_s, step_s):
        """One StepSeries record from what the loop can see: the first
        fetch as the loss, guard/AMP step state, and the executor's
        parked phase split."""
        rh = self.runhealth
        fields = dict(skipped=report.skipped, amp_skipped=report.managed,
                      retries=report.retries, data_wait_s=data_wait_s,
                      step_s=step_s)
        if len(report):
            try:
                fields["loss"] = float(np.asarray(report[0]).reshape(-1)[0])
            except (TypeError, ValueError, IndexError):
                pass
        for name, raw in getattr(report, "runhealth_extras",
                                 {}).items():
            try:
                fields[name] = float(np.asarray(raw).reshape(-1)[0])
            except (TypeError, ValueError, IndexError):
                pass
        phases = _runhealth.take_exec_phases()
        if phases:
            if phases.get("compute_s") is not None:
                fields["compute_s"] = phases["compute_s"]
            if phases.get("fetch_s") is not None:
                fields["fetch_s"] = phases["fetch_s"]
            if phases.get("feed_convert_s") is not None:
                fields["feed_convert_s"] = phases["feed_convert_s"]
        if self.guard.amp_optimizer is not None:
            scale = obs.gauge("amp.loss_scale")
            if scale is not None:
                fields["loss_scale"] = scale
        rh.series.record(step, **fields)

    def _train_loop(self, num_steps, program, scope):
        rh = self.runhealth
        fetch_list = self._fetch_list
        extra_names = []
        if rh is not None and rh.extra_fetches:
            # graph-side health signals (grad norms, schedule lr, ...)
            # ride the fetch list and are stripped off the report below
            extra_names = sorted(rh.extra_fetches)
            fetch_list = list(self._fetch_list or []) \
                + [rh.extra_fetches[k] for k in extra_names]
        start = self._maybe_resume(program, scope)
        completed = start
        last_saved = start if start else None
        last_eof_step = None
        step = start + 1
        while step <= num_steps:
            t_feed = time.monotonic()
            feed = self._feed_fn(step) if self._feed_fn else None
            feed_wait = time.monotonic() - t_feed
            if rh is not None and self._feed_fn is not None:
                # host-side batch production is input-pipeline time,
                # not productive compute (py_reader waits are charged
                # at the pipeline pop instead)
                rh.goodput.add("data_stall", feed_wait)
            t_step = time.monotonic()
            try:
                if rh is not None:
                    with rh.goodput.step():
                        report = self.guard.run(
                            program, feed=feed, fetch_list=fetch_list,
                            scope=scope)
                else:
                    report = self.guard.run(
                        program, feed=feed, fetch_list=fetch_list,
                        scope=scope)
            except core.EOFException:
                self.log.emit("eof", step=step)
                if not (self._readers and self._restart_on_eof):
                    raise
                if last_eof_step == step:
                    # two EOFs with no step in between: the reader
                    # yields nothing — restarting forever won't help
                    raise
                last_eof_step = step
                self._restart_readers(step, "eof")
                continue
            except (Exception,) as e:
                if (self._readers
                        and self.log.counters["reader_restart"]
                        < self._reader_restarts
                        and not isinstance(e, NonFiniteError)):
                    # a dead feeder thread surfaces as the producer's
                    # exception (once) or a missing-feed lowering error
                    # on the next pop — a reset()+start() rebuilds the
                    # thread and retries this step on a fresh epoch
                    self._restart_readers(
                        step, "%s: %s" % (type(e).__name__, e))
                    continue
                raise
            if extra_names:
                vals = [report.pop() for _ in extra_names]
                report.runhealth_extras = dict(
                    zip(extra_names, reversed(vals)))
            completed = step
            self.log.emit("step", step=step, skipped=report.skipped,
                          retries=report.retries)
            if rh is not None:
                self._record_step(step, report, feed_wait,
                                  time.monotonic() - t_step)
            if (self._ckpt_dir and self._save_every
                    and step % self._save_every == 0):
                self.save(step, program, scope)
                last_saved = step
            step += 1
        if (self._ckpt_dir and self._final_save and completed > start
                and last_saved != completed):
            self.save(completed, program, scope)
            last_saved = completed
        self.log.emit("final", step=completed)
        summary = {
            "resumed_from": start if start else None,
            "first_step": start + 1,
            "final_step": completed,
            "steps_run": completed - start,
            "last_saved": last_saved,
            "counters": dict(self.log.counters),
            "events": list(self.log.events),
        }
        if rh is not None:
            summary["runhealth"] = rh.snapshot()
        return summary

    # -- divergence remediation -----------------------------------------
    def rollback_to_last_finite(self, lr_scale=None, program=None,
                                scope=None):
        """Restore the newest checkpoint whose float state is entirely
        finite (walking past any NaN-poisoned saves), optionally scale
        the learning-rate variable by ``lr_scale``, and reset the
        non-finite streak + detector windows so the restored trajectory
        re-baselines. This is the autopilot TRAIN leg's act step.

        Returns ``{"step", "vars", "skipped_steps", "lr", "lr_scale"}``
        on success, None when no finite checkpoint exists (or there is
        no ckpt_dir). The var restore is the same scope.update walk as
        crash-resume, so the restored state is bit-identical to a clean
        ``load_checkpoint`` resume from that step."""
        if not self._ckpt_dir:
            return None
        from ..parallel import checkpoint as ckpt

        if program is None or scope is None:
            rprogram, rscope = self._resolve()
            program = program or rprogram
            scope = scope or rscope
        t0 = time.monotonic()
        state = None
        chosen = None
        skipped = []
        for step in ckpt.all_steps(self._ckpt_dir):
            try:
                cand = ckpt.load_checkpoint(self._ckpt_dir, step=step)
            except Exception:  # torn/corrupt save: keep walking back
                skipped.append(int(step))
                continue
            finite = True
            for arr in cand.values():
                a = np.asarray(arr)
                if a.dtype.kind == "f" and not np.isfinite(a).all():
                    finite = False
                    break
            if finite:
                state, chosen = cand, int(step)
                break
            skipped.append(int(step))
        if state is None:
            self.log.emit("rollback_failed", reason="no finite checkpoint",
                          skipped_steps=skipped)
            return None
        src = getattr(program, "_program", program)
        restored = 0
        for v in src.list_vars():
            if v.persistable and v.name in state:
                scope.update(v.name, state[v.name])
                restored += 1
        out = {"step": chosen, "vars": restored,
               "skipped_steps": skipped, "lr": None,
               "lr_scale": lr_scale}
        if lr_scale is not None and self._lr_var is not None:
            name = getattr(self._lr_var, "name", self._lr_var)
            raw = scope.find_value(name)
            if raw is not None:
                cut = np.asarray(raw, dtype="float32") * float(lr_scale)
                scope.update(name, cut)
                out["lr"] = float(cut.reshape(-1)[0])
        # staged batches + failure streaks belong to the abandoned
        # trajectory
        started = [r for r in self._readers
                   if getattr(r, "_started", False)]
        for r in started:
            r.restart()
        self.guard.reset_nonfinite_streak()
        if self.runhealth is not None:
            self.runhealth.series.reset_anomalies()
        self.log.emit("rollback", step=chosen, vars=restored,
                      skipped_steps=skipped, lr_scale=lr_scale,
                      lr=out["lr"], readers=len(started),
                      seconds=round(time.monotonic() - t0, 6))
        return out
