"""Gradient clipping strategies for the eager (dygraph) path.

Reference surface: python/paddle/fluid/dygraph_grad_clip.py:1
(GradClipByValue / GradClipByNorm / GradClipByGlobalNorm), consumed by
``optimizer.minimize(loss, grad_clip=...)``.

TPU-native design: a clip strategy is a pure function over (param, grad)
pairs. Grads arrive as device arrays on the eager tape, so clipping is
plain jnp math that XLA fuses into the update step; in static mode the
same classes emit graph ops via ``layers.clip`` / ``layers.clip_by_norm``
so ``minimize(grad_clip=...)`` works in BOTH modes (the reference only
honors it in dygraph and silently drops it for static graphs — we accept
it everywhere instead).
"""
import jax.numpy as jnp

from . import framework

__all__ = [
    "GradClipByValue",
    "GradClipByNorm",
    "GradClipByGlobalNorm",
]


def _is_symbolic(g):
    return isinstance(g, framework.Variable)


def _raw(g):
    # eager grads are jnp arrays; accept VarBase too for direct calls
    value = getattr(g, "value", None)
    return g if value is None else value


class GradClipBase:
    """Callable over a list of (param, grad) pairs; None grads pass through."""

    def __str__(self):
        raise NotImplementedError()

    def _clip(self, para_and_grad):
        raise NotImplementedError()

    def __call__(self, para_and_grad):
        return self._clip(para_and_grad)


class GradClipByValue(GradClipBase):
    """Elementwise clamp of every gradient to [min_value, max_value].

    ref dygraph_grad_clip.py:45. If ``min_value`` is None it defaults to
    ``-max_value`` (which must then be positive).
    """

    def __init__(self, min_value, max_value=None):
        if min_value is None and max_value is None:
            raise ValueError(
                "GradClipByValue: at least one bound must be given"
            )
        if min_value is None:
            if max_value <= 0.0:
                raise ValueError(
                    "GradClipByValue: max_value must be positive when "
                    "min_value is None"
                )
            min_value = -max_value
        if max_value is None:
            max_value = abs(float(min_value))
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def __str__(self):
        return "ClipByValue, min = %f, max=%f" % (
            self.min_value, self.max_value)

    def _clip(self, para_and_grad):
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, g))
            elif _is_symbolic(g):
                from .layers import nn as _nn
                out.append((p, _nn.clip(g, self.min_value, self.max_value)))
            else:
                out.append(
                    (p, jnp.clip(_raw(g), self.min_value, self.max_value)))
        return out


class GradClipByNorm(GradClipBase):
    """Per-tensor L2 norm clip: g * clip_norm / max(clip_norm, ||g||).

    ref dygraph_grad_clip.py:120.
    """

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __str__(self):
        return "ClipByNorm, clip_norm=%f" % self.clip_norm

    def _clip(self, para_and_grad):
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, g))
            elif _is_symbolic(g):
                from .layers import nn as _nn
                out.append((p, _nn.clip_by_norm(g, self.clip_norm)))
            else:
                gv = _raw(g)
                norm = jnp.sqrt(jnp.sum(jnp.square(
                    gv.astype(jnp.float32))))
                scale = self.clip_norm / jnp.maximum(norm, self.clip_norm)
                out.append((p, (gv * scale.astype(gv.dtype))))
        return out


class GradClipByGlobalNorm(GradClipBase):
    """Joint clip: every grad scaled by max_norm / max(global_norm, max_norm)
    where global_norm = sqrt(sum ||g_i||^2) over ALL grads.

    ref dygraph_grad_clip.py:191.
    """

    def __init__(self, max_global_norm, dtype="float32"):
        self.max_global_norm = float(max_global_norm)
        self.dtype = dtype

    def __str__(self):
        return "ClipByGlobalNorm, max_global_norm=%f" % self.max_global_norm

    def _clip(self, para_and_grad):
        live = [(p, g) for p, g in para_and_grad if g is not None]
        if not live:
            return list(para_and_grad)
        if any(_is_symbolic(g) for _, g in live):
            # static mode: reuse the graph-side global-norm group clip
            from .clip import _global_norm_clip_group
            clipped = iter(
                _global_norm_clip_group(live, self.max_global_norm))
            return [
                (p, next(clipped)[1]) if g is not None else (p, g)
                for p, g in para_and_grad
            ]
        sq = sum(
            jnp.sum(jnp.square(_raw(g).astype(jnp.float32)))
            for _, g in live
        )
        global_norm = jnp.sqrt(sq)
        scale = self.max_global_norm / jnp.maximum(
            global_norm, self.max_global_norm)
        out = []
        for p, g in para_and_grad:
            if g is None:
                out.append((p, g))
            else:
                gv = _raw(g)
                out.append((p, gv * scale.astype(gv.dtype)))
        return out
