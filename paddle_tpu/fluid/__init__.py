"""paddle_tpu.fluid — TPU-native re-implementation of the PaddlePaddle Fluid
API (ref: python/paddle/fluid/__init__.py)."""
from . import core
from . import framework
from .framework import (  # noqa: F401
    Program,
    Variable,
    Operator,
    Parameter,
    default_main_program,
    default_startup_program,
    program_guard,
    name_scope,
    in_dygraph_mode,
    cpu_places,
    cuda_places,
    tpu_places,
    cuda_pinned_places,
    is_compiled_with_cuda,
    require_version,
    load_op_library,
)
from .core import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
    TPUPlace,
    is_compiled_with_tpu,
)
from . import executor
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from . import resilience
from .resilience import (  # noqa: F401
    FaultInjector,
    GuardedExecutor,
    TrainGuard,
    run_guarded,
)
from . import initializer
from . import layers
from .data import data  # noqa: F401
from . import backward
from .backward import append_backward, gradients  # noqa: F401
from . import optimizer
from . import regularizer
from . import clip
from . import dygraph_grad_clip
from .dygraph_grad_clip import (  # noqa: F401
    GradClipByValue,
    GradClipByNorm,
    GradClipByGlobalNorm,
)
from . import unique_name
from . import param_attr
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from . import layer_helper
from .layer_helper import LayerHelper  # noqa: F401
from . import data_feeder
from .data_feeder import DataFeeder  # noqa: F401
from . import lod
from .lod import LoDTensor, create_lod_tensor, create_random_int_lodtensor  # noqa: F401
from . import io
from . import nets
from . import average
from . import metrics
from . import reader
from .reader import DataLoader  # noqa: F401
from . import dataset
from .dataset import DatasetFactory, InMemoryDataset, QueueDataset  # noqa: F401
from . import data_feed_desc
from .data_feed_desc import DataFeedDesc  # noqa: F401
from . import device_worker
from . import trainer_factory
from .trainer_factory import FetchHandler  # noqa: F401
from . import compiler
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa: F401
from . import parallel_executor
from .parallel_executor import ParallelExecutor  # noqa: F401
from . import dygraph
from . import profiler
from . import contrib
from . import evaluator
from . import inference
from . import transpiler
from . import debugger
from . import graphviz
from . import net_drawer
from . import communicator
from .communicator import Communicator  # noqa: F401
from . import annotations
from . import wrapped_decorator
from . import default_scope_funcs
from . import input
from .input import one_hot, embedding  # noqa: F401
from . import lod_tensor
from . import log_helper
from . import install_check
from . import trainer_desc
from .trainer_desc import (  # noqa: F401
    DistMultiTrainer,
    MultiTrainer,
    PipelineTrainer,
    TrainerDesc,
)
from . import distribute_lookup_table
from . import inferencer
from . import layer_helper_base
from . import incubate  # noqa: F401
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig, memory_optimize, release_memory  # noqa: F401

# top-level conveniences/aliases matching the reference fluid namespace
from .dygraph.tracer import VarBase  # noqa: F401
from .io import save, load  # noqa: F401
# fluid.embedding / fluid.one_hot are the v2 variants from .input
# (imported above); fluid.layers.* keep the v1 trailing-1 squeeze.
from .layers import learning_rate_scheduler as learning_rate_decay  # noqa: F401

import numpy as _np

Tensor = _np.ndarray  # host tensors ARE numpy arrays in this runtime


class LoDTensorArray(list):
    """ref core.LoDTensorArray: a plain list of tensors host-side (the
    in-graph array type is layers.create_array's build-time list)."""


# late op registrations that need fluid internals
from ..ops import _register_late_modules as _late

_late()

__all__ = [
    "Program", "Variable", "Operator", "Parameter", "default_main_program",
    "default_startup_program", "program_guard", "name_scope", "Executor",
    "Scope", "global_scope", "scope_guard", "CPUPlace", "CUDAPlace",
    "TPUPlace", "append_backward", "gradients", "ParamAttr", "DataFeeder",
    "LoDTensor", "create_lod_tensor", "data", "layers", "initializer",
    "optimizer", "regularizer", "clip", "unique_name", "io", "nets",
    "metrics", "DataLoader", "CompiledProgram", "ParallelExecutor",
    "dygraph", "profiler", "contrib", "evaluator", "inference",
    "VarBase", "Tensor", "LoDTensorArray", "save", "load", "embedding",
    "one_hot", "learning_rate_decay", "dygraph_grad_clip", "average",
    "is_compiled_with_cuda", "is_compiled_with_tpu", "require_version",
    "load_op_library",
]


# fluid.install_check is the module (import above); run
# fluid.install_check.run_check() for the self-test (ref layout).
