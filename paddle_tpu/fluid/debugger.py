"""Program debugging utilities (ref: python/paddle/fluid/debugger.py):
colored program pretty-printing, graphviz block dumps, and a nan/inf
localizer.

The nan/inf path is TPU-reshaped: the reference inserts per-op isfinite
checks into the C++ executor loop; here one extra executor run fetches
every op's outputs from the already-lowered env and reports the first
non-finite producer with its callstack — no program mutation, no
recompile of the training step.
"""
import numpy as np

from . import framework

__all__ = [
    "repr_var", "repr_op", "pprint_block_codes", "pprint_program_codes",
    "draw_block_graphviz", "prepare_fast_nan_inf_debug",
    "run_fast_nan_inf_debug",
]


def repr_data_type(dtype):
    return str(dtype)


def repr_var(var):
    return "%s : %s%s" % (
        var.name,
        "%s[%s]" % (var.dtype, ",".join(str(s) for s in (var.shape or ()))),
        " persistable" if getattr(var, "persistable", False) else "",
    )


def repr_attr(name, value):
    return "%s=%r" % (name, value)


def repr_op(op):
    outs = ", ".join(n for ns in op.outputs.values() for n in ns)
    ins = ", ".join(n for ns in op.inputs.values() for n in ns)
    attrs = ", ".join(
        repr_attr(k, v) for k, v in sorted(op.attrs.items())
        if not k.startswith("_")
    )
    return "%s = %s(%s)%s" % (
        outs or "()", op.type, ins, (" {%s}" % attrs) if attrs else "")


def pprint_block_codes(block, show_backward=False):
    lines = ["# block %d" % block.idx]
    for name in sorted(block.vars):
        if not show_backward and "@GRAD" in name:
            continue
        lines.append("var " + repr_var(block.vars[name]))
    lines.append("")
    for op in block.ops:
        if not show_backward and op.type == "backward":
            lines.append("# (backward region: vjp over the ops above)")
            continue
        lines.append(repr_op(op))
    return "\n".join(lines) + "\n"


def pprint_program_codes(program, show_backward=False):
    return "\n".join(
        pprint_block_codes(b, show_backward) for b in program.blocks)


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Dump a block as graphviz dot: ops are boxes, vars ellipses,
    params octagons; `highlights` names vars to color. Returns the
    written path (pdf when the dot binary exists)."""
    from .graphviz import GraphPreviewGenerator

    highlights = set(highlights or ())
    gen = GraphPreviewGenerator("block %d" % block.idx)
    var_nodes = {}

    def var_node(name):
        if name not in var_nodes:
            var = block.vars.get(name)
            persistable = var is not None and getattr(
                var, "persistable", False)
            if persistable:
                var_nodes[name] = gen.add_param(
                    name, getattr(var, "dtype", "?"),
                    highlight=name in highlights)
            else:
                var_nodes[name] = gen.add_arg(
                    name, highlight=name in highlights)
        return var_nodes[name]

    for op in block.ops:
        op_node = gen.add_op(op.type)
        for ns in op.inputs.values():
            for n in ns:
                gen.add_edge(var_node(n), op_node)
        for ns in op.outputs.values():
            for n in ns:
                gen.add_edge(op_node, var_node(n))
    return gen.graph.compile(path)


# ---------------------------------------------------------------------------
# nan/inf localization
# ---------------------------------------------------------------------------
def prepare_fast_nan_inf_debug(program):
    """Mark a program for nan/inf debugging. The TPU path needs no
    program surgery (see module docstring); this records intent so
    run_fast_nan_inf_debug can assert it's used as documented."""
    program._nan_inf_debug = True
    return program


def run_fast_nan_inf_debug(executor, program=None, feed=None,
                           fetch_list=None, scope=None, return_numpy=True,
                           use_program_cache=False, dump_core=True):
    """Run one step; if any fetched value is non-finite, re-run fetching
    EVERY op output and raise naming the first non-finite producer and
    its python callstack."""
    program = program or framework.default_main_program()
    outs = executor.run(program, feed=feed, fetch_list=fetch_list,
                        scope=scope, return_numpy=return_numpy)
    bad = any(
        not np.all(np.isfinite(np.asarray(o, dtype=np.float64)))
        for o in (outs or [])
        if np.asarray(o).dtype.kind in "fc"
    )
    if not bad:
        return outs
    # localize: fetch per-op outputs in program order
    block = program.global_block()
    for op in block.ops:
        if op.type == "backward":
            break
        names = [n for ns in op.outputs.values() for n in ns]
        vars_ = [block.vars[n] for n in names if n in block.vars]
        if not vars_:
            continue
        vals = executor.run(program, feed=feed, fetch_list=vars_,
                            scope=scope)
        for n, v in zip(names, vals):
            arr = np.asarray(v)
            if arr.dtype.kind in "fc" and not np.all(np.isfinite(arr)):
                from .lowering import _format_callstack

                raise FloatingPointError(
                    "first non-finite value produced by op '%s' output "
                    "'%s' (nan=%d inf=%d of %d)\n  op: %s\n  defined "
                    "at:\n%s" % (
                        op.type, n,
                        int(np.isnan(arr).sum()),
                        int(np.isinf(arr).sum()), arr.size,
                        repr_op(op), _format_callstack(op),
                    ))
    raise FloatingPointError(
        "fetched values are non-finite but no forward op produced a "
        "non-finite output — the source is in the backward region; "
        "inspect gradients via fluid.gradients() probes"
    )
