"""Program debugging utilities (ref: python/paddle/fluid/debugger.py):
colored program pretty-printing, graphviz block dumps, and a nan/inf
localizer.

The nan/inf path is TPU-reshaped: the reference inserts per-op isfinite
checks into the C++ executor loop; here one extra executor run fetches
every op's outputs from the already-lowered env and reports the first
non-finite producer with its callstack — no program mutation, no
recompile of the training step.
"""
import numpy as np

from . import framework

__all__ = [
    "repr_var", "repr_op", "pprint_block_codes", "pprint_program_codes",
    "draw_block_graphviz", "prepare_fast_nan_inf_debug",
    "run_fast_nan_inf_debug",
]


def repr_data_type(dtype):
    return str(dtype)


def repr_var(var):
    return "%s : %s%s" % (
        var.name,
        "%s[%s]" % (var.dtype, ",".join(str(s) for s in (var.shape or ()))),
        " persistable" if getattr(var, "persistable", False) else "",
    )


def repr_attr(name, value):
    return "%s=%r" % (name, value)


def repr_op(op):
    outs = ", ".join(n for ns in op.outputs.values() for n in ns)
    ins = ", ".join(n for ns in op.inputs.values() for n in ns)
    attrs = ", ".join(
        repr_attr(k, v) for k, v in sorted(op.attrs.items())
        if not k.startswith("_")
    )
    return "%s = %s(%s)%s" % (
        outs or "()", op.type, ins, (" {%s}" % attrs) if attrs else "")


def pprint_block_codes(block, show_backward=False, owner=None,
                       dead_op_idx=(), dead_vars=(), note=None):
    """One block as pseudo-code. ``owner`` annotates a sub-block with
    the op whose body it is; ``dead_op_idx``/``dead_vars`` (from
    ``analysis.walker.live_report``) mark code off the fetch slice."""
    dead_op_idx = set(dead_op_idx)
    dead_vars = set(dead_vars)
    head = "# block %d" % block.idx
    if owner is not None:
        head += " — body of '%s' (block %d)" % (owner.type,
                                                block.parent_idx)
    if note:
        head += " — " + note
    lines = [head]
    for name in sorted(block.vars):
        if not show_backward and "@GRAD" in name:
            continue
        mark = "   # dead: not on the fetch slice" \
            if name in dead_vars else ""
        lines.append("var " + repr_var(block.vars[name]) + mark)
    lines.append("")
    for i, op in enumerate(block.ops):
        if not show_backward and op.type == "backward":
            lines.append("# (backward region: vjp over the ops above)")
            continue
        prefix = "# dead: " if i in dead_op_idx else ""
        lines.append(prefix + repr_op(op))
    return "\n".join(lines) + "\n"


def pprint_program_codes(program, show_backward=False, fetch_names=None):
    """Whole-program dump routed through the analyzer's walker
    (``paddle_tpu.analysis.walker``): blocks print in pre-order with
    each sub-block right after — and annotated with — the op that owns
    it; blocks no op references are flagged unreachable. With
    ``fetch_names``, global-block ops/vars off the fetch slice get
    ``# dead`` marks (``walker.live_report``)."""
    from ..analysis import walker

    dead_op_idx, dead_vars = (), ()
    if fetch_names:
        live, dead_ops, dead_vars = walker.live_report(
            program, fetch_names)
        dead_op_idx = [i for i, _op in dead_ops]
    chunks = []
    for block, owner in walker.iter_blocks(program):
        note = None
        if block.idx != 0 and owner is None:
            note = "UNREACHABLE (no op references this block)"
        chunks.append(pprint_block_codes(
            block, show_backward, owner=owner, note=note,
            dead_op_idx=dead_op_idx if block.idx == 0 else (),
            dead_vars=dead_vars if block.idx == 0 else ()))
    return "\n".join(chunks)


def draw_block_graphviz(block, highlights=None, path="./temp.dot",
                        fetch_names=None):
    """Dump a block as graphviz dot: ops are boxes, vars ellipses,
    params octagons; `highlights` names vars to color. Sub-blocks owned
    by control-flow ops render as nested clusters (the descent goes
    through ``analysis.walker``, so cond's true/false blocks and RNN
    bodies all resolve), with outer vars looked up through the parent
    chain — a param read inside a loop body renders as a param, not a
    bare arg. With ``fetch_names``, vars off the fetch slice go gray.
    Returns the written path (pdf when the dot binary exists)."""
    from .graphviz import GraphPreviewGenerator
    from ..analysis import walker

    program = block.program
    highlights = set(highlights or ())
    dead = set()
    if fetch_names:
        _live, _dead_ops, dead_vars = walker.live_report(
            program, fetch_names)
        dead = set(dead_vars)
    gen = GraphPreviewGenerator("block %d" % block.idx)
    var_nodes = {}

    def var_node(blk, name, sub):
        if name not in var_nodes:
            var = blk._var_recursive(name) \
                if blk.has_var_recursive(name) else None
            persistable = var is not None and getattr(
                var, "persistable", False)
            if persistable:
                var_nodes[name] = gen.add_param(
                    name, getattr(var, "dtype", "?"),
                    highlight=name in highlights, subgraph=sub)
            else:
                var_nodes[name] = gen.add_arg(
                    name, highlight=name in highlights,
                    dead=name in dead, subgraph=sub)
        return var_nodes[name]

    seen_blocks = set()

    def draw(blk, sub):
        """Draw one block's ops (into cluster `sub`); returns the first
        op node as the anchor its owner links to."""
        if blk.idx in seen_blocks:
            return None  # malformed self/cyclic block refs: draw once
        seen_blocks.add(blk.idx)
        first = None
        for op in blk.ops:
            op_node = gen.add_op(op.type, subgraph=sub)
            first = first if first is not None else op_node
            for ns in op.inputs.values():
                for n in ns:
                    gen.add_edge(var_node(blk, n, sub), op_node)
            for ns in op.outputs.values():
                for n in ns:
                    gen.add_edge(op_node, var_node(blk, n, sub))
            for attr, child in walker.sub_blocks(program, op):
                cluster = gen.add_subgraph(
                    "block %d: %s of '%s'" % (child.idx, attr, op.type))
                anchor = draw(child, cluster)
                if anchor is not None:
                    gen.add_edge(op_node, anchor, style="dashed",
                                 label=attr)
        return first

    draw(block, None)
    return gen.graph.compile(path)


# ---------------------------------------------------------------------------
# nan/inf localization
# ---------------------------------------------------------------------------
def prepare_fast_nan_inf_debug(program):
    """Mark a program for nan/inf debugging. The TPU path needs no
    program surgery (see module docstring); this records intent so
    run_fast_nan_inf_debug can assert it's used as documented."""
    program._nan_inf_debug = True
    return program


def run_fast_nan_inf_debug(executor, program=None, feed=None,
                           fetch_list=None, scope=None, return_numpy=True,
                           use_program_cache=False, dump_core=True):
    """Run one step; if any fetched value is non-finite, re-run fetching
    EVERY op output and raise naming the first non-finite producer and
    its python callstack."""
    program = program or framework.default_main_program()
    outs = executor.run(program, feed=feed, fetch_list=fetch_list,
                        scope=scope, return_numpy=return_numpy)
    bad = any(
        not np.all(np.isfinite(np.asarray(o, dtype=np.float64)))
        for o in (outs or [])
        if np.asarray(o).dtype.kind in "fc"
    )
    if not bad:
        return outs
    # localize: fetch per-op outputs in program order
    block = program.global_block()
    for op in block.ops:
        if op.type == "backward":
            break
        names = [n for ns in op.outputs.values() for n in ns]
        vars_ = [block.vars[n] for n in names if n in block.vars]
        if not vars_:
            continue
        vals = executor.run(program, feed=feed, fetch_list=vars_,
                            scope=scope)
        for n, v in zip(names, vals):
            arr = np.asarray(v)
            if arr.dtype.kind in "fc" and not np.all(np.isfinite(arr)):
                from .lowering import _format_callstack

                raise FloatingPointError(
                    "first non-finite value produced by op '%s' output "
                    "'%s' (nan=%d inf=%d of %d)\n  op: %s\n  defined "
                    "at:\n%s" % (
                        op.type, n,
                        int(np.isnan(arr).sum()),
                        int(np.isinf(arr).sum()), arr.size,
                        repr_op(op), _format_callstack(op),
                    ))
    raise FloatingPointError(
        "fetched values are non-finite but no forward op produced a "
        "non-finite output — the source is in the backward region; "
        "inspect gradients via fluid.gradients() probes"
    )
