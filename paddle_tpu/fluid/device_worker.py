"""Device workers (ref: python/paddle/fluid/device_worker.py).

The reference's DeviceWorker subclasses generate protobuf trainer descs
consumed by C++ worker threads (HogwildWorker, DownpourSGD pserver
workers, Section pipeline workers). On TPU there is one execution
stream: the "worker" is the jitted whole-program step, and concurrency
lives in host-side parsing + the native staging ring. These classes keep
the reference's configuration surface and emit a plain-dict desc that
`trainer_factory` and `Executor.train_from_dataset` consume.
"""

__all__ = [
    "DeviceWorker", "Hogwild", "DownpourSGD", "Section",
    "DeviceWorkerFactory",
]


class DeviceWorker:
    """ref device_worker.py:19."""

    def __init__(self):
        self._program = None
        self._infer = None
        self._fleet_desc = None

    def _set_infer(self, infer=False):
        self._infer = bool(infer)

    def _set_fleet_desc(self, fleet_desc):
        self._fleet_desc = fleet_desc

    def _set_program(self, program):
        self._program = program

    def _gen_worker_desc(self, trainer_desc):
        raise NotImplementedError(
            "DeviceWorker does not implement gen_worker_desc; use a "
            "subclass (Hogwild/Section)"
        )


class Hogwild(DeviceWorker):
    """ref device_worker.py:70. On TPU the 'Hogwild' execution contract
    (each worker repeatedly runs the program on its next batch) maps to
    the single jitted step; lock-free shared-memory updates do not exist
    because XLA updates donated params in place on one stream."""

    def __init__(self):
        super().__init__()

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc["device_worker_name"] = "HogwildWorker"
        if self._infer:
            trainer_desc["hogwild_param"] = {
                "skip_ops": ["backward", "sgd", "momentum", "adam"]
            }
        return trainer_desc


class DownpourSGD(DeviceWorker):
    """ref device_worker.py:93 — pserver push/pull worker. The pserver
    architecture is re-mapped to sharded embeddings + ICI collectives
    (see fluid/transpiler.py); a Downpour-style async worker has no TPU
    equivalent, so constructing one is a loud error."""

    def __init__(self):
        raise NotImplementedError(
            "DownpourSGD device worker: pserver push/pull is replaced by "
            "sharded embeddings + collectives on TPU; use "
            "fleet.distributed_optimizer with the collective mode"
        )


class Section(DeviceWorker):
    """ref device_worker.py:193 — pipeline-parallel section worker; the
    TPU pipeline is `parallel/pipeline.py` (microbatched lax.scan over a
    stage-sharded mesh axis)."""

    def __init__(self):
        super().__init__()
        self._section_config = {}

    def _gen_worker_desc(self, trainer_desc):
        trainer_desc["device_worker_name"] = "SectionWorker"
        trainer_desc["section_param"] = dict(self._section_config)
        return trainer_desc


class DeviceWorkerFactory:
    """ref device_worker.py:241."""

    def _create_device_worker(self, worker_type):
        classes = {"Hogwild": Hogwild, "DownpourSGD": DownpourSGD,
                   "Section": Section}
        key = worker_type[0].upper() + worker_type[1:]
        if key not in classes:
            raise ValueError(
                "unknown device worker %r (have %s)"
                % (worker_type, sorted(classes))
            )
        return classes[key]()
