"""DataFeedDesc (ref: python/paddle/fluid/data_feed_desc.py).

Describes the MultiSlot text format the Dataset trainer path consumes:
one line per sample, each slot serialized as ``<n> v1 .. vn``. The
reference stores the description as a DataFeedDesc protobuf; here it is
a plain python structure parsed from (and printed back to) the same
text-proto format, so reference ``.proto`` files work unchanged without
a protobuf runtime dependency.
"""

__all__ = ["DataFeedDesc"]


class _Slot:
    __slots__ = ("name", "type", "is_dense", "is_used", "dense_dim")

    def __init__(self, name, type="uint64", is_dense=False, is_used=False):
        self.name = name
        self.type = type
        self.is_dense = is_dense
        self.is_used = is_used
        self.dense_dim = 1


def _parse_text_proto(text):
    """Minimal text-proto reader for the DataFeedDesc schema: top-level
    scalar fields, one ``multi_slot_desc`` block containing repeated
    ``slots`` blocks of scalar fields."""
    import re

    top = {"name": "MultiSlotDataFeed", "batch_size": 32}
    slots = []
    # tokenize: key: value | key { | }
    tokens = re.findall(r'[\w_]+\s*:\s*(?:"[^"]*"|[^\s{}]+)|[\w_]+\s*\{|\}',
                        text)
    stack = []
    cur = None
    for tok in tokens:
        tok = tok.strip()
        if tok.endswith("{"):
            scope = tok[:-1].strip()
            stack.append(scope)
            if scope == "slots":
                cur = {}
            continue
        if tok == "}":
            scope = stack.pop()
            if scope == "slots" and cur is not None:
                s = _Slot(
                    cur.get("name", "slot%d" % len(slots)),
                    cur.get("type", "uint64"),
                    _truthy(cur.get("is_dense", "false")),
                    _truthy(cur.get("is_used", "false")),
                )
                slots.append(s)
                cur = None
            continue
        key, _, val = tok.partition(":")
        key, val = key.strip(), val.strip().strip('"')
        if stack and stack[-1] == "slots":
            cur[key] = val
        elif not stack:
            top[key] = val
    return top, slots


def _truthy(v):
    return str(v).lower() in ("true", "1")


class DataFeedDesc:
    """Parse a text-proto description of the feed (ref data_feed_desc.py:21).

    Accepts either a path to a proto text file (the reference calling
    convention) or the proto text itself (convenience).
    """

    def __init__(self, proto_file):
        import os

        if os.path.exists(proto_file):
            with open(proto_file) as f:
                text = f.read()
        else:
            text = proto_file
        top, slots = _parse_text_proto(text)
        self._name = top.get("name", "MultiSlotDataFeed")
        self._batch_size = int(top.get("batch_size", 32))
        self._slots = slots
        self.__name_to_slot = {s.name: s for s in slots}

    # -- mutators (ref API) --------------------------------------------
    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        """Mark slots dense: batch values become a contiguous (B, n)
        array instead of a ragged LoD slot."""
        for n in dense_slots_name:
            if n not in self.__name_to_slot:
                raise ValueError(
                    "set_dense_slots: unknown slot %r (have %s)"
                    % (n, sorted(self.__name_to_slot))
                )
            self.__name_to_slot[n].is_dense = True

    def set_use_slots(self, use_slots_name):
        for n in use_slots_name:
            if n not in self.__name_to_slot:
                raise ValueError(
                    "set_use_slots: unknown slot %r (have %s)"
                    % (n, sorted(self.__name_to_slot))
                )
            self.__name_to_slot[n].is_used = True

    # -- introspection -------------------------------------------------
    @property
    def slots(self):
        return list(self._slots)

    def used_slots(self):
        return [s for s in self._slots if s.is_used]

    def desc(self):
        """Text-proto form (ref returns the protobuf text dump)."""
        out = ['name: "%s"' % self._name,
               "batch_size: %d" % self._batch_size,
               "multi_slot_desc {"]
        for s in self._slots:
            out.append("  slots {")
            out.append('    name: "%s"' % s.name)
            out.append('    type: "%s"' % s.type)
            out.append("    is_dense: %s" % str(s.is_dense).lower())
            out.append("    is_used: %s" % str(s.is_used).lower())
            out.append("  }")
        out.append("}")
        return "\n".join(out) + "\n"
