"""DataFeeder (ref: python/paddle/fluid/data_feeder.py): converts python /
numpy minibatch rows into the feed dict of dense arrays."""
import numpy as np

from . import core
from .framework import Variable

__all__ = ["DataFeeder"]


class ColumnarBatch:
    """A minibatch already materialized as per-slot batch-major arrays.

    Produced by InMemoryDataset's columnar fast path (dataset.py): when
    every slot is fixed-length the whole in-memory dataset is stacked
    into one dense array per slot ONCE, and each batch is a zero-copy
    slice of those columns. DataFeeder.feed passes the columns through
    with only a dtype/shape adjustment instead of re-stacking thousands
    of per-sample lists — the difference between an O(batch) python
    loop and an O(1) numpy view per step (the reference pays neither:
    its C++ DataFeed writes straight into LoDTensor buffers).

    Iteration/indexing fall back to sample tuples so consumers written
    against the sample-list contract keep working.
    """

    __slots__ = ("columns",)

    def __init__(self, columns):
        self.columns = list(columns)

    def __len__(self):
        return len(self.columns[0]) if self.columns else 0

    def __getitem__(self, i):
        return tuple(c[i] for c in self.columns)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


class DataToLoDTensorConverter:
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = [s if s not in (None, -1) else None for s in shape]
        self.dtype = core.np_dtype(core.convert_dtype(dtype))
        self.data = []

    def feed(self, data):
        self.data.append(np.asarray(data, dtype=self.dtype))

    def done(self):
        if self.lod_level == 0:
            arr = np.stack(
                [d.reshape([s for s in self.shape[1:] if s is not None] or d.shape)
                 if None not in self.shape[1:] else d
                 for d in self.data]
            )
            return arr
        # LoD case: pad to max length, companion lengths array
        from .lod import LoDTensor

        return LoDTensor.from_sequences(self.data)


class DataFeeder:
    def __init__(self, feed_list, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        for each_var in feed_list:
            if isinstance(each_var, str):
                from .framework import default_main_program

                each_var = (program or default_main_program()).global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("feed_list should contain Variables")
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            self.feed_shapes.append(each_var.shape)
        self.place = place

    def feed(self, iterable):
        if isinstance(iterable, ColumnarBatch):
            return self._feed_columns(iterable.columns)
        converters = [
            DataToLoDTensorConverter(self.place, lod, shape, dtype)
            for lod, shape, dtype in zip(
                self.feed_lod_level, self.feed_shapes, self.feed_dtypes
            )
        ]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                "sample has %d fields, expected %d"
                % (len(each_sample), len(converters))
            )
            for value, converter in zip(each_sample, converters):
                converter.feed(value)
        return {
            name: conv.done()
            for name, conv in zip(self.feed_names, converters)
        }

    def _feed_columns(self, columns):
        if len(columns) != len(self.feed_names):
            raise ValueError(
                "columnar batch has %d slots, feed_list expects %d"
                % (len(columns), len(self.feed_names))
            )
        out = {}
        for name, dtype, shape, col in zip(
            self.feed_names, self.feed_dtypes, self.feed_shapes, columns
        ):
            arr = np.asarray(col)
            want = core.np_dtype(core.convert_dtype(dtype))
            if arr.dtype != want:
                arr = arr.astype(want)
            # same rule as DataToLoDTensorConverter.done: only reshape
            # when the per-sample shape is fully static
            dims = tuple(
                None if s in (None, -1) else s for s in (shape or [])[1:])
            if dims and None not in dims and arr.shape[1:] != dims:
                arr = arr.reshape((arr.shape[0],) + dims)
            out[name] = arr
        return out

    def feed_parallel(self, iterable, num_places=None):
        yield self.feed(iterable)

    def decorate_reader(self, reader, multi_devices=False, num_places=None,
                        drop_last=True):
        # drop_last (ref data_feeder.py): a trailing batch smaller than the
        # established batch size is dropped — essential on TPU, where a
        # ragged final batch would trigger a fresh XLA compilation. The
        # batch size is established from the FIRST batch; a stream whose
        # only batch is ragged has no size reference and passes through.
        def __reader_creator__():
            full = None
            for item in reader():
                if full is None:
                    full = len(item)
                if drop_last and len(item) < full:
                    continue
                yield self.feed(item)

        return __reader_creator__
