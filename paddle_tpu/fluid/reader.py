"""DataLoader / py_reader equivalents (ref: python/paddle/fluid/reader.py,
operators/reader/*). The C++ blocking-queue + prefetch worker pipeline is
rebuilt in paddle_tpu/native/dataloader.cpp; this module is the python
surface. Falls back to a pure-python thread pipeline when the native lib
isn't built yet."""
import queue
import threading

import numpy as np

from .data_feeder import DataFeeder
from .framework import Variable

__all__ = ["DataLoader", "PyReader"]


class _GeneratorLoader:
    def __init__(self, feed_list, capacity, iterable=True,
                 return_list=False, use_double_buffer=True):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._use_double_buffer = use_double_buffer
        self._batch_reader = None
        self._places = None
        self._thread = None
        self._queue = None
        self._running = False

    # -- decorators ------------------------------------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        from ..reader_utils import batch as batch_fn

        def _batched():
            for b in batch_fn(reader, batch_size, drop_last)():
                yield b

        return self.set_sample_list_generator(_batched, places)

    def set_sample_list_generator(self, reader, places=None):
        def _feeder():
            feeder = DataFeeder(self._feed_list, places)
            for samples in reader():
                yield feeder.feed(samples)

        self._batch_reader = _feeder
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        def _named():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield {
                        v.name: np.asarray(b)
                        for v, b in zip(self._feed_list, batch)
                    }

        self._batch_reader = _named
        self._places = places
        return self

    # -- iteration (prefetch via the native C++ pipeline when available) --
    def _pump(self, native_pipe):
        try:
            for item in self._batch_reader():
                if not self._running:
                    break
                native_pipe.put(item)
        finally:
            native_pipe.put(None)

    def _pump_native(self, pipe):
        try:
            for item in self._batch_reader():
                if not self._running or not pipe.put(item):
                    return
        except BaseException as e:  # surface at the training loop, not EOF
            pipe.put_error("%s: %s" % (type(e).__name__, e))
            return
        pipe.put(None)

    def _native_pipe(self):
        """One C++ pipe per loader, reused across epochs (the arena alloc
        + mlock cost is paid once, not per __iter__)."""
        from ..native import pipeline

        if getattr(self, "_pipe", None) is not None:
            return self._pipe
        try:
            self._pipe = pipeline.NativeBatchPipe(
                capacity=max(2, min(self._capacity, 8))
            )
        except Exception:
            self._pipe = None
        return self._pipe

    def __iter__(self):
        it = self._iter_host()
        if self._use_double_buffer:
            it = self._device_ahead(it)
        yield from it

    def _device_ahead(self, it):
        """use_double_buffer's device half (ref double_buffer op: a
        device-side prefetch buffer between the reader and the
        executor). The NEXT batch's host->device transfer is ISSUED
        before the current batch is yielded, so it rides the device's
        async dispatch while the consumer runs the current step —
        without this, a tunneled TPU pays the full transfer RTT on the
        critical path of every step. Engages only when the loader
        targets ONE accelerator place (the single-device Executor fast
        path); CPU runs, multi-place and placeless loaders keep
        yielding numpy — sharded/data-parallel runners re-shard feeds
        themselves, and handing them dev0-committed arrays would ADD a
        readback per step instead of removing a transfer."""
        import jax

        place = self._places
        if isinstance(place, (list, tuple)):
            if len(place) != 1:
                yield from it
                return
            place = place[0]
        try:
            dev = place.jax_device() if hasattr(place, "jax_device") \
                else None
        except Exception:  # noqa: BLE001 — backend unavailable
            dev = None
        if dev is None or dev.platform == "cpu":
            yield from it
            return

        def _put(v):
            # only plain dense arrays move; LoDTensors and exotic feed
            # values keep their host path through the executor
            if isinstance(v, np.ndarray):
                return jax.device_put(v, dev)
            return v

        pending = None
        while True:
            try:
                item = next(it)
            except StopIteration:
                break
            except BaseException:
                # reader failed mid-epoch: hand over the already-staged
                # batch first so no good batch is silently dropped
                if pending is not None:
                    yield pending
                raise
            if isinstance(item, dict):
                nxt = {k: _put(v) for k, v in item.items()}
            elif isinstance(item, (list, tuple)):
                nxt = [_put(v) for v in item]
            else:
                nxt = item
            if pending is not None:
                yield pending
            pending = nxt
        if pending is not None:
            yield pending

    def _iter_host(self):
        # Preferred path: batch bytes staged through the C++ slot ring
        # (copy worker pool + best-effort pinned arena), so host prep and
        # staging overlap the device step. Batches are copied out of the
        # ring before yielding — consumers may retain them freely (the
        # raw zero-copy contract lives on NativeBatchPipe for callers
        # that control batch lifetime). Fallback: token queue (objects
        # stay in python; still prefetched by the producer thread).
        import numpy as np

        pipe = self._native_pipe()
        if pipe is None:
            yield from self._iter_queue()
            return
        self._running = True
        pump = threading.Thread(
            target=self._pump_native, args=(pipe,), daemon=True
        )
        pump.start()
        clean_eof = False
        try:
            while True:
                item, release = pipe.get()
                if item is None:
                    clean_eof = True
                    break
                item = {k: np.array(v) for k, v in item.items()}
                release()
                if self._return_list:
                    yield [item[v.name] for v in self._feed_list]
                else:
                    yield item
        finally:
            self._running = False
            if not clean_eof:
                # early exit / consumer error: unblock the producer, let
                # it observe the abort, then re-arm for the next epoch
                pipe.abort()
                pump.join(timeout=10)
                pipe.reset()
            else:
                pump.join(timeout=10)

    def _iter_queue(self):
        from ..native import pipeline

        pipe = pipeline.make_queue(self._capacity)
        self._running = True
        self._thread = threading.Thread(
            target=self._pump, args=(pipe,), daemon=True
        )
        self._thread.start()
        try:
            while True:
                item = pipe.get()
                if item is None:
                    break
                if self._return_list:
                    yield [item[v.name] for v in self._feed_list]
                else:
                    yield item
        finally:
            self._running = False

    def __call__(self):
        return self.__iter__()

    # non-iterable (start/reset) mode for PyReader parity ----------------
    def start(self):
        self._gen = iter(self)

    def reset(self):
        self._running = False
        self._gen = None


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False):
        return _GeneratorLoader(
            feed_list, capacity, iterable, return_list, use_double_buffer
        )

    @staticmethod
    def from_dataset(dataset, places, drop_last=True):
        """Iterate a fluid.dataset (Queue/InMemory) as a DataLoader
        (ref reader.py from_dataset): batches flow through the same
        native staging ring as from_generator loaders."""
        dataset._prepare_to_run()
        place = places[0] if isinstance(places, (list, tuple)) else places
        loader = _GeneratorLoader(
            feed_list=dataset.use_vars, capacity=8
        )

        def batches():
            # the configured batch size is the truth — with QueueDataset's
            # multi-threaded per-thread tails a PARTIAL batch can arrive
            # first, so inferring "full" from the first batch would leak
            # partials through drop_last
            full = getattr(dataset, "batch_size", None)
            for b in dataset._batch_iterator():
                if drop_last:
                    if full is None:
                        full = len(b)
                    if len(b) < full:
                        continue
                yield b

        return loader.set_sample_list_generator(batches, places=place)


class PyReader(_GeneratorLoader):
    """ref reader.py PyReader."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(
            feed_list, capacity, iterable, return_list, use_double_buffer
        )

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(
            sample_generator, batch_size, drop_last, places
        )

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)
