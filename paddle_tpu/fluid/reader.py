"""DataLoader / py_reader equivalents (ref: python/paddle/fluid/reader.py,
operators/reader/*). The C++ blocking-queue + prefetch worker pipeline is
rebuilt in paddle_tpu/native/dataloader.cpp; this module is the python
surface. Falls back to a pure-python thread pipeline when the native lib
isn't built yet."""
import queue
import threading

import numpy as np

from .data_feeder import DataFeeder
from .framework import Variable

__all__ = ["DataLoader", "PyReader"]


class _GeneratorLoader:
    def __init__(self, feed_list, capacity, iterable=True,
                 return_list=False, use_double_buffer=True):
        self._feed_list = feed_list or []
        self._capacity = capacity
        self._iterable = iterable
        self._return_list = return_list
        self._use_double_buffer = use_double_buffer
        self._batch_reader = None
        self._places = None
        self._thread = None
        self._queue = None
        self._running = False

    # -- decorators ------------------------------------------------------
    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        from ..reader_utils import batch as batch_fn

        def _batched():
            for b in batch_fn(reader, batch_size, drop_last)():
                yield b

        return self.set_sample_list_generator(_batched, places)

    def set_sample_list_generator(self, reader, places=None):
        def _feeder():
            feeder = DataFeeder(self._feed_list, places)
            for samples in reader():
                yield feeder.feed(samples)

        self._batch_reader = _feeder
        self._places = places
        return self

    def set_batch_generator(self, reader, places=None):
        def _named():
            for batch in reader():
                if isinstance(batch, dict):
                    yield batch
                else:
                    yield {
                        v.name: np.asarray(b)
                        for v, b in zip(self._feed_list, batch)
                    }

        self._batch_reader = _named
        self._places = places
        return self

    # -- iteration (prefetch via native ring buffer when available) ------
    def _pump(self, native_pipe):
        try:
            for item in self._batch_reader():
                if not self._running:
                    break
                native_pipe.put(item)
        finally:
            native_pipe.put(None)

    def __iter__(self):
        from ..native import pipeline

    # prefetch depth = capacity, producer thread decouples host IO from TPU
        pipe = pipeline.make_queue(self._capacity)
        self._running = True
        self._thread = threading.Thread(
            target=self._pump, args=(pipe,), daemon=True
        )
        self._thread.start()
        try:
            while True:
                item = pipe.get()
                if item is None:
                    break
                if self._return_list:
                    yield [item[v.name] for v in self._feed_list]
                else:
                    yield item
        finally:
            self._running = False

    def __call__(self):
        return self.__iter__()

    # non-iterable (start/reset) mode for PyReader parity ----------------
    def start(self):
        self._gen = iter(self)

    def reset(self):
        self._running = False
        self._gen = None


class DataLoader:
    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False):
        return _GeneratorLoader(
            feed_list, capacity, iterable, return_list, use_double_buffer
        )

    @staticmethod
    def from_dataset(dataset, places, drop_last=True):
        raise NotImplementedError(
            "dataset ingestion path: use from_generator with the dataset's "
            "reader"
        )


class PyReader(_GeneratorLoader):
    """ref reader.py PyReader."""

    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(
            feed_list, capacity, iterable, return_list, use_double_buffer
        )

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(
            sample_generator, batch_size, drop_last, places
        )

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places)
