"""fluid.data (ref: python/paddle/fluid/data.py).

Unlike ``fluid.layers.data`` (which PREPENDS a -1 batch dimension),
``fluid.data`` takes the FULL shape — write the batch dimension
yourself, using None (or -1) for "any size"::

    x = fluid.data(name="x", shape=[None, 784], dtype="float32")

This matches the reference exactly so ported scripts keep their
shapes; mixing up the two conventions was a silent-wrong-shape hazard.
"""
from .layers import io as _io

__all__ = ["data"]


def data(name, shape, dtype="float32", lod_level=0):
    full = [-1 if s is None else int(s) for s in shape]
    return _io.data(
        name, full, append_batch_size=False, dtype=dtype,
        lod_level=lod_level,
    )
