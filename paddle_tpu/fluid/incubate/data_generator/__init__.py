"""User-defined data generators for the Dataset trainer path
(ref: python/paddle/fluid/incubate/data_generator/__init__.py).

A DataGenerator subclass turns raw input lines into MultiSlot text the
dataset feed parses: ``dataset.set_pipe_command("python my_gen.py")``
runs the script over each file via stdin/stdout. ``generate_sample``
returns an iterator factory over ``[(slot_name, [values...]), ...]``
records; ``generate_batch`` optionally post-processes each batch of
parsed samples (e.g. in-batch negative sampling).
"""
import sys

__all__ = [
    "DataGenerator", "MultiSlotDataGenerator",
    "MultiSlotStringDataGenerator",
]


class DataGenerator:
    """ref data_generator/__init__.py:21."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32
        self._line_limit = None

    def _set_line_limit(self, line_limit):
        if not isinstance(line_limit, int):
            raise ValueError(
                "line_limit must be int, got %s" % type(line_limit)
            )
        if line_limit < 1:
            raise ValueError("line_limit can not be less than 1")
        self._line_limit = line_limit

    def set_batch(self, batch_size):
        """Batch size used to group samples before generate_batch."""
        self.batch_size_ = int(batch_size)

    # -- drivers --------------------------------------------------------
    def _drain(self, batch, out):
        for sample in self.generate_batch(batch)():
            out.write(self._gen_str(sample))

    def _run(self, lines, out):
        batch = []
        n_lines = 0
        for line in lines:
            for parsed in self.generate_sample(line)():
                if parsed is None:
                    continue
                batch.append(parsed)
                if len(batch) == self.batch_size_:
                    self._drain(batch, out)
                    batch = []
            n_lines += 1
            if self._line_limit and n_lines >= self._line_limit:
                break
        if batch:
            self._drain(batch, out)

    def run_from_memory(self, out=None):
        """Emit samples produced by generate_sample(None) — debugging and
        synthetic-corpus generation."""
        self._run([None], out or sys.stdout)

    def run_from_stdin(self, out=None):
        """Filter mode: raw lines on stdin -> MultiSlot text on stdout
        (what dataset.set_pipe_command runs)."""
        self._run(sys.stdin, out or sys.stdout)

    # -- user overrides -------------------------------------------------
    def generate_sample(self, line):
        raise NotImplementedError(
            "override generate_sample(line) returning an iterator "
            "factory over [(slot_name, [values]), ...] records"
        )

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s

        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator"
        )


class MultiSlotStringDataGenerator(DataGenerator):
    """String-valued slots; fastest path — no type bookkeeping
    (ref data_generator/__init__.py:238)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "generate_sample must yield a list/tuple of "
                "(name, [str, ...]) pairs, got %s" % type(line)
            )
        parts = []
        for name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Typed slots: first sample fixes each slot's type (int -> uint64,
    any float -> float) and later samples must conform
    (ref data_generator/__init__.py:300)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "generate_sample must yield a list/tuple of "
                "(name, [value, ...]) pairs, got %s" % type(line)
            )
        if self._proto_info is None:
            self._proto_info = []
            for name, elements in line:
                if not isinstance(name, str):
                    raise ValueError(
                        "slot name must be str, got %s" % type(name)
                    )
                if not isinstance(elements, list) or not elements:
                    raise ValueError(
                        "slot %r: elements must be a non-empty list "
                        "(pad empty fields in generate_sample)" % name
                    )
                slot_type = "uint64"
                if any(isinstance(e, float) for e in elements):
                    slot_type = "float"
                self._proto_info.append((name, slot_type))
        else:
            if len(line) != len(self._proto_info):
                raise ValueError(
                    "sample has %d slots, first sample had %d"
                    % (len(line), len(self._proto_info))
                )
        parts = []
        for i, (name, elements) in enumerate(line):
            known_name, known_type = self._proto_info[i]
            if name != known_name:
                raise ValueError(
                    "slot %d name %r != first sample's %r"
                    % (i, name, known_name)
                )
            if known_type == "uint64" and any(
                    isinstance(e, float) for e in elements):
                # widen, like the reference's type promotion
                self._proto_info[i] = (name, "float")
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"
