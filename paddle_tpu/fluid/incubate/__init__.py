"""fluid.incubate (ref: python/paddle/fluid/incubate): the fleet API
import paths user scripts rely on, re-exported from paddle_tpu.parallel."""
from . import fleet  # noqa: F401
from . import data_generator  # noqa: F401
