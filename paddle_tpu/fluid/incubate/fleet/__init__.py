"""incubate.fleet (ref: fluid/incubate/fleet)."""
from . import base  # noqa: F401
from . import collective  # noqa: F401
