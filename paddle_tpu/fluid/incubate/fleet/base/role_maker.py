"""Role makers (ref: incubate/fleet/base/role_maker.py) — re-exported
from the mesh-based fleet implementation."""
from paddle_tpu.parallel.fleet import (  # noqa: F401
    PaddleCloudRoleMaker,
    RoleMakerBase,
    UserDefinedRoleMaker,
)

GeneralRoleMaker = PaddleCloudRoleMaker
