"""incubate.fleet.base.fleet_base (ref: fleet base classes — Fleet,
DistributedOptimizer, Mode). The collective implementation lives in
parallel/fleet.py; PSLib subclasses live in parameter_server.pslib."""
from .....parallel.fleet import (  # noqa: F401
    DistributedOptimizer,
    Fleet,
)

__all__ = ["Fleet", "DistributedOptimizer", "Mode"]


class Mode:
    """ref fleet_base.py Mode enum."""

    TRANSPILER = 1
    PSLIB = 2
    COLLECTIVE = 3
