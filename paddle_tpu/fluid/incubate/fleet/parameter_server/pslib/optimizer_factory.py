"""Distributed optimizer factory for PSLib
(ref: incubate/fleet/parameter_server/pslib/optimizer_factory.py:27-402).

``DistributedAdam._minimize`` is where the reference turns a CTR program
into a Downpour config: find every distributed lookup table, register
sparse/dense tables on DownpourServer/DownpourWorker, and strip the
table update ops from the worker program (servers apply them async).

TPU-native delta: the table registry is kept (same introspection), but
instead of stripping ops for async servers, each sparse table's vocab
dim is sharded over the mesh — the update stays INSIDE the synchronous
jitted step and XLA routes the gather/scatter over ICI. No ops are
skipped (worker_skipped_ops is always empty) because nothing is remote.
"""
from .node import DownpourServer, DownpourWorker

__all__ = ["DistributedOptimizerImplBase", "DistributedAdam"]


class DistributedOptimizerImplBase(object):
    """ref optimizer_factory.py:27."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._learning_rate = getattr(optimizer, "_learning_rate", None)

    def minimize(self, losses, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        raise NotImplementedError


def _lookup_table_ops(program):
    return [
        op for op in program.global_block().ops
        if op.type in ("lookup_table", "lookup_table_v2")
        and (op.attrs.get("is_distributed") or op.attrs.get("is_sparse"))
    ]


class DistributedAdam(DistributedOptimizerImplBase):
    """ref optimizer_factory.py:54 — Adam on dense params, sparse-table
    config for every distributed embedding."""

    def __init__(self, optimizer):
        super().__init__(optimizer)
        self._window = 1
        self.type = "downpour"
        self.data_norm_name = [
            ".batch_size", ".batch_square_sum", ".batch_sum",
        ]

    # -- table discovery (ref optimizer_factory.py:71-148) --------------
    def _find_multi_distributed_lookup_table(self, losses):
        names = []
        for loss in losses:
            for op in _lookup_table_ops(loss.block.program):
                w = op.input("W")[0]
                if w not in names:
                    names.append(w)
        return names

    def _find_distributed_lookup_table_inputs(self, program, table_names):
        inputs = {n: [] for n in table_names}
        for op in _lookup_table_ops(program):
            w = op.input("W")[0]
            if w in inputs:
                inputs[w].extend(op.input("Ids"))
        return inputs

    def _find_distributed_lookup_table_outputs(self, program, table_names):
        outputs = {n: [] for n in table_names}
        for op in _lookup_table_ops(program):
            w = op.input("W")[0]
            if w in outputs:
                outputs[w].extend(op.output("Out"))
        return outputs

    def _find_distributed_lookup_table_grads(self, program, table_names):
        return {n: [n + "@GRAD"] for n in table_names}

    # -- the build (ref optimizer_factory.py:150) ------------------------
    def _minimize(self, losses, startup_program=None, parameter_list=None,
                  no_grad_set=None, strategy=None):
        if not isinstance(losses, (list, tuple)):
            losses = [losses]
        strategy = dict(strategy or {})
        programs = {id(loss.block.program) for loss in losses}
        if len(programs) > 1:
            raise NotImplementedError(
                "PSLib multi-program Hogwild training (one loss per "
                "program per thread pool) has no TPU mapping — train "
                "one program per step; losses must share a program"
            )
        program = losses[0].block.program

        table_names = self._find_multi_distributed_lookup_table(losses)
        server, worker = DownpourServer(), DownpourWorker(self._window)
        inputs = self._find_distributed_lookup_table_inputs(
            program, table_names)
        outputs = self._find_distributed_lookup_table_outputs(
            program, table_names)
        sparse_table_ids = {}
        for tid, name in enumerate(table_names):
            server.add_sparse_table(tid, strategy.get(name, strategy))
            worker.add_sparse_table(tid, inputs[name], outputs[name])
            sparse_table_ids[name] = tid

        optimize_ops, params_grads = [], []
        for loss in losses:
            ops, pg = self._optimizer.minimize(
                loss, startup_program, parameter_list, no_grad_set)
            optimize_ops.extend(ops or [])
            params_grads.extend(pg or [])

        dense_tid = len(table_names)
        dense_params = [
            p for p, _ in params_grads if p.name not in sparse_table_ids
        ]
        server.add_dense_table(
            dense_tid, dense_params,
            [p.name + "@GRAD" for p in dense_params], strategy)
        worker.add_dense_table(
            dense_tid, param_vars=dense_params,
            grad_vars=[p.name + "@GRAD" for p in dense_params])

        opt_info = {
            "program": program,
            "sparse_table_names": table_names,
            "sparse_table_ids": sparse_table_ids,
            "server_desc": server.get_desc(),
            "worker_desc": worker.get_desc(),
            "worker_skipped_ops": [],   # nothing is remote on TPU
            "optimizer": type(self._optimizer).__name__,
        }
        return optimize_ops, params_grads, opt_info
