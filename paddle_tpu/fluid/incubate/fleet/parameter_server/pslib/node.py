"""Downpour server/worker table-config carriers
(ref: incubate/fleet/parameter_server/pslib/node.py:18-523).

The reference fills brpc protobuf descs (ps_pb2) that configure live
DownpourBrpcPsServer processes. On TPU there are no server processes —
the sparse tables ARE the vocab-sharded embedding parameters in HBM —
so these classes validate the same strategy keys and carry the same
logical desc as plain dicts. PSLib's optimizer reads them to shard each
table's vocab dim over the mesh; everything else (accessor CVM decay,
brpc service classes) is recorded for introspection parity.
"""

__all__ = ["Server", "Worker", "DownpourServer", "DownpourWorker"]

_SPARSE_TABLE_CLASSES = ("DownpourSparseTable", "DownpourSparseSSDTable")
_SPARSE_ACCESSORS = (
    "DownpourSparseValueAccessor", "DownpourCtrAccessor",
    "DownpourFeatureValueAccessor",
)


class Server(object):
    """ref node.py:18 — base config carrier."""

    def __init__(self):
        self._desc = {}

    def get_desc(self):
        return self._desc


class Worker(object):
    """ref node.py:28."""

    def __init__(self):
        self._desc = {}

    def get_desc(self):
        return self._desc


class DownpourServer(Server):
    """Sparse/dense table config (ref node.py:38). Table descs feed the
    PSLib optimizer's sharding rules instead of brpc server processes."""

    def __init__(self):
        super().__init__()
        self._desc = {
            "service": {
                # parity fields; no brpc service runs on TPU
                "server_class": "DownpourBrpcPsServer",
                "client_class": "DownpourBrpcPsClient",
                "service_class": "DownpourPsService",
            },
            "tables": {},
        }

    def add_sparse_table(self, table_id, strategy):
        """ref node.py:55. ``strategy`` keys mirror the reference
        (sparse_table_class, sparse_accessor_class, sparse_embedx_dim,
        sparse_learning_rate, ...)."""
        strategy = dict(strategy or {})
        table_id = int(table_id)
        if table_id in self._desc["tables"]:
            if self._desc["tables"][table_id]["type"] != "sparse":
                raise ValueError(
                    "table %d already defined as dense" % table_id)
            return
        table_class = strategy.get(
            "sparse_table_class", "DownpourSparseTable")
        if table_class not in _SPARSE_TABLE_CLASSES:
            raise ValueError(
                "unsupported sparse_table_class %r (expected one of %s)"
                % (table_class, (_SPARSE_TABLE_CLASSES,)))
        accessor = strategy.get(
            "sparse_accessor_class", "DownpourCtrAccessor")
        if accessor not in _SPARSE_ACCESSORS:
            raise ValueError(
                "unsupported sparse_accessor_class %r (expected one of "
                "%s)" % (accessor, (_SPARSE_ACCESSORS,)))
        self._desc["tables"][table_id] = {
            "type": "sparse",
            "table_class": table_class,
            "accessor_class": accessor,
            "embedx_dim": int(strategy.get("sparse_embedx_dim", 8)),
            "fea_dim": int(strategy.get("sparse_fea_dim", 11)),
            "learning_rate": float(
                strategy.get("sparse_learning_rate", 0.05)),
            "shard_num": int(strategy.get("sparse_shard_num", 1000)),
            "strategy": strategy,
        }

    def add_dense_table(self, table_id, param_var, grad_var, strategy,
                        sparse_table_names=None):
        """ref node.py:245 — dense params stay replicated on TPU; the
        desc records which vars ride this table."""
        strategy = dict(strategy or {})
        table_id = int(table_id)
        if table_id in self._desc["tables"]:
            if self._desc["tables"][table_id]["type"] != "dense":
                raise ValueError(
                    "table %d already defined as sparse" % table_id)
            return
        self._desc["tables"][table_id] = {
            "type": "dense",
            "table_class": strategy.get(
                "dense_table_class", "DownpourDenseTable"),
            "accessor_class": strategy.get(
                "dense_accessor_class", "DownpourDenseValueAccessor"),
            "learning_rate": float(
                strategy.get("dense_learning_rate", 5e-6)),
            "params": [getattr(p, "name", p) for p in (param_var or [])],
            "grads": [getattr(g, "name", g) for g in (grad_var or [])],
            # ref threads the sparse-table names so CTR accessors can
            # exclude them from dense pulls; recorded for introspection
            "exclude_sparse_tables": list(sparse_table_names or []),
        }

    def add_data_norm_table(self, table_id, learning_rate, param_var,
                            grad_var, strategy=None,
                            sparse_table_names=None):
        """ref node.py:309 — data-norm stats are summable dense vars."""
        merged = dict(strategy or {})
        merged.setdefault("dense_table_class", "DownpourDenseTable")
        merged.setdefault("dense_accessor_class",
                          "DownpourDenseValueAccessor")
        merged["dense_learning_rate"] = learning_rate
        self.add_dense_table(table_id, param_var, grad_var, merged,
                             sparse_table_names)
        self._desc["tables"][int(table_id)]["data_norm"] = True


class DownpourWorker(Worker):
    """Worker-side view of the same tables (ref node.py:375)."""

    def __init__(self, window=1):
        super().__init__()
        self.window = window
        self._desc = {"tables": {}}

    def add_sparse_table(self, table_id, slot_key_vars=None,
                         slot_value_vars=None, strategy=None):
        self._desc["tables"][int(table_id)] = {
            "type": "sparse",
            "strategy": dict(strategy or {}),
            "slot_key": [getattr(v, "name", v)
                         for v in (slot_key_vars or [])],
            "slot_value": [getattr(v, "name", v)
                           for v in (slot_value_vars or [])],
        }

    def add_dense_table(self, table_id, learning_rate=None, param_vars=None,
                        grad_vars=None, dense_start_table_id=None,
                        sparse_table_names=None):
        self._desc["tables"][int(table_id)] = {
            "type": "dense",
            "learning_rate": learning_rate,
            "dense_start_table_id": dense_start_table_id,
            "exclude_sparse_tables": list(sparse_table_names or []),
            "params": [getattr(p, "name", p) for p in (param_vars or [])],
            "grads": [getattr(g, "name", g) for g in (grad_vars or [])],
        }
