"""PSLib fleet — the Downpour parameter-server training surface
(ref: incubate/fleet/parameter_server/pslib/__init__.py:28-652).

TPU-native mapping (SURVEY row 30's pserver story): the reference runs
brpc DownpourPsServer processes holding sparse tables that workers
prefetch from and push async grads to. On TPU the "servers" are the
chips themselves — every distributed lookup table becomes a VOCAB-
SHARDED embedding parameter over the mesh ('mp' axis when
strategy["embedding_parallel_degree"] > 1, else the dp axis), the
lookup is a sharded gather XLA routes over ICI, and the update rides
the same synchronous jitted step. Worker/server lifecycle calls become
no-ops (documented per method); the irreducibly-async pieces
(feature-frequency cache models, table shrink) raise with guidance.

A fluid-era pslib CTR script — init / distributed_optimizer(Adam) /
minimize / train — runs unchanged on the virtual mesh
(tests/test_pslib.py).
"""
import jax

from .....framework import default_main_program, default_startup_program
from ......parallel.mesh import build_mesh
from ......parallel.sharding import DistributedProgram, ShardingRule
from .node import DownpourServer, DownpourWorker  # noqa: F401
from .optimizer_factory import DistributedAdam  # noqa: F401

__all__ = ["PSLib", "DownpourOptimizer", "fleet"]

_ASYNC_ONLY = (
    "it manipulates live async pserver table state (feature-frequency "
    "accessors); on TPU the table is a sharded in-HBM parameter — use "
    "save/load_persistables for snapshots"
)


class PSLib:
    """ref pslib/__init__.py:28 (class PSLib(Fleet))."""

    def __init__(self):
        self._role_maker = None
        self._opt_info = None
        self._distributed_program = None
        self._strategy = {}

    # -- lifecycle (ref :42-194) -----------------------------------------
    def init(self, role_maker=None):
        from ......parallel.fleet import PaddleCloudRoleMaker

        self._role_maker = role_maker or PaddleCloudRoleMaker()
        return self

    def init_worker(self):
        """ref :52 — brpc client setup + barrier. The mesh IS the comm
        fabric; nothing to initialize."""

    def init_server(self, model_dir=None, **kwargs):
        """ref :128 — server-side model load. No server processes exist;
        load into the (sharded) scope instead."""
        if model_dir is not None:
            from ..... import io
            from .....executor import Executor

            io.load_persistables(Executor(), model_dir,
                                 default_main_program())

    def run_server(self):
        raise NotImplementedError(
            "PSLib.run_server: there are no parameter-server processes "
            "on TPU — every chip holds its vocab shard of each table "
            "inside the training step. Run the worker path only "
            "(is_server() is always False here)."
        )

    def stop_worker(self):
        """ref :179 — brpc teardown; no-op."""

    def _set_client_communication_config(self, request_timeout_ms=None,
                                         connect_timeout_ms=None,
                                         max_retry=None):
        """ref :46 — brpc knobs; accepted and ignored (no rpc layer)."""

    # -- role ------------------------------------------------------------
    def is_worker(self):
        return self._role_maker is None or self._role_maker.is_worker()

    def is_server(self):
        return False  # the chips are the servers; scripts run worker path

    def is_first_worker(self):
        return self.worker_index() == 0

    def worker_index(self):
        return self._role_maker.worker_index() if self._role_maker else 0

    def worker_num(self):
        return self._role_maker.worker_num() if self._role_maker else 1

    # -- optimize --------------------------------------------------------
    def distributed_optimizer(self, optimizer, strategy=None):
        self._strategy = dict(strategy or {})
        return DownpourOptimizer(optimizer, self._strategy, self)

    @property
    def main_program(self):
        return self._distributed_program or default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def _build(self, opt_info):
        """Mesh + vocab-sharding rules from the table config."""
        from jax.sharding import PartitionSpec as P

        self._opt_info = opt_info
        ndev = len(jax.devices())
        mp = int(self._strategy.get("embedding_parallel_degree", 0))
        if mp > 1:
            if ndev % mp:
                raise ValueError(
                    "embedding_parallel_degree=%d does not divide the "
                    "%d-device mesh" % (mp, ndev))
            axes = {"dp": ndev // mp, "mp": mp}
            table_axis = "mp"
        else:
            axes = {"dp": ndev}
            table_axis = "dp"   # servers == workers == chips
        mesh = build_mesh(axes)
        import re

        rules = [
            ShardingRule("^" + re.escape(name) + "$", P(table_axis, None))
            for name in opt_info["sparse_table_names"]
        ]
        self._distributed_program = DistributedProgram(
            opt_info["program"], mesh, param_rules=rules)
        return self._distributed_program

    # -- persistence (ref :215-288) --------------------------------------
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from ..... import io

        return io.save_inference_model(
            dirname, feeded_var_names, target_vars, executor,
            main_program or default_main_program(),
            export_for_deployment=export_for_deployment)

    def save_persistables(self, executor, dirname, main_program=None,
                          **kwargs):
        from ..... import io

        return io.save_persistables(
            executor, dirname, main_program or default_main_program())

    def print_table_stat(self, table_id):
        """ref :241 — prints feasign count; here: rows/params of the
        table parameter."""
        import numpy as np

        from .....executor import global_scope

        names = self._opt_info["sparse_table_names"] if self._opt_info \
            else []
        ids = self._opt_info["sparse_table_ids"] if self._opt_info else {}
        for name in names:
            if ids.get(name) == int(table_id):
                val = global_scope().find_value(name)
                if val is not None:
                    arr = np.asarray(val)
                    print("table %d (%s): shape %s, l2 %.6f"
                          % (table_id, name, arr.shape,
                             float(np.sqrt((arr ** 2).sum()))))
                return
        print("table %d: not found" % table_id)

    def clear_model(self):
        """ref :392 — zero every table parameter in the scope."""
        import numpy as np

        from .....executor import global_scope

        scope = global_scope()
        prog = (self._opt_info or {}).get("program") \
            or default_main_program()
        for p in prog.global_block().all_parameters():
            val = scope.find_value(p.name)
            if val is not None:
                scope.update(p.name, np.zeros_like(np.asarray(val)))

    # -- irreducibly-async surface ---------------------------------------
    def save_cache_model(self, executor, dirname, main_program=None,
                         **kwargs):
        raise NotImplementedError(
            "PSLib.save_cache_model filters feasigns by a live access-"
            "frequency accessor; " + _ASYNC_ONLY)

    def shrink_sparse_table(self):
        raise NotImplementedError(
            "PSLib.shrink_sparse_table evicts cold feasigns from async "
            "tables; " + _ASYNC_ONLY)

    def shrink_dense_table(self, decay, emb_dim=11, scope=None,
                           table_id=None):
        raise NotImplementedError(
            "PSLib.shrink_dense_table decays server-held dense values; "
            + _ASYNC_ONLY)

    def load_one_table(self, table_id, model_path, **kwargs):
        raise NotImplementedError(
            "PSLib.load_one_table streams a single brpc table; use "
            "load_persistables (the whole sharded scope) instead")


class DownpourOptimizer:
    """ref pslib/__init__.py:550 (DownpourOptimizer(DistributedOptimizer)):
    wraps a regular optimizer with DistributedAdam's table build."""

    def __init__(self, optimizer, strategy=None, fleet_obj=None):
        self._optimizer = optimizer
        self._strategy = dict(strategy or {})
        self._fleet = fleet_obj if fleet_obj is not None else fleet
        self._impl = DistributedAdam(optimizer)

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set,
            callbacks)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, losses, startup_programs=None, parameter_list=None,
                 no_grad_set=None):
        optimize_ops, params_grads, opt_info = self._impl._minimize(
            losses,
            startup_programs[0] if isinstance(
                startup_programs, (list, tuple)) else startup_programs,
            parameter_list, no_grad_set, strategy=self._strategy)
        self._fleet._build(opt_info)
        return optimize_ops, params_grads

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


fleet = PSLib()
