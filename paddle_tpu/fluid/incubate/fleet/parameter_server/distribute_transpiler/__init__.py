"""Compat surface for fleet.parameter_server.distribute_transpiler
(ref: incubate/fleet/parameter_server/distribute_transpiler/__init__.py:38).
"""
_GUIDANCE = (
    "fleet.parameter_server (pserver mode) does not exist on TPU: "
    "parameters live sharded in HBM and gradients ride ICI "
    "collectives. Use fluid.incubate.fleet.collective.fleet with "
    "DistributedStrategy (dp/tp/sp/pp + sharding_degree for "
    "ZeRO-1) instead."
)


class _PserverUnavailable(NotImplementedError, AttributeError):
    """Raised on any pserver-fleet attribute: NotImplementedError for
    parity with the other intentional raises, AttributeError so
    hasattr()/getattr(..., default) feature probes degrade gracefully
    instead of crashing."""


class _PserverFleet:
    def __getattr__(self, name):
        raise _PserverUnavailable(_GUIDANCE)


fleet = _PserverFleet()
