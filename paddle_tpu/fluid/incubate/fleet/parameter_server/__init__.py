"""fleet.parameter_server (ref: incubate/fleet/parameter_server).

The reference's pserver training mode has no TPU counterpart — sparse
updates flow over ICI collectives instead (see fluid/transpiler.py's
documented re-mapping). The import path is kept so scripts can probe it;
using the pserver fleet raises with that guidance.
"""
from . import distribute_transpiler  # noqa: F401
