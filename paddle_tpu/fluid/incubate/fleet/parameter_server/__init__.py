"""fleet.parameter_server (ref: incubate/fleet/parameter_server).

Two surfaces:
- ``pslib`` — the Downpour/PSLib fleet WORKS here: sparse tables map to
  vocab-sharded embeddings over the mesh (see pslib/__init__.py).
- ``distribute_transpiler`` — the transpiler-based pserver fleet keeps
  its import path but raises with guidance (sparse updates flow over
  ICI collectives instead; see fluid/transpiler.py's re-mapping).
"""
from . import distribute_transpiler  # noqa: F401
from . import pslib  # noqa: F401
