"""Collective-mode fleet (ref: incubate/fleet/collective/__init__.py):
the canonical `from paddle.fluid.incubate.fleet.collective import fleet`
entry point, backed by the GSPMD mesh implementation."""
from paddle_tpu.parallel.fleet import (  # noqa: F401
    DistributedOptimizer,
    DistributedStrategy,
    distributed_optimizer,
    fleet,
    init,
)
