"""FleetUtil (ref: incubate/fleet/utils/fleet_util.py:36) — rank-aware
logging + small numeric helpers. Rank comes from jax.process_index()
(multi-host) instead of the pserver role maker."""
import logging

import numpy as np

__all__ = ["FleetUtil"]

_logger = logging.getLogger("FleetUtil")
_logger.setLevel(logging.INFO)
if not _logger.handlers:
    # the ref builds its logger with an attached StreamHandler
    # (fleet_util.py get_logger); without one, INFO records are dropped
    # by logging's WARNING-level lastResort handler
    _handler = logging.StreamHandler()
    _handler.setFormatter(logging.Formatter(
        "%(levelname)s %(asctime)s %(message)s"))
    _logger.addHandler(_handler)
    _logger.propagate = False


class FleetUtil:
    def _rank(self):
        try:
            import jax

            return jax.process_index()
        except Exception:  # noqa: BLE001 — uninitialised distributed
            return 0

    def rank0_print(self, s):
        if self._rank() == 0:
            print(s, flush=True)

    def rank0_info(self, s):
        if self._rank() == 0:
            _logger.info(s)

    def rank0_error(self, s):
        if self._rank() == 0:
            _logger.error(s)

    def set_zero(self, var_name, scope=None, place=None, param_type="int64"):
        """Reset a scope variable to zeros of `param_type`, keeping its
        shape (ref fleet_util.py:107 re-types the stat var the same way;
        `place` is accepted for signature parity — arrays are placed by
        the executor on next use)."""
        from ....executor import global_scope

        scope = scope if scope is not None else global_scope()
        cur = scope.find_value(var_name)
        if cur is None:
            raise KeyError("set_zero: no var named %r in scope" % var_name)
        shape = np.shape(cur)  # no host copy for device arrays
        scope.update(var_name, np.zeros(shape, dtype=param_type))
