"""incubate.fleet.utils.hdfs (ref: HDFSClient) — same loud-raising
client as contrib.utils.hdfs_utils (object stores/NFS replace HDFS on
TPU hosts; every method explains the migration)."""
from ....contrib.utils.hdfs_utils import HDFSClient, multi_download, \
    multi_upload  # noqa: F401

__all__ = ["HDFSClient", "multi_download", "multi_upload"]
