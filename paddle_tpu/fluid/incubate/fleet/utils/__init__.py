"""fleet.utils (ref: incubate/fleet/utils)."""
from . import fleet_util  # noqa: F401
from .fleet_util import FleetUtil  # noqa: F401
