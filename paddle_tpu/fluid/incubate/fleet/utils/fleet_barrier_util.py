"""incubate.fleet.utils.fleet_barrier_util (ref: check_all_trainers_
ready — an HDFS-file barrier across trainers)."""
import os

__all__ = ["check_all_trainers_ready"]


def check_all_trainers_ready(check_point, emit=None):
    """Single-process worlds are trivially ready; multi-process worlds
    synchronize through jax.distributed's collectives at init, so the
    HDFS touch-file dance is unnecessary — multi-trainer calls raise
    with that pointer (ref fleet_barrier_util.py)."""
    n = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    if n <= 1:
        return
    raise NotImplementedError(
        "check_all_trainers_ready(%r) barriers through HDFS touch "
        "files; multi-host runs here synchronize via jax.distributed "
        "(paddle_tpu.distributed.launch blocks every process at init), "
        "so no file barrier is needed" % (check_point,)
    )
