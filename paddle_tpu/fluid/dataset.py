"""Dataset trainer path (ref: python/paddle/fluid/dataset.py).

The reference feeds MultiSlot text files through C++ DataFeed channels
into multi-threaded Hogwild trainers. The TPU-native redesign keeps the
whole user API — DatasetFactory / QueueDataset / InMemoryDataset, the
MultiSlot file format, pipe_command preprocessing, local/global shuffle —
but maps execution differently: parser THREADS do host-side work
(pipe_command subprocess + tokenizing, both GIL-releasing), assembled
batches stage through the native C++ slot ring (see reader.py), and a
single jitted device step consumes them. Hogwild's lock-free concurrent
updates have no TPU analogue (one XLA stream updates donated params
in-place), so `thread_num` controls parsing parallelism only — same
contract (thread count tunes throughput), different machinery.

MultiSlot line format, one sample per line, slots in ``set_use_var``
order: ``<n> v1 .. vn`` per slot. Sparse slots (lod_level>0 vars) are
ragged id lists; dense slots (lod_level==0) must carry exactly
prod(shape[1:]) values.
"""
import os
import queue as _queue
import subprocess
import threading

import numpy as np

from . import core
from .framework import Variable

__all__ = ["DatasetFactory", "InMemoryDataset", "QueueDataset"]


class DatasetFactory:
    """ref dataset.py:22 — create a dataset by class name."""

    def __init__(self):
        pass

    def create_dataset(self, datafeed_class="QueueDataset"):
        try:
            cls = globals()[datafeed_class]
        except KeyError:
            raise ValueError(
                "DatasetFactory: unknown dataset class %r (have "
                "QueueDataset, InMemoryDataset, FileInstantDataset)"
                % (datafeed_class,)
            )
        return cls()


class DatasetBase:
    """ref dataset.py:64 — shared config + MultiSlot parsing."""

    def __init__(self):
        self.proto_desc_name = "MultiSlotDataFeed"
        self.batch_size = 32
        self.thread_num = 1
        self.filelist = []
        self.use_vars = []
        self.pipe_command = "cat"
        self._prepared = False

    # -- configuration (ref API) ---------------------------------------
    def set_pipe_command(self, pipe_command):
        """Shell command each file is piped through before parsing (the
        reference contract: e.g. a data_generator script printing
        MultiSlot lines). 'cat' short-circuits to direct reads."""
        self.pipe_command = pipe_command

    def set_batch_size(self, batch_size):
        self.batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self.thread_num = max(int(thread_num), 1)

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def set_use_var(self, var_list):
        for v in var_list:
            if not isinstance(v, Variable):
                raise TypeError("set_use_var expects Variables")
        self.use_vars = list(var_list)

    def set_hdfs_config(self, fs_name, fs_ugi):
        raise NotImplementedError(
            "set_hdfs_config: no HDFS client in this environment; stage "
            "files to local disk (or a FUSE mount) and use set_filelist "
            "— fs_name=%r ugi=%r" % (fs_name, fs_ugi)
        )

    def set_fea_eval(self, record_candidate_size, fea_eval=True):
        raise NotImplementedError(
            "set_fea_eval/slots_shuffle (feature-importance shuffling) "
            "is not implemented; shuffle slots offline in pipe_command"
        )

    def slots_shuffle(self, slots):
        raise NotImplementedError(
            "slots_shuffle is not implemented; shuffle the slot in your "
            "pipe_command instead"
        )

    def desc(self):
        """Text-proto description (ref returns the protobuf dump)."""
        from .data_feed_desc import DataFeedDesc

        lines = ['name: "%s"' % self.proto_desc_name,
                 "batch_size: %d" % self.batch_size, "multi_slot_desc {"]
        for v in self.use_vars:
            lines += [
                "  slots {",
                '    name: "%s"' % v.name,
                '    type: "%s"' % (
                    "uint64" if "int" in str(v.dtype) else "float"),
                "    is_dense: %s" % str(v.lod_level == 0).lower(),
                "    is_used: true",
                "  }",
            ]
        lines.append("}")
        text = "\n".join(lines) + "\n"
        # round-trips through DataFeedDesc by construction
        DataFeedDesc(text)
        return text

    # -- lifecycle ------------------------------------------------------
    def _prepare_to_run(self):
        if not self.use_vars:
            raise ValueError(
                "dataset: call set_use_var([...]) before running"
            )
        if not self.filelist:
            raise ValueError(
                "dataset: call set_filelist([...]) before running"
            )
        self._prepared = True

    def _finish_to_run(self):
        self._prepared = False

    def _release_loader(self):
        """Free the cached trainer loader (and its native pipe's
        mlock'd arena — capacity x 64MB of locked host memory). The
        cache (set by Executor.train_from_dataset) otherwise lives as
        long as the dataset so epochs reuse the pipe; call this (or
        InMemoryDataset.release_memory, which calls it) when done
        training from this dataset."""
        cached = getattr(self, "_loader_cache", None)
        if cached is None:
            return
        self._loader_cache = None
        pipe = getattr(cached[1], "_pipe", None)
        if pipe is not None:
            cached[1]._pipe = None
            try:
                pipe.close()
            except Exception:  # noqa: BLE001 — release is best-effort
                pass

    # ref internal hooks, kept for API parity with fleet integrations
    def _dynamic_adjust_before_train(self, thread_num):
        pass

    def _dynamic_adjust_after_train(self):
        pass

    # -- parsing --------------------------------------------------------
    def _slot_spec(self):
        """Per-use_var (is_int, dense_dim-or-None) parsed from the var."""
        spec = []
        for v in self.use_vars:
            is_int = "int" in str(v.dtype)
            if v.lod_level == 0:
                dim = 1
                for s in (v.shape or [1])[1:]:
                    dim *= int(s) if s not in (None, -1) else 1
                spec.append((is_int, max(dim, 1)))
            else:
                spec.append((is_int, None))
        return spec

    def _iter_lines(self, fname):
        if self.pipe_command in (None, "", "cat"):
            with open(fname) as f:
                yield from f
            return
        with open(fname, "rb") as src:
            proc = subprocess.Popen(
                ["/bin/sh", "-c", self.pipe_command],
                stdin=src, stdout=subprocess.PIPE, text=True,
            )
            try:
                yield from proc.stdout
            finally:
                proc.stdout.close()
                rc = proc.wait()
                if rc != 0:
                    raise RuntimeError(
                        "pipe_command %r failed with exit code %d on %s"
                        % (self.pipe_command, rc, fname)
                    )

    def _parse_line(self, line, spec):
        toks = line.split()
        if not toks:
            return None
        sample = []
        pos = 0
        for si, (is_int, dense_dim) in enumerate(spec):
            if pos >= len(toks):
                raise ValueError(
                    "MultiSlot parse error: line ended before slot %d "
                    "(%r...)" % (si, line[:80])
                )
            n = int(toks[pos])
            pos += 1
            vals = toks[pos:pos + n]
            if len(vals) != n:
                raise ValueError(
                    "MultiSlot parse error: slot %d declares %d values, "
                    "found %d (%r...)" % (si, n, len(vals), line[:80])
                )
            pos += n
            conv = int if is_int else float
            vals = [conv(x) for x in vals]
            if dense_dim is not None and n != dense_dim:
                raise ValueError(
                    "dense slot %d (%s) expects %d values per sample, "
                    "got %d" % (si, self.use_vars[si].name, dense_dim, n)
                )
            sample.append(vals)
        return tuple(sample)

    def _parse_file(self, fname, spec):
        for line in self._iter_lines(fname):
            s = self._parse_line(line, spec)
            if s is not None:
                yield s


class QueueDataset(DatasetBase):
    """Streaming dataset (ref dataset.py:646): files are parsed on the
    fly by `thread_num` parser threads, each assembling its own batches
    (per-thread tails stay partial, like the reference's per-channel
    DataFeed)."""

    def __init__(self):
        super().__init__()
        self.proto_desc_name = "MultiSlotDataFeed"

    def local_shuffle(self):
        raise NotImplementedError(
            "QueueDataset streams files and cannot shuffle; use "
            "InMemoryDataset.local_shuffle (ref raises the same way)"
        )

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "QueueDataset cannot global_shuffle; use InMemoryDataset "
            "(ref raises the same way)"
        )

    def _batch_iterator(self, thread=0, rows=None):
        """``rows`` overrides the assembled batch size (the executor's
        scan path requests k*batch_size super-batches it splits/scans
        on device)."""
        spec = self._slot_spec()
        bs_rows = int(rows) if rows else self.batch_size
        nthread = min(
            thread or self.thread_num, max(len(self.filelist), 1)
        )
        out = _queue.Queue(maxsize=max(2 * nthread, 4))
        FIN = object()
        errors = []

        def worker(files):
            batch = []
            try:
                for fn in files:
                    for s in self._parse_file(fn, spec):
                        batch.append(s)
                        if len(batch) == bs_rows:
                            out.put(batch)
                            batch = []
                if batch:
                    out.put(batch)
            except BaseException as e:  # surfaced at the consumer
                errors.append(e)
            finally:
                out.put(FIN)

        shards = [self.filelist[i::nthread] for i in range(nthread)]
        for sh in shards:
            threading.Thread(target=worker, args=(sh,), daemon=True).start()
        live = nthread
        while live:
            item = out.get()
            if item is FIN:
                live -= 1
                continue
            yield item
        if errors:
            raise errors[0]


class InMemoryDataset(DatasetBase):
    """ref dataset.py:276 — parse everything into host memory first,
    shuffle there, then batch."""

    def __init__(self):
        super().__init__()
        self.proto_desc_name = "MultiSlotInMemoryDataFeed"
        self.queue_num = None
        self.parse_ins_id = False
        self.parse_content = False
        self.merge_size = -1
        self.fleet_send_batch_size = 1024
        self.fleet_send_sleep_seconds = 0
        self._memory = None
        self._preload_threads = None
        self._shuffle_seed = 0
        # columnar fast path cache: None = not built yet, False = not
        # columnarizable (ragged / LoD slots), list = per-slot arrays
        self._columns = None

    # -- ref knobs ------------------------------------------------------
    def set_queue_num(self, queue_num):
        """Kept for parity; parsing fan-in is thread_num here (no C++
        channel array to size)."""
        self.queue_num = int(queue_num)

    def set_parse_ins_id(self, parse_ins_id):
        """When true, each line starts with an instance id token before
        the slots (ref MultiSlotInMemoryDataFeed.parse_ins_id)."""
        self.parse_ins_id = bool(parse_ins_id)

    def set_parse_content(self, parse_content):
        self.parse_content = bool(parse_content)

    def set_merge_by_lineid(self, merge_size=2):
        """Merge samples sharing an instance id (requires
        set_parse_ins_id(True)): slot value lists are concatenated."""
        self.merge_size = int(merge_size)
        self.parse_ins_id = True

    def set_fleet_send_batch_size(self, fleet_send_batch_size=1024):
        self.fleet_send_batch_size = int(fleet_send_batch_size)

    def set_fleet_send_sleep_seconds(self, fleet_send_sleep_seconds=0):
        self.fleet_send_sleep_seconds = int(fleet_send_sleep_seconds)

    # -- loading --------------------------------------------------------
    def _parse_line(self, line, spec):
        if not self.parse_ins_id:
            return super()._parse_line(line, spec)
        toks = line.split(None, 1)
        if not toks:
            return None
        ins_id, rest = toks[0], (toks[1] if len(toks) > 1 else "")
        s = super()._parse_line(rest, spec)
        return None if s is None else (ins_id,) + s

    def load_into_memory(self):
        spec = self._slot_spec()
        if not self.filelist:
            raise ValueError("set_filelist before load_into_memory")
        mem = []
        lock = threading.Lock()
        nthread = min(self.thread_num, len(self.filelist))
        errors = []

        def worker(files):
            local = []
            try:
                for fn in files:
                    local.extend(self._parse_file(fn, spec))
            except BaseException as e:
                errors.append(e)
            with lock:
                mem.extend(local)

        ts = [
            threading.Thread(
                target=worker, args=(self.filelist[i::nthread],),
                daemon=True)
            for i in range(nthread)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        if errors:
            raise errors[0]
        if self.merge_size > 0:
            mem = self._merge_by_lineid(mem)
        self._memory = mem
        self._columns = None

    def _merge_by_lineid(self, mem):
        import collections

        grouped = collections.OrderedDict()
        for s in mem:
            grouped.setdefault(s[0], []).append(s[1:])
        merged = []
        for ins_id, group in grouped.items():
            acc = [list(slot) for slot in group[0]]
            for s in group[1:self.merge_size]:
                for slot_acc, slot_vals in zip(acc, s):
                    slot_acc.extend(slot_vals)
            merged.append((ins_id,) + tuple(acc))
        return merged

    def preload_into_memory(self, thread_num=None):
        if thread_num is not None:
            self.set_thread(thread_num)
        # parse/pipe_command failures surface in wait_preload_done, not a
        # misleading "call load_into_memory first" later
        self._preload_error = []

        def _load():
            try:
                self.load_into_memory()
            except BaseException as e:
                self._preload_error.append(e)

        t = threading.Thread(target=_load, daemon=True)
        t.start()
        self._preload_threads = [t]

    def wait_preload_done(self):
        for t in self._preload_threads or ():
            t.join()
        self._preload_threads = None
        errs = getattr(self, "_preload_error", None)
        if errs:
            self._preload_error = []
            raise errs[0]

    # -- shuffle --------------------------------------------------------
    def _require_memory(self):
        if self._memory is None:
            raise RuntimeError(
                "call load_into_memory() (or preload_into_memory + "
                "wait_preload_done) first"
            )

    def local_shuffle(self):
        self._require_memory()
        rng = np.random.default_rng(self._shuffle_seed)
        self._shuffle_seed += 1
        perm = rng.permutation(len(self._memory))
        self._memory = [self._memory[i] for i in perm]
        if isinstance(self._columns, list):
            self._columns = [c[perm] for c in self._columns]

    def global_shuffle(self, fleet=None, thread_num=12):
        """Single-host: identical to local_shuffle. Multi-host: every
        worker shuffles its own shard — the cross-worker sample exchange
        the reference does over pserver channels is unnecessary when each
        worker already reads a disjoint filelist shard (the launch-time
        sharding this framework's distributed.launch performs)."""
        self._require_memory()
        self.local_shuffle()

    def release_memory(self):
        self._memory = None
        self._columns = None
        self._release_loader()

    def get_memory_data_size(self, fleet=None):
        """Local sample count; with a fleet, the reference all-reduces the
        count — here every worker reads a disjoint filelist shard, so the
        global size is worker_count * local (callers needing the exact
        global sum can psum it via layers.collective)."""
        self._require_memory()
        n = len(self._memory)
        if fleet is not None:
            n = n * max(int(getattr(fleet, "worker_num", lambda: 1)()), 1)
        return n

    def get_shuffle_data_size(self, fleet=None):
        return self.get_memory_data_size(fleet)

    # -- batching -------------------------------------------------------
    def _try_columnarize(self):
        """Stack the in-memory samples into one dense array per slot
        (the DataFeeder.ColumnarBatch fast path). Possible iff every
        use_var is lod_level 0 AND every sample's value list for a slot
        has the same length — true by contract for dense slots and in
        practice for fixed-width id lists (e.g. Criteo's 26 categorical
        fields). Ragged or LoD slots keep the per-sample path (which
        builds LoDTensors). Cost is paid once; every epoch after
        batches as O(1) numpy slices."""
        if self._columns is not None:
            return self._columns
        if any(v.lod_level for v in self.use_vars):
            self._columns = False
            return False
        strip = 1 if self.parse_ins_id else 0
        spec = self._slot_spec()
        try:
            self._columns = [
                np.array([s[strip + si] for s in self._memory],
                         dtype=np.int64 if is_int else np.float32)
                for si, (is_int, _dim) in enumerate(spec)
            ]
        except (ValueError, TypeError):  # ragged slot somewhere
            self._columns = False
        return self._columns

    def _batch_iterator(self, thread=0, rows=None):
        """``rows`` overrides the slice size (the executor's scan path
        requests k*batch_size super-batches)."""
        self._require_memory()
        bs = int(rows) if rows else self.batch_size
        cols = self._try_columnarize()
        if cols is not False:
            from .data_feeder import ColumnarBatch

            for i in range(0, len(self._memory), bs):
                yield ColumnarBatch([c[i:i + bs] for c in cols])
            return
        strip = 1 if self.parse_ins_id else 0
        mem = self._memory
        for i in range(0, len(mem), bs):
            chunk = mem[i:i + bs]
            yield [s[strip:] for s in chunk]


class FileInstantDataset(DatasetBase):
    """ref dataset.py:729 — streams like QueueDataset (the 'instant'
    C++ feed variant has no behavioral difference at this layer)."""

    def __init__(self):
        super().__init__()
        self.proto_desc_name = "MultiSlotFileInstantDataFeed"

    _batch_iterator = QueueDataset._batch_iterator

    def local_shuffle(self):
        raise NotImplementedError(
            "FileInstantDataset cannot local_shuffle (ref raises too)"
        )

    def global_shuffle(self, fleet=None):
        raise NotImplementedError(
            "FileInstantDataset cannot global_shuffle (ref raises too)"
        )


class BoxPSDataset(InMemoryDataset):
    """ref dataset.py:767 — BoxPS is a GPU parameter-server cache with
    no TPU analogue; embedding tables shard over the mesh instead."""

    def __init__(self):
        raise NotImplementedError(
            "BoxPSDataset targets the BoxPS GPU cache; on TPU use "
            "InMemoryDataset and shard embeddings via fleet/pjit"
        )
