"""Minimal graphviz dot-source builder
(ref: python/paddle/fluid/graphviz.py).

Pure text generation: ``Graph`` accumulates nodes/edges/rank groups and
emits dot source; ``show`` additionally runs the ``dot`` binary when it
is installed (and silently keeps just the .dot file otherwise — CI boxes
rarely have graphviz)."""
import os
import shutil
import subprocess

__all__ = ["Graph", "Node", "Edge", "Subgraph", "GraphPreviewGenerator"]


def crepr(v):
    return '"%s"' % v if isinstance(v, str) else str(v)


class Rank:
    def __init__(self, kind, name, priority):
        if kind not in ("source", "sink", "same", "min", "max"):
            raise ValueError("unsupported rank kind %r" % kind)
        self.kind = kind
        self.name = name
        self.priority = priority
        self.nodes = []

    def __str__(self):
        if not self.nodes:
            return ""
        return "{rank=%s; %s}" % (
            self.kind, ",".join(n.name for n in self.nodes))


class Node:
    counter = 1

    def __init__(self, label, prefix, description="", **attrs):
        self.label = label
        self.name = "%s_%d" % (prefix, Node.counter)
        Node.counter += 1
        self.description = description
        self.attrs = attrs

    def __str__(self):
        attrs = dict(self.attrs)
        attrs.setdefault("label", self.label)
        body = ",".join(
            "%s=%s" % (k, crepr(v)) for k, v in sorted(attrs.items()))
        return "%s [%s];" % (self.name, body)


class Edge:
    def __init__(self, source, target, **attrs):
        self.source = source
        self.target = target
        self.attrs = attrs

    def __str__(self):
        body = ",".join(
            "%s=%s" % (k, crepr(v)) for k, v in sorted(self.attrs.items()))
        return "%s -> %s [%s];" % (self.source.name, self.target.name, body)


class Subgraph:
    """A dot ``subgraph cluster_*``: nodes added to it render inside a
    labelled box (control-flow sub-blocks in the program dumps). Edges
    stay at the top level — dot resolves node names globally."""

    counter = 1

    def __init__(self, label, **attrs):
        self.name = "cluster_%d" % Subgraph.counter
        Subgraph.counter += 1
        self.label = label
        self.attrs = attrs
        self.nodes = []

    def __str__(self):
        lines = ["subgraph %s {" % self.name,
                 "label=%s;" % crepr(self.label)]
        lines += ["%s=%s;" % (k, crepr(v))
                  for k, v in sorted(self.attrs.items())]
        lines += [str(n) for n in self.nodes]
        lines.append("}")
        return "\n".join(lines)


class Graph:
    def __init__(self, title, **attrs):
        self.title = title
        self.attrs = attrs
        self.nodes = []
        self.edges = []
        self.subgraphs = []
        self.rank_groups = {}

    def add_node(self, label, prefix, description="", subgraph=None,
                 **attrs):
        node = Node(label, prefix, description, **attrs)
        if subgraph is not None:
            subgraph.nodes.append(node)
        else:
            self.nodes.append(node)
        return node

    def add_subgraph(self, label, **attrs):
        sub = Subgraph(label, **attrs)
        self.subgraphs.append(sub)
        return sub

    def add_edge(self, source, target, **attrs):
        edge = Edge(source, target, **attrs)
        self.edges.append(edge)
        return edge

    def rank_group(self, kind, priority):
        name = "rankgroup-%d" % len(self.rank_groups)
        self.rank_groups[name] = Rank(kind, name, priority)
        return name

    def node(self, label, prefix, description="", **attrs):
        node = self.add_node(label, prefix, description, **attrs)
        group = attrs.get("rank_group")
        if group in self.rank_groups:
            self.rank_groups[group].nodes.append(node)
        return node

    def code(self):
        head = 'digraph G {\nlabel=%s;\n' % crepr(self.title)
        head += "".join(
            "%s=%s;\n" % (k, crepr(v)) for k, v in sorted(self.attrs.items())
        )
        parts = [str(s) for s in self.subgraphs]
        parts += [str(n) for n in self.nodes]
        parts += [str(e) for e in self.edges]
        parts += [
            str(r) for r in sorted(
                self.rank_groups.values(), key=lambda r: r.priority)
            if str(r)
        ]
        return head + "\n".join(parts) + "\n}\n"

    def compile(self, dot_path):
        """Write dot source; render a PDF next to it if `dot` exists."""
        with open(dot_path, "w") as f:
            f.write(self.code())
        if shutil.which("dot"):
            out = os.path.splitext(dot_path)[0] + ".pdf"
            subprocess.run(
                ["dot", "-Tpdf", dot_path, "-o", out], check=False)
            return out
        return dot_path

    # ref naming
    def show(self, dot_path):
        return self.compile(dot_path)

    def __str__(self):
        return self.code()


class GraphPreviewGenerator:
    """Typed helpers over Graph (ref graphviz.py:184): params as
    octagons, ops as rectangles, vars as ellipses."""

    def __init__(self, title):
        self.graph = Graph(title, layout="dot")

    def add_subgraph(self, label, **attrs):
        attrs.setdefault("style", "rounded")
        attrs.setdefault("color", "gray50")
        return self.graph.add_subgraph(label, **attrs)

    def add_param(self, name, data_type, highlight=False, subgraph=None):
        return self.graph.add_node(
            "%s\\n%s" % (name, data_type), prefix="param", shape="octagon",
            style="filled", subgraph=subgraph,
            fillcolor="green" if highlight else "lightgrey")

    def add_op(self, opType, subgraph=None, **kwargs):
        kwargs.setdefault("style", "rounded")
        return self.graph.add_node(
            opType, prefix="op", shape="box", subgraph=subgraph, **kwargs)

    def add_arg(self, name, highlight=False, subgraph=None, dead=False):
        if dead:
            # unreferenced relative to the fetch targets (walker
            # live_report): keep it visible but visually inert
            return self.graph.add_node(
                name, prefix="arg", shape="ellipse", style="dashed",
                color="gray60", fontcolor="gray60", subgraph=subgraph)
        return self.graph.add_node(
            name, prefix="arg", shape="ellipse", subgraph=subgraph,
            style="filled" if highlight else "solid",
            fillcolor="yellow" if highlight else "white")

    def add_edge(self, source, target, **kwargs):
        return self.graph.add_edge(source, target, **kwargs)
