"""Legacy low-level op wrappers (ref: python/paddle/fluid/op.py:24-292).

The reference builds raw C++ OperatorBase instances from op protos,
outside any Program — the pre-layers API kept alive for ancient unit
tests. This framework has no standalone C++ operators: every op is a
symbolic record in a Program lowered into the single jitted step. The
introspection half (op registry listing) is real; direct operator
construction raises with the modern path.
"""
from ..ops.registry import KNOWN_UNSUPPORTED, LOWERINGS

__all__ = ["get_all_op_protos", "Operator", "OperatorFactory",
           "OpDescCreationMethod"]


class _OpProto(object):
    """Minimal proto-like descriptor over the lowering registry."""

    def __init__(self, type):
        self.type = type
        self.comment = "TPU lowering registered in paddle_tpu.ops"


def get_all_op_protos():
    """Descriptors for every registered op type (ref op.py:24 reads the
    C++ OpInfoMap; here the jax lowering registry is the op library)."""
    return [_OpProto(t) for t in sorted(LOWERINGS)]


_GUIDANCE = (
    "paddle_tpu has no standalone operator objects: ops are symbolic "
    "Program records lowered into one jitted step. Build programs with "
    "fluid.layers.* (or block.append_op for custom graphs) and run them "
    "with fluid.Executor."
)


class OpDescCreationMethod(object):
    """ref op.py:41 — protobuf OpDesc assembly; unmappable (no protobuf
    op descs exist), raises with the modern path."""

    def __init__(self, op_proto):
        self._proto = op_proto

    def __call__(self, *args, **kwargs):
        raise NotImplementedError(
            "OpDescCreationMethod(%s): " % getattr(
                self._proto, "type", "?") + _GUIDANCE)


class OperatorFactory(object):
    """ref op.py:178 — C++ OperatorBase construction."""

    def types(self):
        return sorted(set(LOWERINGS) | set(KNOWN_UNSUPPORTED))

    def get_op_info(self, t):
        if t not in LOWERINGS and t not in KNOWN_UNSUPPORTED:
            raise ValueError("Operator %r has not been registered" % t)
        return _OpProto(t)

    def __call__(self, *args, **kwargs):
        raise NotImplementedError("OperatorFactory: " + _GUIDANCE)


Operator = OperatorFactory()
