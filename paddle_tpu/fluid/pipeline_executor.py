"""Pipeline-parallel execution of a fluid Program (PipelineOptimizer path).

TPU-native rework of the reference's pipeline trainer
(ref: python/paddle/fluid/optimizer.py:3193 PipelineOptimizer, which splits
the program at ``cut_list`` vars and runs section workers over blocking
queues on different devices). Here:

  * the forward region is split at the cut vars' producing ops into S
    heterogeneous stage functions;
  * all S stages run under one ``shard_map`` over the 'pp' mesh axis —
    each device executes its own stage via ``lax.switch`` on its axis
    index, activations circulate with ``lax.ppermute`` inside a
    ``lax.scan`` over (microbatches + stages - 1) ticks (GPipe schedule);
  * the BACKWARD pipeline is not hand-written: ``jax.vjp`` through the
    scan + ppermute forward yields the reverse schedule mechanically
    (ppermute transposes to the inverse permutation, scan to a reverse
    scan) — the payoff of building the pipeline as a pure jax function;
  * grads are bound to the program's ``p@GRAD`` vars and the post-backward
    ops (optimizer updates, LR schedules) run replicated as usual.

Semantics: with M microbatches of equal size, mean-reduced losses match
sequential full-batch execution exactly (mean of microbatch means). v1
limitations (documented, loud): stage bodies must be stateless in the
persistable sense (no batch-norm running-stat updates inside the pipeline)
and fetches must be producible by the last stage.

Composed parallelism (dp x pp in ONE program — the fleet
DistributedStrategy composition the reference pursues in
incubate/fleet/collective/__init__.py:134-253): pass
``PipelineOptimizer(..., mesh=, feed_specs=)`` a mesh that carries a
'pp' axis PLUS other axes. The pipeline shard_map is then manual over
'pp' ONLY (``axis_names={'pp'}``) — stage dispatch and the ppermute
ring see their pp shard — while every other axis stays an *auto* axis:
feeds keep their dp batch sharding and GSPMD partitions the stage
bodies and inserts the dp collectives exactly as it does outside the
pipeline (batch-group all-reduces are executed by every device of one
pp coordinate, consistent with that coordinate's lax.switch branch).

Param sharding over auto axes (tp) is REJECTED here, deliberately: the
heterogeneous stage bodies live in lax.switch branches that diverge by
pp index, and GSPMD freely inserts mesh-wide resharding
collective-permutes inside those branches when re-laying-out sharded
weights for a dot — devices of the other pp coordinate never reach
them, which deadlocks the collective (observed on the 8-device CPU
mesh: 4 threads at op_id=1, 4 at op_id=2). Uniform-body pipelines
don't have this hazard — for true dp x tp x pp composition use the
stacked-stage pipeline (paddle_tpu.parallel.pipeline.gpipe_composed),
whose single stage body is executed by EVERY device so tp psums are
structurally uniform.
"""
import warnings

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.registry import LowerContext
from .lowering import (
    OpLoweringError, apply_op, run_ops, segment_cuts, _make_var_lookup,
)

__all__ = ["run_pipeline_program"]


def _cut_names(cut_list):
    names = []
    for c in cut_list or []:
        if isinstance(c, (list, tuple)):
            names.extend(_cut_names(c))
        else:
            names.append(c.name if hasattr(c, "name") else str(c))
    return names


def _split_stages(region, cut_list):
    """Partition the forward op span at each cut var's producing op
    (the cut op ends its stage, like the reference's section split)."""
    cuts = segment_cuts(region, _cut_names(cut_list))
    spans = []
    prev = 0
    for c in cuts:
        spans.append((prev, c + 1))
        prev = c + 1
    spans.append((prev, len(region)))
    return spans


def _boundary_vars(region, spans, program):
    """Vars produced in stage <= b and consumed in a later stage — the
    union over boundaries is the ring buffer's (uniform) structure. Reads
    include while/cond sub-block closure reads (op_read_names), which the
    op's declared inputs would miss."""
    from .lowering import op_read_names

    stage_of = {}
    for s, (lo, hi) in enumerate(spans):
        for j in range(lo, hi):
            for ns in region[j].outputs.values():
                for n in ns:
                    stage_of[n] = s
    crossing = set()
    for s, (lo, hi) in enumerate(spans):
        for j in range(lo, hi):
            for n in op_read_names(region[j], program):
                if n in stage_of and stage_of[n] < s:
                    crossing.add(n)
    return sorted(crossing), stage_of


def run_pipeline_program(executor, program, feed, fetch_list, scope,
                         return_numpy):
    info = program._parallel_info
    block = program.global_block()
    op_list = list(block.ops)

    bw_idx = next(
        (i for i, op in enumerate(op_list) if op.type == "backward"), None
    )
    if bw_idx is None:
        raise OpLoweringError(
            "pipeline mode needs a backward op: call "
            "PipelineOptimizer.minimize(loss) before Executor.run"
        )
    region = op_list[:bw_idx]
    bw_op = op_list[bw_idx]
    post_ops = op_list[bw_idx + 1:]

    spans = _split_stages(region, info.get("cut_list"))
    n_stages = len(spans)
    if n_stages < 2:
        raise OpLoweringError(
            "PipelineOptimizer cut_list produced %d stage(s); pass the "
            "boundary activation vars as cut_list=[...]" % n_stages
        )
    devices = jax.devices()
    if len(devices) < n_stages:
        raise OpLoweringError(
            "pipeline needs one device per stage: %d stages but only %d "
            "device(s) visible" % (n_stages, len(devices))
        )
    ring_names, stage_of = _boundary_vars(region, spans, program)

    from .executor import _as_name

    fetch_names = [_as_name(f) for f in fetch_list or []]
    loss_name = bw_op.input("Loss")[0]
    last_lo, last_hi = spans[-1]
    last_stage_produced = {
        n for j in range(last_lo, last_hi)
        for ns in region[j].outputs.values() for n in ns
    }
    post_produced = {
        n for op in post_ops for ns in op.outputs.values() for n in ns
    }
    persist_names = {
        v.name for v in block.vars.values() if v.persistable
    }
    for f in fetch_names:
        if (f != loss_name and f not in last_stage_produced
                and f not in post_produced and f not in persist_names):
            raise OpLoweringError(
                "pipeline fetch '%s' is produced mid-pipeline; only "
                "last-stage vars (loss, metrics), post-backward vars "
                "(lr, counters) and persistable state are fetchable in "
                "pipeline mode" % f
            )
    record_names = sorted(
        (set(fetch_names) & last_stage_produced) | {loss_name}
    )

    feed_arrays = executor._prepare_feeds(program, feed)
    state = executor._gather_state(program, scope)
    target_names = bw_op.attrs["targets"]
    for n in target_names:
        if n not in state:
            raise OpLoweringError(
                "pipeline backward target '%s' missing from scope — run the "
                "startup program first" % n
            )

    n_micro = info.get("n_microbatches") or n_stages
    # the batch dimension is the largest leading dim among feeds; only
    # feeds carrying it are microbatched — smaller leading dims are
    # non-batch constants (im_info vectors etc.) and get replicated
    dim0s = [v.shape[0] for v in feed_arrays.values() if v.ndim > 0]
    batch_dim = max(dim0s) if dim0s else 0
    if batch_dim and batch_dim % n_micro:
        raise OpLoweringError(
            "feed batch %d not divisible by %d microbatches"
            % (batch_dim, n_micro)
        )

    if info.get("param_rules"):
        # Rejected on ANY mesh: on a composed mesh sharded weights make
        # GSPMD insert mesh-wide resharding collectives inside the
        # divergent lax.switch branches (a structural deadlock, observed
        # as 4-vs-4 rendezvous splits on the 8-device CPU mesh); on the
        # default pp-only mesh there is no auto axis to shard over. Both
        # roads lead to the same advice.
        raise OpLoweringError(
            "PipelineOptimizer(param_rules=...) is not supported: the "
            "heterogeneous stage bodies diverge per pp index "
            "(lax.switch), and sharded weights make GSPMD insert "
            "mesh-wide resharding collectives inside the divergent "
            "branches — a structural deadlock. Shard the batch over "
            "'dp' via feed_specs (safe: dp collective groups stay "
            "within one pp coordinate), or use the stacked-stage "
            "pipeline for dp x tp x pp "
            "(paddle_tpu.parallel.pipeline.gpipe_composed).")
    mesh = info.get("mesh")
    if mesh is None:
        mesh = Mesh(np.array(devices[:n_stages]), ("pp",))
    else:
        if "pp" not in mesh.axis_names:
            raise OpLoweringError(
                "PipelineOptimizer mesh must carry a 'pp' axis; got axes %s"
                % (mesh.axis_names,))
        if mesh.shape["pp"] != n_stages:
            raise OpLoweringError(
                "mesh 'pp' axis has size %d but cut_list produced %d "
                "stages" % (mesh.shape["pp"], n_stages))

    repl = NamedSharding(mesh, P())
    feed_specs = info.get("feed_specs") or {}
    unknown = set(feed_specs) - set(feed_arrays)
    if unknown:
        raise OpLoweringError(
            "PipelineOptimizer feed_specs name(s) %s match no feed "
            "(feeds: %s) — a typo here would silently replicate the "
            "batch instead of sharding it"
            % (sorted(unknown), sorted(feed_arrays)))
    feed_arrays = {
        k: jax.device_put(v, NamedSharding(mesh, feed_specs[k]))
        if k in feed_specs else jax.device_put(v, repl)
        for k, v in feed_arrays.items()
    }

    # ZeRO-1 composed with the pipeline (the fleet sharding_degree +
    # pipeline composition, ref incubate/fleet/collective/__init__.py):
    # OPTIMIZER state (belong_to_optimizer vars, like
    # DistributedProgram._opt_state_names) may shard over auto axes
    # because it is only read by the POST-pipeline ops (Adam/Momentum
    # updates), which run outside the divergent lax.switch branches —
    # unlike param_rules (rejected above). A matched opt var the
    # forward region READS is refused for exactly that reason;
    # non-optimizer matches are ignored, like DistributedProgram.
    opt_rules = info.get("opt_state_rules") or []
    if opt_rules:
        state_shardings = _resolve_opt_shardings(
            executor, program, region, opt_rules, mesh, repl, state)
        state = {k: jax.device_put(v, state_shardings.get(k, repl))
                 for k, v in state.items()}
    else:
        state = {k: jax.device_put(v, repl) for k, v in state.items()}
    rng = jax.device_put(executor._next_rng(program), repl)

    sig = (
        "pipeline", program._uid, program._version, n_stages, n_micro,
        tuple(sorted((k, v.shape, str(v.dtype))
                     for k, v in feed_arrays.items())),
        tuple(fetch_names),
        tuple(sorted((k, v.shape, str(v.dtype)) for k, v in state.items())),
    )
    entry = executor._cache.get(sig)
    if entry is None:
        jitted = _build_pipeline_fn(
            program, region, spans, ring_names, record_names, target_names,
            bw_op, post_ops, loss_name, mesh, n_micro, batch_dim,
        )
        # AOT-compile like the main executor path: without this the
        # donated state comes back in compiler-chosen layouts and run 2
        # would retrace+recompile the whole shard_map/scan module
        try:
            entry = jitted.lower(state, feed_arrays, rng).compile()
        except OpLoweringError:
            raise
        except Exception as e:
            warnings.warn(
                "pipeline AOT compile failed (%s: %s); falling back to "
                "traced jit — expect one redundant recompile"
                % (type(e).__name__, e)
            )
            entry = jitted
        executor._cache[sig] = entry

    fetches, new_state = entry(state, feed_arrays, rng)
    for k, v in new_state.items():
        scope.update(k, v)
    out = [fetches[n] for n in fetch_names]
    if return_numpy:
        return [np.asarray(v) for v in out]
    return out


def _resolve_opt_shardings(executor, program, region, opt_rules, mesh,
                           repl, state):
    """{state name -> NamedSharding} for opt_state_rules. Constant per
    (program, rules, mesh), so it is cached on the executor — the
    per-step cost is one dict lookup per var, not a regex sweep plus a
    recursive region-read scan."""
    key = ("pipe_opt_shardings", program._uid, program._version, id(mesh))
    cached = executor._cache.get(key)
    if cached is not None:
        return cached

    from .lowering import op_read_names
    from ..parallel.sharding import _spec_fits

    opt_names = {
        v.name for v in program.global_block().vars.values()
        if getattr(v, "belong_to_optimizer", False)
    }
    region_reads = set()
    for op in region:
        region_reads.update(op_read_names(op, program))

    out = {}
    for name, value in state.items():
        if name not in opt_names:
            continue
        shape = np.shape(value)
        for r in opt_rules:
            if not r.match(name):
                continue
            entries = tuple(r.spec)
            while entries and entries[-1] is None:
                entries = entries[:-1]
            if len(entries) > len(shape):
                continue
            spec = P(*entries)
            if not _spec_fits(spec, shape, mesh):
                continue
            if name in region_reads:
                raise OpLoweringError(
                    "opt_state_rules matched %r, which the pipeline "
                    "forward region READS — sharding it would put "
                    "GSPMD reshard collectives inside the divergent "
                    "stage branches (see param_rules error). Only "
                    "post-pipeline optimizer state may shard." % name)
            out[name] = NamedSharding(mesh, spec)
            break
    executor._cache[key] = out
    return out


def _build_pipeline_fn(program, region, spans, ring_names, record_names,
                       target_names, bw_op, post_ops, loss_name, mesh,
                       n_micro, batch_dim):
    block = program.global_block()
    var_lookup = _make_var_lookup(block)
    n_stages = len(spans)
    persist = {
        v.name for v in block.vars.values() if v.persistable
    }

    def step(state, feeds, rng):
        ctx = LowerContext(rng=rng, is_test=False, program=program,
                           mesh_axes={}, platform=None)
        ctx.run_ops = run_ops

        # microbatch the batch-dim feeds: (B, ...) -> (M, B//M, ...);
        # scalars and non-batch feeds are replicated per tick
        feeds_mb = {}
        for k, v in feeds.items():
            if v.ndim > 0 and v.shape[0] == batch_dim and batch_dim:
                feeds_mb[k] = v.reshape(
                    (n_micro, v.shape[0] // n_micro) + v.shape[1:]
                )
            else:
                feeds_mb[k] = jnp.broadcast_to(
                    v, (n_micro,) + v.shape
                )

        # ring buffer template: zeros in every boundary var's
        # microbatch-sized shape (trace stage-by-stage to get shapes)
        shapes = _infer_boundary_shapes(
            region, spans, ring_names, record_names, state, feeds_mb,
            program, var_lookup,
        )

        nontarget_state = {
            k: v for k, v in state.items() if k not in set(target_names)
        }

        def pipelined_loss(params):
            def local(params_l, nt_state_l, feeds_mb_l):
                idx = lax.axis_index("pp")
                perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

                def stage_body(s, env_base, buf):
                    lo, hi = spans[s]
                    e = dict(env_base)
                    e.update(buf)
                    for j in range(lo, hi):
                        e = apply_op(region[j], e, ctx, var_lookup,
                                     op_tag=1000 + j)
                    new_buf = {
                        n: e.get(n, buf[n]) for n in ring_names
                    }
                    rec = {
                        n: e[n] if n in e else jnp.zeros(shapes["rec"][n][0],
                                                         shapes["rec"][n][1])
                        for n in record_names
                    }
                    return new_buf, rec

                def tick(carry, t):
                    buf, recs = carry
                    mb_idx = jnp.clip(t - idx, 0, n_micro - 1)
                    env_base = dict(params_l)
                    env_base.update(nt_state_l)
                    for k, v in feeds_mb_l.items():
                        env_base[k] = v[mb_idx]
                    branches = [
                        (lambda b, _s=s: stage_body(_s, env_base, b))
                        for s in range(n_stages)
                    ]
                    # distinct PRNG per microbatch: without the traced
                    # token, dropout in a stage would reuse one mask for
                    # every microbatch (fold_in of a constant op tag is
                    # itself a compile-time constant inside this scan)
                    ctx._iter_token = mb_idx
                    try:
                        new_buf, rec = lax.switch(idx, branches, buf)
                    finally:
                        ctx._iter_token = None
                    done = t - (n_stages - 1)
                    is_last = idx == n_stages - 1
                    valid = is_last & (done >= 0) & (done < n_micro)
                    di = jnp.clip(done, 0, n_micro - 1)
                    recs = jax.tree_util.tree_map(
                        lambda acc, r: lax.cond(
                            valid,
                            lambda a: a.at[di].set(r),
                            lambda a: a,
                            acc,
                        ),
                        recs, rec,
                    )
                    new_buf = jax.tree_util.tree_map(
                        lambda x: lax.ppermute(x, "pp", perm), new_buf
                    )
                    return (new_buf, recs), None

                buf0 = {
                    n: jnp.zeros(shapes["ring"][n][0], shapes["ring"][n][1])
                    for n in ring_names
                }
                recs0 = {
                    n: jnp.zeros((n_micro,) + shapes["rec"][n][0],
                                 shapes["rec"][n][1])
                    for n in record_names
                }
                (_, recs), _ = lax.scan(
                    tick, (buf0, recs0),
                    jnp.arange(n_micro + n_stages - 1),
                )
                # only the last stage recorded; psum broadcasts to all
                return jax.tree_util.tree_map(
                    lambda x: lax.psum(x, "pp"), recs
                )

            # manual ONLY over 'pp' (stage switch + ppermute ring); any
            # other mesh axis (dp/tp/...) stays auto — GSPMD keeps the
            # feeds' dp sharding and the params' tp sharding inside the
            # stage bodies and inserts those collectives itself
            from ..parallel.sharding import shard_map_manual
            recs = shard_map_manual(
                local, mesh,
                in_specs=(P(), P(), P()),
                out_specs=P(),
                manual_axes={"pp"},
            )(params, nontarget_state, feeds_mb)
            loss_mb = recs[loss_name]
            loss = jnp.mean(loss_mb.astype(jnp.float32))
            return loss, recs

        params = {n: state[n] for n in target_names}
        (loss_val, vjp_fn, recs) = jax.vjp(
            pipelined_loss, params, has_aux=True
        )
        (grads,) = vjp_fn(jnp.ones_like(loss_val))

        # bind grads + recorded fetches, then run optimizer/post ops
        env = dict(state)
        env.update(feeds)
        env[loss_name] = loss_val
        for n in record_names:
            if n != loss_name:
                # microbatch-mean for float metrics (exact for means);
                # SUM for integer fetches — counts (accuracy Correct,
                # chunk totals) are additive over microbatches, and the
                # last microbatch alone would be silently ~M× too small
                r = recs[n]
                env[n] = jnp.mean(r.astype(jnp.float32), axis=0) \
                    if jnp.issubdtype(r.dtype, jnp.floating) \
                    else jnp.sum(r, axis=0)
        grad_names = bw_op.output("Grads")
        for tname, gname in zip(target_names, grad_names):
            env[gname] = grads[tname]
        for k, op in enumerate(post_ops):
            env = apply_op(op, env, ctx, var_lookup, op_tag=50000 + k)

        fetch_all = set(record_names) | (persist & set(env))
        for op in post_ops:
            for ns in op.outputs.values():
                fetch_all.update(ns)
        fetches = {n: env[n] for n in fetch_all if n in env}
        fetches[loss_name] = loss_val
        new_state = {n: env[n] for n in persist if n in env}
        return fetches, new_state

    return jax.jit(step, donate_argnums=(0,))


def _infer_boundary_shapes(region, spans, ring_names, record_names, state,
                           feeds_mb, program, var_lookup):
    """Abstractly evaluate one microbatch through the stages to learn the
    shapes/dtypes of boundary + recorded vars. Uses a private ctx with a
    constant rng so no outer-trace tracers leak into eval_shape."""
    probe_ctx = LowerContext(rng=jax.random.PRNGKey(0), is_test=False,
                             program=program, mesh_axes={}, platform=None)
    probe_ctx.run_ops = run_ops

    def probe(state_s, feeds_one):
        e = dict(state_s)
        e.update(feeds_one)
        for lo, hi in spans:
            for j in range(lo, hi):
                e = apply_op(region[j], e, probe_ctx, var_lookup,
                             op_tag=1000 + j)
        return (
            {n: e[n] for n in ring_names},
            {n: e[n] for n in record_names},
        )

    state_s = {
        k: jax.ShapeDtypeStruct(jnp.shape(v), jnp.result_type(v))
        for k, v in state.items()
    }
    feeds_one = {
        k: jax.ShapeDtypeStruct(v.shape[1:], v.dtype)
        for k, v in feeds_mb.items()
    }
    ring, rec = jax.eval_shape(probe, state_s, feeds_one)
    return {
        "ring": {k: (tuple(v.shape), v.dtype) for k, v in ring.items()},
        "rec": {k: (tuple(v.shape), v.dtype) for k, v in rec.items()},
    }
