"""ParallelExecutor (ref: python/paddle/fluid/parallel_executor.py) — thin
wrapper over CompiledProgram.with_data_parallel (pjit over the device Mesh)."""
import numpy as np

from . import core, framework
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy
from .executor import Executor, global_scope

__all__ = ["ParallelExecutor"]


class ParallelExecutor:
    def __init__(
        self,
        use_cuda=False,
        loss_name=None,
        main_program=None,
        share_vars_from=None,
        exec_strategy=None,
        build_strategy=None,
        num_trainers=1,
        trainer_id=0,
        scope=None,
    ):
        self._places = (
            framework.cuda_places() if use_cuda else framework.cpu_places()
        )
        # use_cuda selects the accelerator backend; here that is the TPU
        self._main_program = main_program or framework.default_main_program()
        self._scope = scope or global_scope()
        self._exe = Executor(
            core.default_place() if use_cuda else core.CPUPlace()
        )
        self._compiled = CompiledProgram(
            self._main_program, build_strategy
        ).with_data_parallel(
            loss_name=loss_name,
            exec_strategy=exec_strategy,
            share_vars_from=share_vars_from
            and share_vars_from._compiled,
        )

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        feed = feed if feed is not None else feed_dict
        return self._exe.run(
            program=self._compiled,
            feed=feed,
            fetch_list=fetch_list,
            scope=self._scope,
            return_numpy=return_numpy,
        )

    @property
    def device_count(self):
        import jax

        try:
            return len(jax.devices())
        except RuntimeError:
            return 1

    def drop_local_exe_scopes(self):
        pass
