"""fluid.input: one_hot / embedding, the "v2" variants
(ref: python/paddle/fluid/input.py).

Unlike ``fluid.layers.one_hot`` / ``fluid.layers.embedding`` (which
collapse a trailing ids dimension of 1, the LoD-era convention), these
append the new dimension to the id shape AS-IS: ids of shape (B, 1)
produce (B, 1, depth) / (B, 1, emb_size), exactly like the reference —
shapes in ported v2-style scripts line up.
"""
from .layer_helper import LayerHelper

__all__ = ["one_hot", "embedding"]


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot_v2", **locals())
    out = helper.create_variable_for_type_inference("float32")
    out.shape = tuple(input.shape or (-1,)) + (depth,)
    helper.append_op(
        type="one_hot",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={"depth": depth, "allow_out_of_range": allow_out_of_range,
               "_squeeze": False},
    )
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(
        attr=helper.param_attr, shape=size, dtype=dtype, is_bias=False)
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = tuple(input.shape or (-1,)) + (size[1],)
    padding_idx = (
        -1 if padding_idx is None
        else padding_idx if padding_idx >= 0
        else size[0] + padding_idx
    )
    helper.append_op(
        type="lookup_table_v2",
        inputs={"Ids": [input], "W": [w]},
        outputs={"Out": [out]},
        attrs={"padding_idx": padding_idx, "is_sparse": is_sparse,
               "is_distributed": is_distributed, "_squeeze": False},
    )
    return out
