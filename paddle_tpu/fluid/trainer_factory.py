"""Trainer factory + fetch monitor (ref: python/paddle/fluid/
trainer_factory.py).

The reference instantiates C++ trainer descs (MultiTrainer /
DistMultiTrainer / PipelineTrainer) pairing a trainer with a device
worker. Here the trainer desc is a plain dict driving
`Executor.train_from_dataset`'s loop; `FetchHandler` /
`FetchHandlerMonitor` keep the reference's asynchronous fetch-callback
contract (a daemon thread periodically handing the handler a dict of
fetched vars from the scope).
"""
import threading
import time

import numpy as np

__all__ = ["TrainerFactory", "FetchHandler", "FetchHandlerMonitor"]


class _TrainerDesc:
    def __init__(self, class_name):
        self.class_name = class_name
        self.desc = {"trainer_name": class_name}
        self.device_worker = None

    def _set_device_worker(self, worker):
        self.device_worker = worker
        if worker is not None:
            worker._gen_worker_desc(self.desc)

    def _set_thread(self, n):
        self.desc["thread_num"] = int(n)


class TrainerFactory:
    """ref trainer_factory.py:33."""

    def __init__(self):
        pass

    def _create_trainer(self, opt_info=None):
        from .device_worker import DeviceWorkerFactory

        if not opt_info:
            trainer = _TrainerDesc("MultiTrainer")
            trainer._set_device_worker(
                DeviceWorkerFactory()._create_device_worker("Hogwild"))
            return trainer
        trainer = _TrainerDesc(opt_info.get("trainer", "MultiTrainer"))
        worker_name = opt_info.get("device_worker", "Hogwild")
        trainer._set_device_worker(
            DeviceWorkerFactory()._create_device_worker(worker_name))
        return trainer


class FetchHandler:
    """User-overridable fetch callback (ref executor FetchHandler):
    ``var_dict`` maps display names to scope var names; ``handler`` is
    invoked every ``period_secs`` with {display_name: np.ndarray}."""

    def __init__(self, var_dict=None, period_secs=60):
        self.var_dict = var_dict or {}
        self.period_secs = period_secs

    def handler(self, res_dict):
        for k, v in res_dict.items():
            print("%s: %s" % (k, v))

    @staticmethod
    def help():
        print(
            "subclass FetchHandler and override handler(res_dict); "
            "var_dict={'loss': loss_var.name}, period_secs=N"
        )


class FetchHandlerMonitor:
    """ref trainer_factory.py:93 — daemon thread sampling scope vars."""

    def __init__(self, scope, handler):
        self.scope = scope
        self.handler = handler
        self._running = False
        self._thread = None

    def handler_launch_func(self, scope, handler):
        """ref trainer_factory.py:106 — run the sampling loop for an
        explicit (scope, handler) pair; start() uses the instance's."""
        self.scope, self.handler = scope, handler
        self._loop()

    def _loop(self):
        while self._running:
            time.sleep(self.handler.period_secs)
            if not self._running:
                return
            res = {}
            for disp, varname in self.handler.var_dict.items():
                name = getattr(varname, "name", varname)
                val = self.scope.find_var(name)
                if val is not None:
                    res[disp] = np.asarray(val.get_tensor())
            self.handler.handler(res)

    def start(self):
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._running = False
