"""Deprecated Evaluator API (ref: python/paddle/fluid/evaluator.py — kept
there only as aliases steering users to fluid.metrics). Same here: thin
program-building wrappers over layers.metric_op / metrics for code written
against the old surface."""
import warnings

from . import layers
from .metrics import Accuracy as _AccuracyMetric

__all__ = ["Accuracy", "ChunkEvaluator", "EditDistance", "DetectionMAP"]


def _deprecation(name, new):
    warnings.warn(
        "fluid.evaluator.%s is deprecated — use %s" % (name, new),
        DeprecationWarning, stacklevel=3,
    )


class Accuracy:
    """Build-time accuracy evaluator (deprecated; fluid.metrics.Accuracy +
    layers.accuracy is the supported pair). The legacy protocol works:
    fetch `.metrics[0]` each batch (its value feeds `update`), or let
    `eval()` aggregate whatever was accumulated so far."""

    def __init__(self, input, label, k=1, **kwargs):
        _deprecation("Accuracy", "fluid.metrics.Accuracy")
        self.metrics = []
        acc = layers.accuracy(input=input, label=label, k=k)
        self.metrics.append(acc)
        self._state = _AccuracyMetric()

    def eval(self, executor=None, eval_program=None):
        try:
            return self._state.eval()
        except ValueError:
            raise RuntimeError(
                "evaluator.Accuracy.eval(): nothing accumulated. Fetch "
                "self.metrics[0] in your exe.run and call "
                "update(value=batch_acc, weight=batch_size) per batch — "
                "or migrate to fluid.metrics.Accuracy (this class is a "
                "deprecated shim)."
            )

    def update(self, value, weight):
        self._state.update(value, weight)

    def reset(self, executor=None, reset_program=None):
        self._state = _AccuracyMetric()


class ChunkEvaluator:
    def __init__(self, *args, **kwargs):
        _deprecation("ChunkEvaluator", "fluid.metrics.ChunkEvaluator")
        from .metrics import ChunkEvaluator as M

        self._m = M()

    def __getattr__(self, item):
        return getattr(self._m, item)


class EditDistance:
    def __init__(self, *args, **kwargs):
        _deprecation("EditDistance", "fluid.metrics.EditDistance")
        from .metrics import EditDistance as M

        self._m = M()

    def __getattr__(self, item):
        return getattr(self._m, item)


class DetectionMAP:
    def __init__(self, *args, **kwargs):
        _deprecation("DetectionMAP", "fluid.metrics.DetectionMAP")
        from .metrics import DetectionMAP as M

        self._m = M(*args, **kwargs) if args or kwargs else M()

    def __getattr__(self, item):
        return getattr(self._m, item)
