"""ref import path dygraph/layer_object_helper.py — parameter-creation
helper dygraph Layers use. Here the ordinary LayerHelper already works
in both modes (it checks in_dygraph_mode and creates eager params), so
LayerObjectHelper is a thin name-carrying subclass."""
from ..layer_helper import LayerHelper

__all__ = ["LayerObjectHelper"]


class LayerObjectHelper(LayerHelper):
    def __init__(self, name):
        super().__init__(name)
        self._name = name

    @property
    def name(self):
        return self._name
