"""Eager execution + autograd tape
(ref: paddle/fluid/imperative/tracer.cc, python/paddle/fluid/dygraph/base.py).

TPU-native: each eager op call runs its jax lowering immediately (jit-cached
by XLA at the lax level); the tape records (lowering, inputs, outputs) and
backward() replays it in reverse through jax.vjp — no per-op grad kernels.
"""
import numpy as np

import jax
import jax.numpy as jnp

from .. import core
from ... import ops as ops_lib
from ...ops.registry import LowerContext, get_lowering

# lazy: creating a PRNGKey initializes the jax backend, which must not
# happen at import time (the TPU tunnel may be busy or absent)
_eager_rng = [None]
_rng_counter = [0]
_train_mode = [True]


def _next_eager_rng():
    if _eager_rng[0] is None:
        _eager_rng[0] = jax.random.PRNGKey(0)
    _rng_counter[0] += 1
    return jax.random.fold_in(_eager_rng[0], _rng_counter[0])


def seed(s):
    _eager_rng[0] = jax.random.PRNGKey(s)
    _rng_counter[0] = 0


def set_train_mode(mode):
    _train_mode[0] = bool(mode)


def in_train_mode():
    return _train_mode[0]


class VarBase:
    """Eager tensor (ref: framework.py ParamBase / imperative VarBase)."""

    _counter = [0]

    def __init__(self, value=None, name=None, stop_gradient=False,
                 persistable=False, trainable=True, dtype=None, shape=None):
        self.value = None if value is None else jnp.asarray(value)
        if name is None:
            VarBase._counter[0] += 1
            name = "eager_var_%d" % VarBase._counter[0]
        self.name = name
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable
        self.grad = None
        self._dtype_hint = dtype
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.gradient_clip_attr = None

    # -- tensor interface ------------------------------------------------
    @property
    def shape(self):
        return tuple(self.value.shape) if self.value is not None else None

    @shape.setter
    def shape(self, _):
        # graph-mode layers annotate inferred shapes; eager shape always
        # comes from the concrete value, so the annotation is a no-op
        pass

    @property
    def dtype(self):
        if self.value is not None:
            return core.convert_dtype(self.value.dtype)
        return self._dtype_hint

    @property
    def lod_level(self):
        return 0

    def numpy(self):
        return np.asarray(self.value)

    def gradient(self):
        if self.grad is None:
            return None
        return np.asarray(self.grad)

    def clear_gradient(self):
        self.grad = None

    def detach(self):
        return VarBase(self.value, stop_gradient=True)

    def astype(self, dtype):
        return call_op(
            "cast", {"X": [self]}, {"out_dtype": core.convert_dtype(dtype)}
        )

    def set_value(self, value):
        self.value = jnp.asarray(value)

    def backward(self, backward_strategy=None, retain_graph=False):
        run_backward(self)

    def __repr__(self):
        return "VarBase(name=%s, shape=%s, dtype=%s%s)" % (
            self.name, self.shape, self.dtype,
            ", grad" if self.grad is not None else "",
        )

    def __len__(self):
        return int(self.value.shape[0])

    def __float__(self):
        return float(np.asarray(self.value).reshape(-1)[0])

    def __getitem__(self, item):
        return VarBase(self.value[item], stop_gradient=self.stop_gradient)


class Tracer:
    def __init__(self):
        self.tape = []
        self.enabled = True

    def reset(self):
        self.tape = []


_tracer = Tracer()


def get_tape():
    return _tracer.tape


def _is_float(v):
    try:
        return jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
    except Exception:
        return False


def eager_run_op(type=None, inputs=None, outputs=None, attrs=None):
    """Execute one symbolic op eagerly; record on the tape. Matches the
    Block.append_op signature so LayerHelper routes here in dygraph mode."""
    inputs = inputs or {}
    outputs = outputs or {}
    attrs = dict(attrs or {})
    fn = get_lowering(type)
    ins_vb = {
        slot: [v for v in (vs if isinstance(vs, (list, tuple)) else [vs])]
        for slot, vs in inputs.items()
    }
    ins_vals = {
        slot: [v.value for v in vs] for slot, vs in ins_vb.items()
    }
    ctx = LowerContext(
        rng=_next_eager_rng(), is_test=not _train_mode[0]
    )
    out_vals = fn(ctx, ins_vals, attrs)
    outs_vb = {}
    for slot, vars_ in outputs.items():
        vars_ = vars_ if isinstance(vars_, (list, tuple)) else [vars_]
        vals = out_vals.get(slot, [])
        for i, var in enumerate(vars_):
            if i < len(vals):
                if not isinstance(var, VarBase):
                    raise TypeError(
                        "dygraph op '%s' output %s must be VarBase" % (type, slot)
                    )
                var.value = vals[i]
        outs_vb[slot] = list(vars_)

    needs_grad = any(
        isinstance(v, VarBase) and not v.stop_gradient and _is_float(v.value)
        for vs in ins_vb.values()
        for v in vs
    )
    if _tracer.enabled and needs_grad:
        _tracer.tape.append((type, fn, attrs, ins_vb, outs_vb,
                             ctx._rng, not _train_mode[0]))
        for vs in outs_vb.values():
            for v in vs:
                v.stop_gradient = False
    else:
        for vs in outs_vb.values():
            for v in vs:
                if v.value is not None and not needs_grad:
                    v.stop_gradient = True
    # single output convenience
    first_slot = next(iter(outputs), None)
    if first_slot is not None and len(outputs) == 1 and len(outs_vb[first_slot]) == 1:
        return outs_vb[first_slot][0]
    return outs_vb


def call_op(type, inputs, attrs=None, out_slots=("Out",), n_outs=None):
    """Functional eager op call: creates output VarBases itself."""
    outs = {}
    n_outs = n_outs or {}
    for slot in out_slots:
        k = n_outs.get(slot, 1)
        outs[slot] = [VarBase() for _ in range(k)]
    res = eager_run_op(type=type, inputs=inputs, outputs=outs, attrs=attrs)
    if isinstance(res, VarBase):
        return res
    if len(out_slots) == 1:
        vs = outs[out_slots[0]]
        return vs[0] if len(vs) == 1 else vs
    return outs


def run_backward(loss):
    """Reverse-mode sweep over the tape from `loss` (cotangent = ones)."""
    if loss.value is None:
        raise ValueError("backward() on empty VarBase")
    cotangents = {id(loss): jnp.ones_like(loss.value)}
    tape = _tracer.tape
    for (op_type, fn, attrs, ins_vb, outs_vb, rng, was_test) in reversed(tape):
        out_list = [v for vs in outs_vb.values() for v in vs]
        if not any(id(v) in cotangents for v in out_list):
            continue
        # differentiable input positions
        flat_ins = [(slot, i, v)
                    for slot, vs in ins_vb.items()
                    for i, v in enumerate(vs)]
        diff_pos = [
            (slot, i, v) for slot, i, v in flat_ins
            if not v.stop_gradient and _is_float(v.value)
        ]
        if not diff_pos:
            continue

        def fwd(primals):
            vals = {
                slot: [v.value for v in vs] for slot, vs in ins_vb.items()
            }
            for (slot, i, _), p in zip(diff_pos, primals):
                vals[slot][i] = p
            ctx = LowerContext(rng=rng, is_test=was_test)
            out = fn(ctx, vals, attrs)
            flat = []
            for slot, vs in outs_vb.items():
                ovals = out.get(slot, [])
                for j in range(len(vs)):
                    flat.append(ovals[j] if j < len(ovals) else None)
            return tuple(x for x in flat if x is not None)

        primals = [v.value for _, _, v in diff_pos]
        out_primals, vjp_fn = jax.vjp(fwd, primals)
        cts = []
        k = 0
        for slot, vs in outs_vb.items():
            for v in vs:
                if k < len(out_primals):
                    ct = cotangents.get(id(v))
                    if ct is None:
                        ct = jnp.zeros_like(out_primals[k])
                    elif not _is_float(out_primals[k]):
                        ct = jnp.zeros_like(out_primals[k])
                    cts.append(jnp.asarray(ct, out_primals[k].dtype)
                               if _is_float(out_primals[k])
                               else jnp.zeros_like(out_primals[k]))
                    k += 1
        (in_cts,) = vjp_fn(tuple(cts))
        for (slot, i, v), g in zip(diff_pos, in_cts):
            if g is None:
                continue
            prev = cotangents.get(id(v))
            cotangents[id(v)] = g if prev is None else prev + g
    # assign .grad on every input var that received a cotangent (params
    # accumulate across backward() calls, like the reference)
    seen = set()
    for (op_type, fn, attrs, ins_vb, outs_vb, rng, was_test) in tape:
        for vs in ins_vb.values():
            for v in vs:
                if id(v) in seen or id(v) not in cotangents:
                    continue
                seen.add(id(v))
                g = cotangents[id(v)]
                v.grad = g if v.grad is None else v.grad + g
    _tracer.tape = []
