"""dygraph imperative mode (ref: python/paddle/fluid/dygraph/__init__.py)."""
from . import base
from .base import (  # noqa: F401
    enabled,
    guard,
    no_grad,
    to_variable,
    enable_dygraph,
    disable_dygraph,
)
from . import layers
from .layers import Layer  # noqa: F401
from . import nn
from .nn import *  # noqa: F401,F403
from . import tracer
from .tracer import VarBase  # noqa: F401
from . import checkpoint
from .checkpoint import save_dygraph, load_dygraph  # noqa: F401
from . import jit
from .jit import TracedLayer  # noqa: F401
from . import parallel
from .parallel import DataParallel, ParallelEnv, prepare_context  # noqa: F401
from . import learning_rate_scheduler
from .learning_rate_scheduler import *  # noqa: F401,F403
from . import container
from .container import Sequential  # noqa: F401
from . import backward_strategy
from .backward_strategy import BackwardStrategy  # noqa: F401
from .tracer import Tracer  # noqa: F401


def start_gperf_profiler():
    """ref dygraph/profiler.py; delegates to the jax-profiler wrapper."""
    from ..profiler import start_profiler

    start_profiler("All")


def stop_gperf_profiler():
    from ..profiler import stop_profiler

    stop_profiler()

__all__ = (
    ["enabled", "guard", "no_grad", "to_variable", "Layer", "VarBase",
     "save_dygraph", "load_dygraph", "TracedLayer", "DataParallel",
     "ParallelEnv", "prepare_context", "Sequential",
     "BackwardStrategy", "Tracer", "start_gperf_profiler",
     "stop_gperf_profiler"]
    + nn.__all__
    + learning_rate_scheduler.__all__
)
