"""Layer containers (ref: python/paddle/fluid/dygraph/container.py)."""
from .layers import Layer

__all__ = ["Sequential"]


class Sequential(Layer):
    """Chain of sublayers applied in order (ref container.py Sequential).
    Accepts layers positionally or as (name, layer) pairs; indexable."""

    def __init__(self, *layers):
        super().__init__("sequential")
        for i, item in enumerate(layers):
            if isinstance(item, (list, tuple)):
                name, layer = item
            else:
                name, layer = str(i), item
            self.add_sublayer(name, layer)

    def __getitem__(self, i):
        return list(self._sub_layers.values())[i]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x
