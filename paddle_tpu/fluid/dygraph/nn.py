"""dygraph nn modules (ref: python/paddle/fluid/dygraph/nn.py)."""
import numpy as np

import jax.numpy as jnp

from .. import core
from ..initializer import Constant, Normal
from ..param_attr import ParamAttr
from . import tracer as tr
from .layers import Layer
from .tracer import VarBase, call_op

__all__ = [
    "Conv2D", "Conv3D", "Pool2D", "FC", "Linear", "BatchNorm", "Embedding",
    "GRUUnit", "LayerNorm", "NCE", "PRelu", "BilinearTensorProduct",
    "Conv2DTranspose", "Conv3DTranspose", "SequenceConv", "RowConv",
    "TreeConv", "GroupNorm", "SpectralNorm", "Dropout",
]


def _pair(v, n=2):
    return list(v) if isinstance(v, (list, tuple)) else [v] * n


def _reject_name_scope(first, cls):
    if isinstance(first, str):
        raise TypeError(
            "%s() no longer takes name_scope as its first argument (the "
            "reference dropped it — dygraph/nn.py); pass the layer's "
            "dimensions directly, e.g. Conv2D(num_channels, num_filters, "
            "filter_size)" % cls)



class Conv2D(Layer):
    def __init__(self, num_channels, num_filters=None, filter_size=None,
                 stride=1, padding=0, dilation=1, groups=None,
                 param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                 dtype="float32"):
        _reject_name_scope(num_channels, "Conv2D")
        super().__init__(None, dtype)
        self._num_filters = num_filters
        self._filter_size = _pair(filter_size)
        self._stride = _pair(stride)
        self._padding = _pair(padding)
        self._dilation = _pair(dilation)
        self._groups = groups or 1
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._num_channels = num_channels
        self.weight = None
        self.bias = None

    def _build_once(self, input):
        channels = self._num_channels or input.shape[1]
        filter_shape = [
            self._num_filters,
            channels // self._groups,
        ] + self._filter_size
        fan_in = channels * self._filter_size[0] * self._filter_size[1]
        self.weight = self.create_parameter(
            attr=self._param_attr,
            shape=filter_shape,
            dtype=self._dtype,
            default_initializer=Normal(0.0, (2.0 / fan_in) ** 0.5),
        )
        if self._bias_attr is not False:
            self.bias = self.create_parameter(
                attr=self._bias_attr,
                shape=[self._num_filters],
                dtype=self._dtype,
                is_bias=True,
            )

    def forward(self, input):
        if self.weight is None:
            self._build_once(input)
        out = call_op(
            "conv2d",
            {"Input": [input], "Filter": [self.weight]},
            {
                "strides": self._stride,
                "paddings": self._padding,
                "dilations": self._dilation,
                "groups": self._groups,
            },
            out_slots=("Output",),
        )
        if self.bias is not None:
            out = call_op(
                "elementwise_add",
                {"X": [out], "Y": [self.bias]},
                {"axis": 1},
            )
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class Conv3D(Conv2D):
    def __init__(self, num_channels, num_filters=None, filter_size=None,
                 stride=1, padding=0, dilation=1, groups=None,
                 param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                 dtype="float32"):
        _reject_name_scope(num_channels, "Conv3D")
        super().__init__(num_channels, num_filters, filter_size,
                         stride=stride, padding=padding, dilation=dilation,
                         groups=groups, param_attr=param_attr,
                         bias_attr=bias_attr, use_cudnn=use_cudnn, act=act,
                         dtype=dtype)
        self._filter_size = _pair(filter_size, 3)
        self._stride = _pair(stride, 3)
        self._padding = _pair(padding, 3)
        self._dilation = _pair(dilation, 3)

    def forward(self, input):
        if self.weight is None:
            self._build_once(input)
        out = call_op(
            "conv3d",
            {"Input": [input], "Filter": [self.weight]},
            {
                "strides": self._stride,
                "paddings": self._padding,
                "dilations": self._dilation,
                "groups": self._groups,
            },
            out_slots=("Output",),
        )
        if self.bias is not None:
            out = call_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": 1}
            )
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class Conv2DTranspose(Layer):
    def __init__(self, num_channels, num_filters=None, filter_size=None,
                 output_size=None, padding=0, stride=1, dilation=1,
                 groups=None, param_attr=None, bias_attr=None,
                 use_cudnn=True, act=None, dtype="float32"):
        _reject_name_scope(num_channels, "Conv2DTranspose")
        super().__init__(None, dtype)
        self._num_channels = num_channels
        self._num_filters = num_filters
        self._filter_size = _pair(filter_size)
        self._padding = _pair(padding)
        self._stride = _pair(stride)
        self._dilation = _pair(dilation)
        self._groups = groups or 1
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None

    def forward(self, input):
        if self.weight is None:
            channels = self._num_channels or input.shape[1]
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[channels, self._num_filters // self._groups]
                + self._filter_size,
                dtype=self._dtype,
            )
            if self._bias_attr is not False:
                self.bias = self.create_parameter(
                    attr=self._bias_attr,
                    shape=[self._num_filters],
                    dtype=self._dtype,
                    is_bias=True,
                )
        out = call_op(
            "conv2d_transpose",
            {"Input": [input], "Filter": [self.weight]},
            {
                "strides": self._stride,
                "paddings": self._padding,
                "dilations": self._dilation,
                "groups": self._groups,
            },
            out_slots=("Output",),
        )
        if self.bias is not None:
            out = call_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": 1}
            )
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class Conv3DTranspose(Layer):
    """ref dygraph/nn.py:491 Conv3DTranspose → conv3d_transpose lowering."""

    def __init__(self, num_channels, num_filters=None, filter_size=None,
                 padding=0, stride=1, dilation=1, groups=None,
                 param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                 dtype="float32", output_size=None):
        _reject_name_scope(num_channels, "Conv3DTranspose")
        super().__init__(None, dtype)
        self._num_channels = num_channels
        self._num_filters = num_filters
        self._filter_size = _pair(filter_size, 3)
        self._output_size = (
            _pair(output_size, 3) if output_size is not None else None
        )
        self._padding = _pair(padding, 3)
        self._stride = _pair(stride, 3)
        self._dilation = _pair(dilation, 3)
        self._groups = groups or 1
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None

    def forward(self, input):
        if self.weight is None:
            channels = self._num_channels or input.shape[1]
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[channels, self._num_filters // self._groups]
                + self._filter_size,
                dtype=self._dtype,
            )
            if self._bias_attr is not False:
                self.bias = self.create_parameter(
                    attr=self._bias_attr,
                    shape=[self._num_filters],
                    dtype=self._dtype,
                    is_bias=True,
                )
        from ..layers.nn import _resolve_output_padding

        out_padding = _resolve_output_padding(
            self._output_size, self._filter_size, input.shape[2:5],
            self._padding, self._stride, self._dilation, 3, _pair,
            lambda i, k, p, s, d: (i - 1) * s - 2 * p + d * (k - 1) + 1,
        )
        out = call_op(
            "conv3d_transpose",
            {"Input": [input], "Filter": [self.weight]},
            {
                "strides": self._stride,
                "paddings": self._padding,
                "dilations": self._dilation,
                "groups": self._groups,
                "output_padding": out_padding,
            },
            out_slots=("Output",),
        )
        if self.bias is not None:
            out = call_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]}, {"axis": 1}
            )
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class SequenceConv(Layer):
    """ref dygraph/nn.py:2591 SequenceConv. Input is the dense-padded
    (B, T, D) sequence batch; optional seq_len vector masks the padding."""

    def __init__(self, name_scope, num_filters, filter_size=3,
                 filter_stride=1, padding=None, bias_attr=None,
                 param_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        if filter_stride != 1:
            # reference restriction (sequence_lod.py:106)
            raise ValueError("SequenceConv only supports filter_stride=1")
        self._num_filters = num_filters
        self._filter_size = filter_size
        self._filter_stride = filter_stride
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self.weight = None
        self.bias = None

    def forward(self, input, seq_len=None):
        if self.weight is None:
            d = input.shape[-1]
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[self._filter_size * d, self._num_filters],
                dtype=self._dtype,
            )
            if self._bias_attr is not False:
                self.bias = self.create_parameter(
                    attr=self._bias_attr,
                    shape=[self._num_filters],
                    dtype=self._dtype,
                    is_bias=True,
                )
        ins = {"X": [input], "Filter": [self.weight]}
        if seq_len is not None:
            ins["SeqLen"] = [seq_len]
        out = call_op(
            "sequence_conv",
            ins,
            {
                "contextStride": self._filter_stride,
                "contextStart": -(self._filter_size // 2),
                "contextLength": self._filter_size,
            },
        )
        if self.bias is not None:
            out = call_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]},
                {"axis": 2},
            )
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class RowConv(Layer):
    """ref dygraph/nn.py:2685 RowConv (lookahead conv over time)."""

    def __init__(self, name_scope, future_context_size, param_attr=None,
                 act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._future_context_size = future_context_size
        self._param_attr = param_attr
        self._act = act
        self.weight = None

    def forward(self, input):
        if self.weight is None:
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[self._future_context_size + 1, input.shape[-1]],
                dtype=self._dtype,
            )
        out = call_op(
            "row_conv", {"X": [input], "Filter": [self.weight]}, {}
        )
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class TreeConv(Layer):
    """ref dygraph/nn.py:2970 TreeConv (TBCNN continuous binary tree) →
    tree_conv lowering (reachability matmuls)."""

    def __init__(self, feature_size, output_size=None,
                 num_filters=1, max_depth=2, act="tanh", param_attr=None,
                 bias_attr=None, name=None, dtype="float32"):
        _reject_name_scope(feature_size, "TreeConv")
        super().__init__(None, dtype)
        self._feature_size = feature_size
        self._output_size = output_size
        self._num_filters = num_filters
        self._max_depth = max_depth
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None

    def forward(self, nodes_vector, edge_set):
        if self.weight is None:
            f = self._feature_size or nodes_vector.shape[-1]
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[f, 3, self._output_size, self._num_filters],
                dtype=self._dtype,
            )
            if self._bias_attr:
                self.bias = self.create_parameter(
                    attr=self._bias_attr,
                    shape=[self._num_filters],
                    dtype=self._dtype,
                    is_bias=True,
                )
        out = call_op(
            "tree_conv",
            {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
             "Filter": [self.weight]},
            {"max_depth": self._max_depth},
        )
        if self.bias is not None:
            out = call_op(
                "elementwise_add", {"X": [out], "Y": [self.bias]},
                {"axis": 3},
            )
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class Pool2D(Layer):
    def __init__(self, pool_size=-1, pool_type="max",
                 pool_stride=1, pool_padding=0, global_pooling=False,
                 use_cudnn=True, ceil_mode=False, exclusive=True,
                 dtype="float32"):
        _reject_name_scope(pool_size, "Pool2D")
        super().__init__(None, dtype)
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": _pair(pool_size),
            "strides": _pair(pool_stride),
            "paddings": _pair(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
        }

    def forward(self, input):
        return call_op("pool2d", {"X": [input]}, dict(self._attrs))


class Linear(Layer):
    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__("linear", dtype)
        self.weight = self.create_parameter(
            attr=param_attr, shape=[input_dim, output_dim], dtype=dtype
        )
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter(
                attr=bias_attr, shape=[output_dim], dtype=dtype, is_bias=True
            )
        )
        self._act = act

    def forward(self, input):
        out = call_op(
            "mul",
            {"X": [input], "Y": [self.weight]},
            {"x_num_col_dims": len(input.shape) - 1, "y_num_col_dims": 1},
        )
        if self.bias is not None:
            out = call_op(
                "elementwise_add",
                {"X": [out], "Y": [self.bias]},
                {"axis": len(out.shape) - 1},
            )
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class FC(Layer):
    def __init__(self, name_scope, size, num_flatten_dims=1, param_attr=None,
                 bias_attr=None, act=None, is_test=False, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self.weight = None
        self.bias = None

    def forward(self, input):
        if self.weight is None:
            in_features = int(
                np.prod(input.shape[self._num_flatten_dims :])
            )
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[in_features, self._size],
                dtype=self._dtype,
            )
            if self._bias_attr is not False:
                self.bias = self.create_parameter(
                    attr=self._bias_attr,
                    shape=[self._size],
                    dtype=self._dtype,
                    is_bias=True,
                )
        out = call_op(
            "mul",
            {"X": [input], "Y": [self.weight]},
            {
                "x_num_col_dims": self._num_flatten_dims,
                "y_num_col_dims": 1,
            },
        )
        if self.bias is not None:
            out = call_op(
                "elementwise_add",
                {"X": [out], "Y": [self.bias]},
                {"axis": self._num_flatten_dims},
            )
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class BatchNorm(Layer):
    def __init__(self, num_channels, act=None, is_test=False,
                 momentum=0.9, epsilon=1e-05, param_attr=None,
                 bias_attr=None, dtype="float32", data_layout="NCHW",
                 in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        _reject_name_scope(num_channels, "BatchNorm")
        super().__init__(None, dtype)
        self._act = act
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_layout = data_layout
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            attr=param_attr, shape=[num_channels], dtype=dtype,
            default_initializer=Constant(1.0),
        )
        self.bias = self.create_parameter(
            attr=bias_attr, shape=[num_channels], dtype=dtype, is_bias=True
        )
        self._mean = VarBase(
            jnp.zeros((num_channels,), core.np_dtype(dtype)),
            name=moving_mean_name, persistable=True, stop_gradient=True,
            trainable=False,
        )
        self._variance = VarBase(
            jnp.ones((num_channels,), core.np_dtype(dtype)),
            name=moving_variance_name, persistable=True, stop_gradient=True,
            trainable=False,
        )

    def forward(self, input):
        outs = {
            "Y": [VarBase()],
            "MeanOut": [self._mean],
            "VarianceOut": [self._variance],
            "SavedMean": [VarBase()],
            "SavedVariance": [VarBase()],
        }
        tr.eager_run_op(
            type="batch_norm",
            inputs={
                "X": [input],
                "Scale": [self.weight],
                "Bias": [self.bias],
                "Mean": [self._mean],
                "Variance": [self._variance],
            },
            outputs=outs,
            attrs={
                "momentum": self._momentum,
                "epsilon": self._epsilon,
                "is_test": not self.training,
                "data_layout": self._data_layout,
                "use_global_stats": self._use_global_stats,
            },
        )
        y = outs["Y"][0]
        if self._act:
            y = call_op(self._act, {"X": [y]})
        return y


class Embedding(Layer):
    def __init__(self, size=None, is_sparse=False,
                 is_distributed=False, padding_idx=None, param_attr=None,
                 dtype="float32"):
        _reject_name_scope(size, "Embedding")
        super().__init__(None, dtype)
        self._size = size
        self._padding_idx = (
            -1 if padding_idx is None else
            padding_idx if padding_idx >= 0 else size[0] + padding_idx
        )
        self.weight = self.create_parameter(
            attr=param_attr, shape=size, dtype=dtype
        )

    def forward(self, input):
        return call_op(
            "lookup_table_v2",
            {"Ids": [input], "W": [self.weight]},
            {"padding_idx": self._padding_idx},
        )


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-05, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        _reject_name_scope(normalized_shape, "LayerNorm")
        super().__init__(None, dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._scale = scale
        self._shift = shift
        self._epsilon = epsilon
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self.weight = None
        self.bias = None

    def forward(self, input):
        begin_norm_axis = len(input.shape) - len(self._normalized_shape)
        if tuple(input.shape[begin_norm_axis:]) != tuple(
                self._normalized_shape):
            raise ValueError(
                "LayerNorm normalized_shape %s does not match input tail "
                "%s" % (self._normalized_shape,
                        tuple(input.shape[begin_norm_axis:])))
        if self.weight is None and self._scale:
            n = int(np.prod(self._normalized_shape))
            self.weight = self.create_parameter(
                attr=self._param_attr, shape=[n], dtype=self._dtype,
                default_initializer=Constant(1.0),
            )
            if self._shift:
                self.bias = self.create_parameter(
                    attr=self._bias_attr, shape=[n], dtype=self._dtype,
                    is_bias=True,
                )
        ins = {"X": [input]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = call_op(
            "layer_norm",
            ins,
            {
                "epsilon": self._epsilon,
                "begin_norm_axis": begin_norm_axis,
            },
            out_slots=("Y", "Mean", "Variance"),
        )
        y = out["Y"][0]
        if self._act:
            y = call_op(self._act, {"X": [y]})
        return y


class GRUUnit(Layer):
    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        _reject_name_scope(size, "GRUUnit")
        super().__init__(None, dtype)
        self._size = size  # 3*D
        d = size // 3
        self._d = d
        self._origin_mode = origin_mode
        self._activation = activation
        self._gate_activation = gate_activation
        self.weight = self.create_parameter(
            attr=param_attr, shape=[d, 3 * d], dtype=dtype
        )
        self.bias = self.create_parameter(
            attr=bias_attr, shape=[1, 3 * d], dtype=dtype, is_bias=True
        )

    def forward(self, input, hidden):
        outs = {
            "Hidden": [VarBase()],
            "ResetHiddenPrev": [VarBase()],
            "Gate": [VarBase()],
        }
        tr.eager_run_op(
            type="gru_unit",
            inputs={
                "Input": [input],
                "HiddenPrev": [hidden],
                "Weight": [self.weight],
                "Bias": [self.bias],
            },
            outputs=outs,
            attrs={
                "activation": self._activation,
                "gate_activation": self._gate_activation,
                "origin_mode": self._origin_mode,
            },
        )
        return outs["Hidden"][0], outs["ResetHiddenPrev"][0], outs["Gate"][0]


class NCE(Layer):
    def __init__(self, num_total_classes, dim=None, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=None,
                 sampler="uniform", custom_dist=None, seed=0,
                 is_sparse=False, dtype="float32"):
        _reject_name_scope(num_total_classes, "NCE")
        super().__init__(None, dtype)
        self._dim = dim
        self._num_total_classes = num_total_classes
        self._num_neg_samples = num_neg_samples or 10
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None

    def forward(self, input, label, sample_weight=None):
        if sample_weight is not None:
            raise NotImplementedError(
                "NCE: per-sample weights are not supported; weight the "
                "loss externally instead"
            )
        if self.weight is None:
            dim = self._dim or input.shape[1]
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[self._num_total_classes, dim],
                dtype=self._dtype,
            )
            self.bias = self.create_parameter(
                attr=self._bias_attr,
                shape=[self._num_total_classes, 1],
                dtype=self._dtype,
                is_bias=True,
            )
        return call_op(
            "nce",
            {
                "Input": [input],
                "Label": [label],
                "Weight": [self.weight],
                "Bias": [self.bias],
            },
            {
                "num_total_classes": self._num_total_classes,
                "num_neg_samples": self._num_neg_samples,
            },
            out_slots=("Cost",),
        )


class PRelu(Layer):
    def __init__(self, mode, input_shape=None, param_attr=None,
                 dtype="float32", channel=None):
        if mode not in ("all", "channel", "element"):
            raise ValueError(
                "PRelu mode must be 'all'/'channel'/'element', got %r "
                "(the legacy (name_scope, mode) construction was removed "
                "to match the reference)" % (mode,))
        super().__init__(None, dtype)
        self._mode = mode
        self._param_attr = param_attr
        self._channel = channel
        self._input_shape = input_shape
        self.weight = None

    def forward(self, input):
        if self.weight is None:
            if self._mode == "all":
                shape = [1]
            elif self._mode == "channel":
                shape = [self._channel or input.shape[1]]
            else:
                shape = list(self._input_shape or input.shape[1:])
            self.weight = self.create_parameter(
                attr=self._param_attr, shape=shape, dtype=self._dtype,
                default_initializer=Constant(0.25),
            )
        return call_op(
            "prelu",
            {"X": [input], "Alpha": [self.weight]},
            {"mode": self._mode},
        )


class BilinearTensorProduct(Layer):
    def __init__(self, input1_dim, input2_dim=None, output_dim=None,
                 name=None, act=None,
                 param_attr=None, bias_attr=None, dtype="float32"):
        _reject_name_scope(input1_dim, "BilinearTensorProduct")
        super().__init__(None, dtype)
        self._input1_dim = input1_dim
        self._input2_dim = input2_dim
        self._size = output_dim
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None

    def forward(self, x, y):
        if self.weight is None:
            d1 = self._input1_dim or x.shape[1]
            d2 = self._input2_dim or y.shape[1]
            self.weight = self.create_parameter(
                attr=self._param_attr,
                shape=[self._size, d1, d2],
                dtype=self._dtype,
            )
            if self._bias_attr is not False:
                self.bias = self.create_parameter(
                    attr=self._bias_attr, shape=[1, self._size],
                    dtype=self._dtype, is_bias=True,
                )
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = call_op("bilinear_tensor_product", ins)
        if self._act:
            out = call_op(self._act, {"X": [out]})
        return out


class GroupNorm(Layer):
    def __init__(self, channels, groups=None, epsilon=1e-05,
                 param_attr=None,
                 bias_attr=None, act=None, data_layout="NCHW",
                 dtype="float32"):
        _reject_name_scope(channels, "GroupNorm")
        if groups is None:
            raise ValueError(
                "GroupNorm requires groups (ref signature: "
                "GroupNorm(channels, groups, ...))")
        super().__init__(None, dtype)
        self._channels = channels
        self._groups = groups
        self._epsilon = epsilon
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self.weight = None
        self.bias = None

    def forward(self, input):
        if self.weight is None:
            c = self._channels or input.shape[1]
            self.weight = self.create_parameter(
                attr=self._param_attr, shape=[c], dtype=self._dtype,
                default_initializer=Constant(1.0),
            )
            self.bias = self.create_parameter(
                attr=self._bias_attr, shape=[c], dtype=self._dtype,
                is_bias=True,
            )
        out = call_op(
            "group_norm",
            {"X": [input], "Scale": [self.weight], "Bias": [self.bias]},
            {"groups": self._groups, "epsilon": self._epsilon},
            out_slots=("Y", "Mean", "Variance"),
        )
        y = out["Y"][0]
        if self._act:
            y = call_op(self._act, {"X": [y]})
        return y


class SpectralNorm(Layer):
    def __init__(self, weight_shape=None, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        _reject_name_scope(weight_shape, "SpectralNorm")
        super().__init__(None, dtype)
        self._weight_shape = weight_shape
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        self._u = None
        self._v = None

    def forward(self, weight):
        if self._weight_shape is not None and tuple(weight.shape) != tuple(
                self._weight_shape):
            raise ValueError(
                "SpectralNorm weight_shape %s does not match weight %s"
                % (self._weight_shape, tuple(weight.shape)))
        if self._u is None:
            h = weight.shape[self._dim]
            w = int(np.prod(weight.shape)) // h
            self._u = VarBase(
                jnp.asarray(np.random.normal(size=h).astype("float32")),
                persistable=True, stop_gradient=True, trainable=False,
            )
            self._v = VarBase(
                jnp.asarray(np.random.normal(size=w).astype("float32")),
                persistable=True, stop_gradient=True, trainable=False,
            )
        return call_op(
            "spectral_norm",
            {"Weight": [weight], "U": [self._u], "V": [self._v]},
            {"dim": self._dim, "power_iters": self._power_iters,
             "eps": self._eps},
        )


class Dropout(Layer):
    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer"):
        super().__init__("dropout")
        self._p = p
        self._impl = dropout_implementation

    def forward(self, input):
        out = call_op(
            "dropout",
            {"X": [input]},
            {
                "dropout_prob": self._p,
                "is_test": not self.training,
                "dropout_implementation": self._impl,
            },
            out_slots=("Out", "Mask"),
        )
        return out["Out"][0]
