"""ref import path dygraph/math_op_patch.py — the reference monkey-
patches arithmetic dunders onto VarBase at import time. Here dygraph
variables implement their operators natively (fluid/dygraph/base.py),
so the patch entry points are satisfied-by-construction no-ops kept
for scripts that call them explicitly."""

__all__ = ["monkey_patch_math_varbase"]


def monkey_patch_math_varbase():
    """Already in effect: dygraph variables carry +,-,*,/,matmul,
    comparison dunders natively."""
