"""dygraph LR schedulers (ref: python/paddle/fluid/dygraph/
learning_rate_scheduler.py) — python objects with .step()."""
import math

__all__ = [
    "NoamDecay", "PiecewiseDecay", "NaturalExpDecay", "ExponentialDecay",
    "InverseTimeDecay", "PolynomialDecay", "CosineDecay", "LinearLrWarmup",
    "ReduceLROnPlateau",
]


class LearningRateDecay:
    def __init__(self, begin=0, step=1, dtype="float32"):
        self.step_num = begin
        self.step_size = step
        self.dtype = dtype

    def step(self):
        lr = self.get_lr()
        self.step_num += self.step_size
        return lr

    __call__ = step

    def get_lr(self):
        raise NotImplementedError


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.boundaries = boundaries
        self.values = values

    def get_lr(self):
        for i, b in enumerate(self.boundaries):
            if self.step_num < b:
                return self.values[i]
        return self.values[-1]


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1,
                 dtype="float32"):
        super().__init__(begin, step, dtype)
        self.d_model = d_model
        self.warmup_steps = warmup_steps

    def get_lr(self):
        s = max(self.step_num, 1)
        return (self.d_model ** -0.5) * min(
            s ** -0.5, s * self.warmup_steps ** -1.5
        )


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def get_lr(self):
        d = self.step_num / self.decay_steps
        if self.staircase:
            d = math.floor(d)
        return self.learning_rate * math.exp(-self.decay_rate * d)


class ExponentialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def get_lr(self):
        d = self.step_num / self.decay_steps
        if self.staircase:
            d = math.floor(d)
        return self.learning_rate * (self.decay_rate ** d)


class InverseTimeDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.decay_rate = decay_rate
        self.staircase = staircase

    def get_lr(self):
        d = self.step_num / self.decay_steps
        if self.staircase:
            d = math.floor(d)
        return self.learning_rate / (1 + self.decay_rate * d)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=0.0001,
                 power=1.0, cycle=False, begin=0, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.decay_steps = decay_steps
        self.end_learning_rate = end_learning_rate
        self.power = power
        self.cycle = cycle

    def get_lr(self):
        s = self.step_num
        if self.cycle:
            div = max(1.0, math.ceil(s / self.decay_steps))
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            s = min(s, decay_steps)
        return (self.learning_rate - self.end_learning_rate) * (
            (1 - s / decay_steps) ** self.power
        ) + self.end_learning_rate


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0,
                 step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def get_lr(self):
        cur_epoch = math.floor(self.step_num / self.step_each_epoch)
        return (
            self.learning_rate
            * 0.5
            * (math.cos(cur_epoch * math.pi / self.epochs) + 1)
        )


class LinearLrWarmup(LearningRateDecay):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 begin=1, step=1, dtype="float32"):
        super().__init__(begin, step, dtype)
        self.learning_rate = learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr

    def get_lr(self):
        if self.step_num < self.warmup_steps:
            return self.start_lr + (self.end_lr - self.start_lr) * (
                self.step_num / self.warmup_steps
            )
        base = self.learning_rate
        return base.get_lr() if hasattr(base, "get_lr") else base


class ReduceLROnPlateau(LearningRateDecay):
    def __init__(self, learning_rate, mode="min", decay_rate=0.1,
                 patience=10, verbose=False, threshold=1e-4,
                 threshold_mode="rel", cooldown=0, min_lr=0, eps=1e-8,
                 dtype="float32"):
        super().__init__(0, 1, dtype)
        self.lr = learning_rate
        self.mode = mode
        self.decay_rate = decay_rate
        self.patience = patience
        self.verbose = verbose
        self.threshold = threshold
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.eps = eps
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0

    def get_lr(self):
        return self.lr

    def step(self, metric=None):
        if metric is None:
            return self.lr
        m = float(metric)
        better = (
            self.best is None
            or (self.mode == "min" and m < self.best - self.threshold)
            or (self.mode == "max" and m > self.best + self.threshold)
        )
        if better:
            self.best = m
            self.num_bad = 0
        elif self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                new_lr = max(self.lr * self.decay_rate, self.min_lr)
                if self.lr - new_lr > self.eps:
                    self.lr = new_lr
                self.cooldown_counter = self.cooldown
                self.num_bad = 0
        return self.lr
