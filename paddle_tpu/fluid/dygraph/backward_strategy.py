"""Backward configuration (ref: python/paddle/fluid/dygraph/
backward_strategy.py). The vjp-based tape always sums gradients
deterministically in program order, so sort_sum_gradient is recorded but
changes nothing (it existed to make the reference's accumulation order
deterministic — already guaranteed here)."""

__all__ = ["BackwardStrategy"]


class BackwardStrategy:
    def __init__(self):
        self.sort_sum_gradient = False
