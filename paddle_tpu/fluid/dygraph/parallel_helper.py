"""ref import path dygraph/parallel_helper.py — process-local parallel
context flag used by dygraph DataParallel (ref parallel_helper.py)."""
import os

__all__ = ["_is_parallel_ctx_initialized", "_set_parallel_ctx",
           "_init_parallel_ctx"]

_parallel_ctx_initialized = False


def _is_parallel_ctx_initialized():
    return _parallel_ctx_initialized


def _set_parallel_ctx(ctx=True):
    global _parallel_ctx_initialized
    _parallel_ctx_initialized = bool(ctx)


def _init_parallel_ctx():
    """The mesh IS the comm context; just record the flag (the
    reference spins up an NCCL parallel context here)."""
    _set_parallel_ctx(True)
    return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
