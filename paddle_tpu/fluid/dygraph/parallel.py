"""dygraph DataParallel (ref: python/paddle/fluid/dygraph/parallel.py).

TPU-native: gradients are all-reduced with jax.lax collectives when running
under a mesh; single-process multi-device eager training instead uses the
static-graph CompiledProgram path, so this class focuses on API parity:
scale_loss + apply_collective_grads."""
import numpy as np
import jax
import jax.numpy as jnp

from .layers import Layer

__all__ = ["prepare_context", "ParallelEnv", "DataParallel", "Env"]


def prepare_context(strategy=None):
    return strategy


class ParallelEnv:
    def __init__(self):
        self._nranks = 1
        self._local_rank = 0
        try:
            self._nranks = jax.device_count()
        except RuntimeError:
            pass

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._local_rank

    @property
    def current_endpoint(self):
        return "127.0.0.1:0"

    @property
    def trainer_endpoints(self):
        return ["127.0.0.1:0"]


Env = ParallelEnv


class DataParallel(Layer):
    def __init__(self, layers, strategy=None):
        super().__init__("data_parallel")
        self._layers = layers
        self._strategy = strategy or ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        n = getattr(self._strategy, "nranks", 1)
        if n <= 1:
            return loss
        from .tracer import call_op

        return call_op("scale", {"X": [loss]}, {"scale": 1.0 / n})

    def apply_collective_grads(self):
        # under pjit/shard_map the psum is inserted by the partitioner;
        # eager single-host: no-op
        pass

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict
