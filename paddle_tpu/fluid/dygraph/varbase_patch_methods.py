"""ref import path dygraph/varbase_patch_methods.py — the reference
patches methods (numpy(), backward(), gradient(), ...) onto VarBase.
Here dygraph variables implement these natively; the patch entry point
is a satisfied-by-construction no-op."""

__all__ = ["monkey_patch_varbase"]


def monkey_patch_varbase():
    """Already in effect: dygraph variables carry numpy()/backward()/
    gradient()/clear_gradient() natively."""
