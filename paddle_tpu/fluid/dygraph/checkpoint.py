"""save/load_dygraph (ref: python/paddle/fluid/dygraph/checkpoint.py)."""
import os
import pickle

import numpy as np

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path):
    """Saves a Layer.state_dict() or optimizer state to model_path.pdparams."""
    suffix = ".pdparams"
    payload = {}
    is_opt = False
    for k, v in state_dict.items():
        if hasattr(v, "numpy"):
            payload[k] = np.asarray(v.numpy())
        else:
            payload[k] = v
            is_opt = True
    if is_opt:
        suffix = ".pdopt"
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(model_path + suffix, "wb") as f:
        pickle.dump(payload, f, protocol=2)


def load_dygraph(model_path):
    params, opt = None, None
    if os.path.exists(model_path + ".pdparams"):
        with open(model_path + ".pdparams", "rb") as f:
            params = pickle.load(f)
    if os.path.exists(model_path + ".pdopt"):
        with open(model_path + ".pdopt", "rb") as f:
            opt = pickle.load(f)
    if params is None and opt is None:
        raise ValueError("no checkpoint found at %s" % model_path)
    return params, opt
