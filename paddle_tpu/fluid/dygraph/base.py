"""dygraph.base: guard / to_variable / eager helpers
(ref: python/paddle/fluid/dygraph/base.py)."""
import contextlib
import functools

import numpy as np
import jax
import jax.numpy as jnp

from .. import core, framework, unique_name
from ..initializer import (
    BilinearInitializer,
    ConstantInitializer,
    MSRAInitializer,
    NormalInitializer,
    NumpyArrayInitializer,
    TruncatedNormalInitializer,
    UniformInitializer,
    XavierInitializer,
)
from . import tracer as tr
from .tracer import VarBase

__all__ = [
    "guard", "enabled", "to_variable", "no_grad", "enable_dygraph",
    "disable_dygraph",
]


def enabled():
    return framework.in_dygraph_mode()


_guard_exit = []


def enable_dygraph(place=None):
    ctx = framework._dygraph_guard(tr._tracer)
    ctx.__enter__()
    pctx = framework._dygraph_place_guard(place or core.default_place())
    pctx.__enter__()
    _guard_exit.append((ctx, pctx))


def disable_dygraph():
    if _guard_exit:
        ctx, pctx = _guard_exit.pop()
        pctx.__exit__(None, None, None)
        ctx.__exit__(None, None, None)


@contextlib.contextmanager
def guard(place=None):
    with framework._dygraph_guard(tr._tracer):
        with framework._dygraph_place_guard(place or core.default_place()):
            yield


@contextlib.contextmanager
def no_grad_ctx():
    prev = tr._tracer.enabled
    tr._tracer.enabled = False
    try:
        yield
    finally:
        tr._tracer.enabled = prev


def no_grad(func=None):
    if func is None:
        return no_grad_ctx()

    @functools.wraps(func)
    def wrapper(*args, **kwargs):
        with no_grad_ctx():
            return func(*args, **kwargs)

    return wrapper


def to_variable(value, name=None, zero_copy=None):
    if isinstance(value, VarBase):
        return value
    arr = np.asarray(value)
    return VarBase(jnp.asarray(arr), name=name, stop_gradient=False)


# ---------------------------------------------------------------------------
# eager initialization (maps graph initializers to direct jax calls)
# ---------------------------------------------------------------------------
def eager_init(initializer, shape, dtype):
    dt = core.np_dtype(core.convert_dtype(dtype))
    rng = tr._next_eager_rng()
    shape = tuple(int(s) for s in shape)
    if initializer is None:
        initializer = XavierInitializer()
    if isinstance(initializer, ConstantInitializer):
        return jnp.full(shape, initializer._value, dtype=dt)
    if isinstance(initializer, UniformInitializer):
        return jax.random.uniform(
            rng, shape, minval=initializer._low, maxval=initializer._high
        ).astype(dt)
    if isinstance(initializer, NormalInitializer):
        return (
            initializer._mean
            + initializer._std_dev * jax.random.normal(rng, shape)
        ).astype(dt)
    if isinstance(initializer, TruncatedNormalInitializer):
        return (
            initializer._mean
            + initializer._std_dev
            * jax.random.truncated_normal(rng, -2.0, 2.0, shape)
        ).astype(dt)
    if isinstance(initializer, (XavierInitializer, MSRAInitializer)):
        class _V:
            pass

        v = _V()
        v.shape = shape
        fan_in, fan_out = initializer._compute_fans(v)
        import math

        if isinstance(initializer, XavierInitializer):
            fi = initializer._fan_in or fan_in
            fo = initializer._fan_out or fan_out
            if initializer._uniform:
                lim = math.sqrt(6.0 / (fi + fo))
                return jax.random.uniform(
                    rng, shape, minval=-lim, maxval=lim
                ).astype(dt)
            std = math.sqrt(2.0 / (fi + fo))
            return (std * jax.random.normal(rng, shape)).astype(dt)
        fi = initializer._fan_in or fan_in
        if initializer._uniform:
            lim = math.sqrt(6.0 / fi)
            return jax.random.uniform(
                rng, shape, minval=-lim, maxval=lim
            ).astype(dt)
        std = math.sqrt(2.0 / fi)
        return (std * jax.random.normal(rng, shape)).astype(dt)
    if isinstance(initializer, NumpyArrayInitializer):
        return jnp.asarray(initializer._value).astype(dt).reshape(shape)
    raise TypeError("unsupported initializer %r for eager init" % initializer)


def create_eager_parameter(attr, shape, dtype, startup_program=None):
    value = eager_init(attr.initializer, shape, dtype)
    p = VarBase(
        value,
        name=attr.name or unique_name.generate("eager_param"),
        persistable=True,
        trainable=attr.trainable,
        stop_gradient=not attr.trainable,
    )
    p.optimize_attr = {"learning_rate": attr.learning_rate}
    p.regularizer = attr.regularizer
    return p


# ---------------------------------------------------------------------------
# dygraph optimizer updates
# ---------------------------------------------------------------------------
_EAGER_ACCS = {
    "sgd": [],
    "momentum": [("Velocity", "VelocityOut", 0.0)],
    "lars_momentum": [("Velocity", "VelocityOut", 0.0)],
    "adagrad": [("Moment", "MomentOut", 0.0)],
    "decayed_adagrad": [("Moment", "MomentOut", 0.0)],
    "adadelta": [
        ("AvgSquaredGrad", "AvgSquaredGradOut", 0.0),
        ("AvgSquaredUpdate", "AvgSquaredUpdateOut", 0.0),
    ],
    "adam": [
        ("Moment1", "Moment1Out", 0.0),
        ("Moment2", "Moment2Out", 0.0),
        ("Beta1Pow", "Beta1PowOut", "beta1"),
        ("Beta2Pow", "Beta2PowOut", "beta2"),
    ],
    "lamb": [
        ("Moment1", "Moment1Out", 0.0),
        ("Moment2", "Moment2Out", 0.0),
        ("Beta1Pow", "Beta1PowOut", "beta1"),
        ("Beta2Pow", "Beta2PowOut", "beta2"),
    ],
    "adamax": [
        ("Moment", "MomentOut", 0.0),
        ("InfNorm", "InfNormOut", 0.0),
        ("Beta1Pow", None, "beta1"),
    ],
    "rmsprop": [
        ("Moment", "MomentOut", 0.0),
        ("MeanSquare", "MeanSquareOut", 0.0),
        ("MeanGrad", "MeanGradOut", 0.0),
    ],
    "ftrl": [
        ("SquaredAccumulator", "SquaredAccumOut", 0.0),
        ("LinearAccumulator", "LinearAccumOut", 0.0),
    ],
}


def _opt_attrs(opt):
    m = {}
    for k, v in opt.__dict__.items():
        if k.startswith("_") and isinstance(v, (int, float, bool)):
            m[k.lstrip("_")] = v
    # common renames
    ren = {
        "momentum": "mu",
        "rho": "decay" if opt.type == "rmsprop" else "rho",
        "weight_decay": "weight_decay",
    }
    attrs = {}
    for k, v in m.items():
        attrs[ren.get(k, k)] = v
    if opt.type in ("momentum", "lars_momentum") and "momentum" in m:
        attrs["mu"] = m["momentum"]
    if opt.type == "rmsprop" and "rho" in m:
        attrs["decay"] = m["rho"]
    if opt.type == "lamb":
        attrs["weight_decay"] = getattr(opt, "_weight_decay", 0.01)
    return attrs


def dygraph_minimize(opt, loss, parameter_list=None, no_grad_set=None,
                     grad_clip=None):
    """Apply optimizer updates eagerly using param.grad (populated by
    loss.backward())."""
    from ...ops.registry import LowerContext, get_lowering

    params = parameter_list
    if params is None:
        params = _default_param_registry()
    if no_grad_set:
        skip = {
            getattr(v, "name", v) for v in no_grad_set
        }
        params = [p for p in params if p.name not in skip]
    if not params:
        raise ValueError(
            "dygraph minimize: pass parameter_list=model.parameters()"
        )
    if not hasattr(opt, "_eager_state"):
        opt._eager_state = {}
    lr = opt._learning_rate
    if hasattr(lr, "step"):  # LearningRateDecay object
        lr_val = lr.step()
    else:
        lr_val = float(lr)
    lowering = get_lowering(opt.type)
    spec = _EAGER_ACCS.get(opt.type)
    if spec is None:
        raise NotImplementedError(
            "optimizer %s not supported in dygraph mode" % opt.type
        )
    attrs = _opt_attrs(opt)
    if grad_clip is not None:
        from ..dygraph_grad_clip import GradClipBase

        if not isinstance(grad_clip, GradClipBase):
            raise TypeError(
                "grad_clip must be a dygraph_grad_clip.GradClipBase "
                "(GradClipByValue/GradClipByNorm/GradClipByGlobalNorm), "
                "got %r" % (grad_clip,)
            )
        live = [p for p in params if p.grad is not None and p.trainable]
        clipped = grad_clip([(p, p.grad) for p in live])
        for p, g in clipped:
            p.grad = g
    for p in params:
        if p.grad is None or not p.trainable:
            continue
        state = opt._eager_state.setdefault(p.name, {})
        ins = {
            "Param": [p.value],
            "Grad": [jnp.asarray(p.grad, p.value.dtype)],
            "LearningRate": [jnp.asarray(lr_val, jnp.float32)],
        }
        for slot, out_slot, fill in spec:
            if slot not in state:
                if isinstance(fill, str):
                    state[slot] = jnp.asarray(attrs.get(fill, 0.9), jnp.float32)
                else:
                    state[slot] = jnp.zeros_like(p.value) + fill
            ins[slot] = [state[slot]]
        ctx = LowerContext(rng=tr._next_eager_rng())
        outs = lowering(ctx, ins, attrs)
        p.value = outs["ParamOut"][0]
        for slot, out_slot, _ in spec:
            if out_slot and out_slot in outs:
                state[slot] = outs[out_slot][0]
            elif out_slot is None and opt.type == "adamax":
                state[slot] = state[slot] * attrs.get("beta1", 0.9)
    return None, [(p, p.grad) for p in params]


_param_registry = []


def _register_param(p):
    _param_registry.append(p)


def _default_param_registry():
    return [p for p in _param_registry if p.trainable]
