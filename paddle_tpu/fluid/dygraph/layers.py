"""dygraph Layer base class (ref: python/paddle/fluid/dygraph/layers.py)."""
import collections

import numpy as np

from .. import core, unique_name
from ..param_attr import ParamAttr
from . import base as dybase
from . import tracer as tr
from .tracer import VarBase

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        if name_scope is None:
            name_scope = self.__class__.__name__.lower()
        self._full_name = unique_name.generate(name_scope)
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._helper_once = None
        self.training = True

    def full_name(self):
        return self._full_name

    # -- modes -----------------------------------------------------------
    def train(self):
        self.training = True
        tr.set_train_mode(True)
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        tr.set_train_mode(False)
        for l in self.sublayers():
            l.training = False
        return self

    # -- parameters ------------------------------------------------------
    def create_parameter(self, attr=None, shape=None, dtype="float32",
                         is_bias=False, default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if default_initializer is not None:
            attr._set_default_initializer(default_initializer)
        elif is_bias:
            attr._set_default_bias_initializer()
        else:
            attr._set_default_param_initializer()
        if attr.name is None:
            attr.name = unique_name.generate(
                ".".join([self._full_name, "b" if is_bias else "w"])
            )
        p = dybase.create_eager_parameter(attr, shape, dtype)
        dybase._register_param(p)
        return p

    def create_variable(self, name=None, persistable=None, dtype=None):
        v = VarBase(
            None,
            name=name or unique_name.generate(self._full_name + ".var"),
            persistable=bool(persistable),
        )
        v._dtype_hint = dtype or "float32"
        return v

    def parameters(self, include_sublayers=True):
        ret = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                ret.extend(l.parameters())
        return ret

    def named_parameters(self, prefix="", include_sublayers=True):
        for name, p in self._parameters.items():
            yield (prefix + ("." if prefix else "") + name, p)
        if include_sublayers:
            for lname, l in self._sub_layers.items():
                yield from l.named_parameters(
                    prefix + ("." if prefix else "") + lname
                )

    def sublayers(self, include_sublayers=True):
        ret = []
        for l in self._sub_layers.values():
            ret.append(l)
            if include_sublayers:
                ret.extend(l.sublayers())
        return ret

    def named_sublayers(self, prefix="", include_sublayers=True,
                        include_self=False):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            p = prefix + ("." if prefix else "") + name
            yield p, l
            if include_sublayers:
                yield from l.named_sublayers(p)

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- state dict ------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(
                include_sublayers=include_sublayers):
            if p is not None:
                dest[structured_name_prefix + name] = p
        return dest

    def set_dict(self, stat_dict, include_sublayers=True):
        named = dict(
            self.named_parameters(include_sublayers=include_sublayers))
        by_varname = {p.name: p for _, p in named.items()}
        for k, v in stat_dict.items():
            target = named.get(k) or by_varname.get(k)
            if target is None:
                continue
            arr = v.numpy() if hasattr(v, "numpy") else np.asarray(v)
            target.set_value(arr)

    load_dict = set_dict

    # -- call ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        return self.forward(*inputs, **kwargs)

    # -- attribute auto-registration -------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, VarBase) and value.persistable:
            if params is not None:
                params[name] = value
        elif isinstance(value, Layer):
            if layers is not None:
                layers[name] = value
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        params = self.__dict__.get("_parameters")
        if params is not None and name in params:
            return params[name]
        layers = self.__dict__.get("_sub_layers")
        if layers is not None and name in layers:
            return layers[name]
        raise AttributeError(
            "%s has no attribute %s" % (type(self).__name__, name)
        )
