"""TracedLayer (ref: python/paddle/fluid/dygraph/jit.py).

TPU-native: tracing a dygraph Layer produces a jax.jit-compiled callable —
the eager tape is bypassed entirely and XLA compiles the whole forward.
"""
import numpy as np

import jax
import jax.numpy as jnp

from . import base as dybase
from . import tracer as tr
from .tracer import VarBase

__all__ = ["TracedLayer", "trace"]


class TracedLayer:
    def __init__(self, layer, feed_vars):
        self._layer = layer
        self._params = {p.name: p for p in layer.parameters()}

        def pure_fn(param_vals, in_vals):
            # temporarily bind param values, run eager forward w/o tape
            old = {n: p.value for n, p in self._params.items()}
            for n, p in self._params.items():
                p.value = param_vals[n]
            prev_enabled = tr._tracer.enabled
            tr._tracer.enabled = False
            try:
                outs = layer(*[VarBase(v, stop_gradient=True) for v in in_vals])
            finally:
                tr._tracer.enabled = prev_enabled
                for n, p in self._params.items():
                    p.value = old[n]
            if isinstance(outs, (list, tuple)):
                return tuple(o.value for o in outs)
            return (outs.value,)

        self._jitted = jax.jit(pure_fn)

    def __call__(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        in_vals = [
            v.value if isinstance(v, VarBase) else jnp.asarray(v)
            for v in inputs
        ]
        pv = {n: p.value for n, p in self._params.items()}
        outs = self._jitted(pv, in_vals)
        return [VarBase(o, stop_gradient=True) for o in outs]

    @staticmethod
    def trace(layer, inputs):
        traced = TracedLayer(layer, inputs)
        outs = traced(inputs)
        return outs, traced

    def save_inference_model(self, dirname, feed=None, fetch=None):
        """Persist the traced layer's weights. feed/fetch subset selection
        (ref jit.py save_inference_model) is not supported on the eager
        trace path — re-trace a wrapper layer exposing only the wanted
        inputs/outputs instead."""
        if feed is not None or fetch is not None:
            raise NotImplementedError(
                "TracedLayer.save_inference_model: feed/fetch subset "
                "selection is not supported; trace a wrapper Layer that "
                "takes/returns exactly the tensors you want saved"
            )
        from ..dygraph.checkpoint import save_dygraph

        save_dygraph(self._layer.state_dict(), dirname + "/model")


def trace(layer, inputs):
    return TracedLayer.trace(layer, inputs)
