"""ref import path dygraph/profiler.py — re-exports the profiler
surface (one jax.profiler wrapper serves both modes)."""
from ..profiler import profiler, start_profiler, stop_profiler  # noqa: F401

__all__ = ["start_profiler", "stop_profiler", "profiler"]
