"""Trainer descriptors (ref: python/paddle/fluid/trainer_desc.py).

The reference assembles TrainerDesc protobufs for the C++ trainer
runtime; here a desc is a plain dict consumed by
Executor.train_from_dataset (see trainer_factory.py). The class split is
kept so fleet-style code that selects a trainer by name works:
MultiTrainer (single-machine Hogwild contract), DistMultiTrainer
(collective fleet), PipelineTrainer (parallel/pipeline.py gpipe).
"""

__all__ = ["TrainerDesc", "MultiTrainer", "DistMultiTrainer",
           "PipelineTrainer"]


class TrainerDesc:
    def __init__(self):
        self.proto_desc = {"thread_num": 1, "fetch_config": {}}
        self._program = None
        self._device_worker = None
        self._infer = False

    def _set_fetch_var_and_info(self, fetch_vars, fetch_info, print_period):
        self.proto_desc["fetch_config"] = {
            "fetch_var_names": [
                getattr(v, "name", v) for v in fetch_vars or []],
            "fetch_var_str_format": list(fetch_info or []),
            "print_period": int(print_period),
        }

    def _set_debug(self, debug):
        self.proto_desc["debug"] = bool(debug)

    def _set_thread(self, thread_num):
        self.proto_desc["thread_num"] = int(thread_num)

    def _set_device_worker(self, device_worker):
        self._device_worker = device_worker
        if device_worker is not None:
            device_worker._gen_worker_desc(self.proto_desc)

    def _set_infer(self, infer):
        self._infer = bool(infer)
        if self._device_worker is not None:
            self._device_worker._set_infer(infer)

    def _set_program(self, program):
        self._program = program

    def _desc(self):
        return dict(self.proto_desc)


class MultiTrainer(TrainerDesc):
    def __init__(self):
        super().__init__()
        self.proto_desc["class_name"] = "MultiTrainer"


class DistMultiTrainer(TrainerDesc):
    def __init__(self):
        super().__init__()
        self.proto_desc["class_name"] = "DistMultiTrainer"


class PipelineTrainer(TrainerDesc):
    def __init__(self):
        super().__init__()
        self.proto_desc["class_name"] = "PipelineTrainer"
