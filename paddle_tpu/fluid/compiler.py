"""CompiledProgram: data-parallel compilation
(ref: python/paddle/fluid/compiler.py + framework/parallel_executor.cc).

TPU-native redesign: the reference builds one SSA graph per GPU and
all-reduces gradients over NCCL. Here the SAME lowered step function is
jitted with jax shardings over a device Mesh: feeds are sharded on the batch
axis, state is replicated, and XLA inserts the ICI all-reduces for the vjp
gradients automatically. One executable, N chips.
"""
import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import core
from .framework import Variable
from .lowering import build_step_fn

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy"]


class BuildStrategy:
    """Accepted for API parity; the XLA partitioner replaces the reference's
    graph-pass knobs."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = (
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        )
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_reduce_ops = True
        self.fuse_all_optimizer_ops = False
        self.fuse_elewise_add_act_ops = False
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = True


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._is_data_parallel = False
        self._loss_name = None
        self._places = None
        self._share_vars_from = None
        self._mesh = None
        self._cache = {}

    def with_data_parallel(
        self,
        loss_name=None,
        build_strategy=None,
        exec_strategy=None,
        share_vars_from=None,
        places=None,
    ):
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._share_vars_from = share_vars_from
        self._places = places
        return self

    def _get_mesh(self, place):
        if self._mesh is not None:
            return self._mesh
        if self._places is not None and len(self._places):
            devs = [p.jax_device() if hasattr(p, "jax_device") else p
                    for p in self._places]
        else:
            backend = getattr(place, "_backend", None)
            try:
                devs = jax.devices(backend) if backend else jax.devices()
            except RuntimeError:
                devs = jax.devices()
        self._mesh = Mesh(np.array(devs), axis_names=("dp",))
        return self._mesh

    # called by Executor.run when program is a CompiledProgram
    def _executor_run(self, executor, feed, fetch_list, scope, return_numpy):
        from .executor import global_scope

        program = self._program
        scope = scope if scope is not None else global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []
        fetch_names = [
            f.name if isinstance(f, Variable) else f for f in fetch_list
        ]
        if not self._is_data_parallel:
            return executor.run(
                program, feed, fetch_list, scope=scope,
                return_numpy=return_numpy,
            )

        mesh = self._get_mesh(executor.place)
        ndev = mesh.devices.size
        repl = NamedSharding(mesh, P())
        batch_shard = NamedSharding(mesh, P("dp"))
        block = program.global_block()
        feed_arrays = {}
        for name, value in (feed or {}).items():
            value = getattr(value, "_ndarray", value)
            arr = np.asarray(value)
            if block.has_var(name) and block.var(name).dtype is not None:
                want = core.np_dtype(block.var(name).dtype)
                if arr.dtype != want:
                    arr = arr.astype(want)
            if arr.shape and arr.shape[0] % ndev == 0:
                feed_arrays[name] = jax.device_put(arr, batch_shard)
            else:
                feed_arrays[name] = jax.device_put(arr, repl)
        state = {
            k: (v if hasattr(v, "sharding")
                and getattr(v.sharding, "mesh", None) is mesh
                else jax.device_put(np.asarray(v), repl))
            for k, v in executor._gather_state(program, scope).items()
        }

        sig = (
            program._uid, program._version,
            tuple(sorted((k, v.shape, str(v.dtype))
                         for k, v in feed_arrays.items())),
            tuple(fetch_names), ndev,
            tuple(sorted((k, v.shape, str(v.dtype))
                         for k, v in state.items())),
        )
        entry = self._cache.get(sig)
        if entry is None:
            step = build_step_fn(program, list(feed_arrays), fetch_names)
            # shardings are carried by the committed input arrays (feeds
            # batch-sharded over 'dp', state replicated); XLA partitions the
            # whole step and inserts the ICI collectives for the vjp grads
            entry = jax.jit(step, donate_argnums=(0,))
            self._cache[sig] = entry

        rng = jax.device_put(executor._next_rng(program), repl)
        fetches, new_state = entry(state, feed_arrays, rng)
        for k, v in new_state.items():
            scope.set(k, v)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)


CompiledProgram.with_inference_optimize = lambda self, config: self
