"""ref import path python/paddle/fluid/inferencer.py (the reference file
is a tombstone pointing at contrib); the working Inferencer lives in
fluid.contrib.inferencer."""
__all__ = []
