"""ref transpiler/memory_optimization_transpiler.py import path; the
implementations live in the package __init__ (XLA buffer assignment
subsumes the pass — see memory_optimize's docstring)."""
from . import memory_optimize, release_memory  # noqa: F401

__all__ = ["memory_optimize", "release_memory"]
