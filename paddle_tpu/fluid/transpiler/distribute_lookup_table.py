"""Distributed lookup-table discovery
(ref: python/paddle/fluid/transpiler/details/distribute_lookup_table.py).

Finds the embedding table marked ``is_distributed`` in a program — the
table the pserver runtime would shard. The TPU pipeline uses the same
discovery to pick which table gets vocab-dim sharding over the mesh
(see parallel/sharding.py rules).
"""

__all__ = ["find_distributed_lookup_table"]

LOOKUP_TABLE_TYPES = ("lookup_table", "lookup_table_v2")


def find_distributed_lookup_table(program):
    """Return the single distributed lookup table's param name, or None.
    Multiple distinct distributed tables raise, like the reference."""
    table_name = None
    for op in program.global_block().ops:
        if op.type not in LOOKUP_TABLE_TYPES:
            continue
        if not op.attrs.get("is_distributed", False):
            continue
        name = op.input("W")[0]
        if table_name is None:
            table_name = name
        elif table_name != name:
            raise RuntimeError(
                "all distributed lookup_table ops must share one table; "
                "found %r and %r" % (table_name, name)
            )
    return table_name
