"""Collective transpilers (ref: fluid/transpiler/collective.py:1-380 —
Collective base, GradAllReduce, LocalSGD, SingleProcessMultiThread).

The reference rewrites the program with NCCL init + c_allreduce ops on
`nrings` comm rings. TPU mapping: ``transpile`` attaches a mesh runner
to the MAIN program — GradAllReduce becomes GSPMD dp (batch sharded,
grads averaged by construction; XLA fuses/schedules the all-reduces,
so `nrings` is a no-op knob recorded for parity), LocalSGD becomes the
per-shard-state shard_map program (parallel/local_sgd.py) averaging
params every ``k_steps``. After transpile, ``exe.run(main_program)``
executes the sharded step — same call sites as the reference flow.

Single-process view: endpoints/rank describe the reference's
process-per-GPU world; here one process drives all local devices, so
the endpoint list's LENGTH (world size) must match the visible device
count and `rank`/`current_endpoint` are validated for parity.
"""
import jax

__all__ = ["Collective", "GradAllReduce", "LocalSGD",
           "SingleProcessMultiThread"]


class Collective:
    """Base transpiler (ref collective.py:36)."""

    mode = None

    def __init__(self, nrings=2):
        self.nrings = nrings  # parity: XLA owns collective scheduling
        self.nranks = 0
        self.rank = 0
        self.startup_program = None
        self.main_program = None

    def transpile(self, startup_program, main_program, rank, endpoints,
                  current_endpoint, wait_port=True):
        from ..framework import (
            default_main_program, default_startup_program)

        if main_program is None:
            main_program = default_main_program()
        if startup_program is None:
            startup_program = default_startup_program()
        if isinstance(endpoints, str):
            endpoints = endpoints.split(",")
        self.nranks = len(endpoints)
        self.rank = int(rank)
        if not (0 <= self.rank < self.nranks):
            raise ValueError("rank %d not in [0, %d)" %
                             (self.rank, self.nranks))
        if current_endpoint not in endpoints:
            raise ValueError("current_endpoint %r not in endpoints" %
                             (current_endpoint,))
        ndev = len(jax.devices())
        if self.nranks > ndev:
            raise ValueError(
                "collective transpile for %d ranks but only %d devices "
                "visible — one process drives the whole mesh here, so "
                "the endpoint list must not exceed the device count"
                % (self.nranks, ndev))
        # nranks < ndev is a valid rank subset: _attach builds the mesh
        # over the first nranks devices (devices=jax.devices()[:nranks])
        self.startup_program = startup_program
        self.main_program = main_program
        self._attach(main_program)
        return main_program

    def _attach(self, main_program):
        raise NotImplementedError


class GradAllReduce(Collective):
    """Synchronous dp (ref collective.py:180): batch sharded over a dp
    mesh; gradient averaging is implicit in GSPMD (the loss reduces
    over the global batch)."""

    mode = "grad_allreduce"

    def _attach(self, main_program):
        from ...parallel.mesh import build_mesh
        from ...parallel.sharding import DistributedProgram

        mesh = build_mesh({"dp": self.nranks},
                          devices=jax.devices()[:self.nranks])
        main_program._transpiled_dist = DistributedProgram(
            main_program, mesh, feed_axis="dp")


class LocalSGD(Collective):
    """k-step local updates + param averaging (ref collective.py:270).
    The reference averages every step (snapshot + allreduce); pass
    ``k_steps`` to widen the interval (the fleet strategy knob)."""

    mode = "local_sgd"

    def __init__(self, nrings=2, k_steps=1):
        super().__init__(nrings)
        self.snapshot_key = "@SNAPSHOT"  # parity: no snapshots needed
        self.k_steps = int(k_steps)

    def snapshot_name(self, param_name):
        return param_name + self.snapshot_key

    def _attach(self, main_program):
        from ...parallel.local_sgd import LocalSGDProgram
        from ...parallel.mesh import build_mesh

        mesh = build_mesh({"dp": self.nranks},
                          devices=jax.devices()[:self.nranks])
        main_program._transpiled_dist = LocalSGDProgram(
            main_program, mesh, k_steps=self.k_steps)


class SingleProcessMultiThread(GradAllReduce):
    """ref collective.py:374 — single-node all-device dp."""

    def __init__(self):
        super().__init__(nrings=1)

    def transpile(self, startup_program=None, main_program=None,
                  rank=0, endpoints=None, current_endpoint=None,
                  wait_port=True):
        ndev = len(jax.devices())
        endpoints = endpoints or ["127.0.0.1:%d" % (6170 + i)
                                  for i in range(ndev)]
        return super().transpile(
            startup_program, main_program, rank, endpoints,
            current_endpoint or endpoints[rank], wait_port)
