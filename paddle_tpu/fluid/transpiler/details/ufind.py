"""Union-find (ref: fluid/transpiler/details/ufind.py:18 — used by the
memory-optimization transpiler to group aliasable vars)."""

__all__ = ["UnionFind"]


class UnionFind(object):
    """Path-compressing union-find over arbitrary hashable elements."""

    def __init__(self, elementes=None):
        self._parents = []
        self._index = {}
        self._curr_idx = 0
        for ele in elementes or []:
            self._parents.append(self._curr_idx)
            self._index[ele] = self._curr_idx
            self._curr_idx += 1

    def find(self, x):
        curr_idx = self._index[x]
        while curr_idx != self._parents[curr_idx]:
            self._parents[curr_idx] = self._parents[
                self._parents[curr_idx]]
            curr_idx = self._parents[curr_idx]
        return curr_idx

    def union(self, x, y):
        x_root = self.find(x)
        y_root = self.find(y)
        if x_root != y_root:
            self._parents[x_root] = y_root

    def is_connected(self, x, y):
        return self.find(x) == self.find(y)
