"""Distributed-var introspection registry
(ref: fluid/transpiler/details/vars_distributed.py:18-280).

The reference records how each parameter is sliced across pserver
endpoints. In this framework the DistributeTranspiler maps pserver
slices onto mesh shardings (fluid/transpiler), but the registry survives
unchanged as introspection surface: transpiler users iterate it to see
origin/slice relationships, vtype tags, and per-"endpoint" placement
(endpoint here is the mesh-shard label the transpiler assigns).
"""
from ...framework import Variable

__all__ = ["VarStruct", "VarDistributed", "VarsDistributed"]


class VarStruct(object):
    """Plain-data mirror of a Variable's metadata (ref :18)."""

    def __init__(self, name, shape, dtype, type, lod_level, persistable):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.type = type
        self.lod_level = lod_level
        self.persistable = persistable

    @classmethod
    def from_var(cls, var):
        return cls(var.name, var.shape, var.dtype,
                   getattr(var, "type", None),
                   getattr(var, "lod_level", 0),
                   getattr(var, "persistable", False))


class VarDistributed(object):
    """origin-var <-> slice-var relationship record (ref :32)."""

    def __init__(self, origin_var, slice_var, is_slice=None, block_id=None,
                 offset=None, vtype=None, endpoint=None):
        self.origin = (VarStruct.from_var(origin_var)
                       if isinstance(origin_var, Variable) else origin_var)
        self.slice = (VarStruct.from_var(slice_var)
                      if isinstance(slice_var, Variable) else slice_var)
        same = self.equal(self.origin, self.slice)
        self.is_slice = (not same) if is_slice is None else is_slice
        self.block_id = 0 if block_id is None else block_id
        self.offset = 0 if offset is None else offset
        self.vtype = vtype
        self.endpoint = endpoint

    @staticmethod
    def equal(var1, var2):
        assert isinstance(var1, VarStruct) and isinstance(var2, VarStruct)
        return (var1.name == var2.name and var1.type == var2.type
                and var1.shape == var2.shape and var1.dtype == var2.dtype
                and var1.lod_level == var2.lod_level
                and var1.persistable == var2.persistable)

    def __str__(self):
        origin = "%s : fluid.%s.shape%s.astype(%s)" % (
            self.origin.name, self.origin.type, self.origin.shape,
            self.origin.dtype)
        sliced = ("%s : fluid.%s.shape%s.astype(%s)"
                  ".slice(%s).block(%s).offset(%s)" % (
                      self.slice.name, self.slice.type, self.slice.shape,
                      self.slice.dtype, self.is_slice, self.block_id,
                      self.offset))
        return ("var owned: %s, origin var: ( %s ), slice var: ( %s ), "
                "endpoint: %s " % (self.vtype, origin, sliced,
                                   self.endpoint))


class VarsDistributed(object):
    """Registry of VarDistributed records (ref :123)."""

    def __init__(self):
        self.distributed_vars = []

    def add_distributed_var(self, origin_var, slice_var, is_slice=None,
                            block_id=None, offset=None, vtype=None,
                            endpoint=None):
        self.distributed_vars.append(VarDistributed(
            origin_var, slice_var, is_slice, block_id, offset, vtype,
            endpoint))

    def get_distributed_var_by_slice(self, var_name):
        for dist_var in self.distributed_vars:
            if dist_var.slice.name == var_name:
                return dist_var
        return None

    @staticmethod
    def equal(var1, var2):
        return (var1.name == var2.name and var1.type == var2.type
                and var1.shape == var2.shape and var1.dtype == var2.dtype
                and var1.lod_level == var2.lod_level
                and var1.persistable == var2.persistable)

    def get_distributed_var_by_origin_and_ep(self, origin_var_name,
                                             endpoint):
        for dist_var in self.distributed_vars:
            if (dist_var.origin.name == origin_var_name
                    and dist_var.endpoint == endpoint):
                return dist_var
        return None

    def get_distributed_vars_by_vtypes(self, vtypes, groupby=False):
        vtype_vars = [v for v in self.distributed_vars
                      if v.vtype in vtypes]
        if not groupby:
            return vtype_vars
        params_map = {}
        for var in vtype_vars:
            params_map.setdefault(var.origin.name, []).append(var)
        return params_map

    def get_distributed_vars_by_ep(self, endpoint, vtype=None):
        endpoint_vars = [v for v in self.distributed_vars
                         if v.endpoint == endpoint]
        if vtype is None:
            return endpoint_vars
        return [v for v in endpoint_vars if v.vtype == vtype]

    def overview(self):
        """Multiline dump of every record (ref :258)."""
        vars_str = [str(var) for var in self.distributed_vars]
        return "\n".join(vars_str)
