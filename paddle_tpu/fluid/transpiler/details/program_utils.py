"""Program introspection / pretty-printing
(ref: fluid/transpiler/details/program_utils.py:23-208).

Same text layout as the reference's program_to_code (vars then ops,
``{Out=...} = op(inputs=...)`` lines) over this framework's dict-based
Operator records, so fluid-era debugging scripts read the same dumps.
"""
import sys

__all__ = [
    "delete_ops", "find_op_by_input_arg", "find_op_by_output_arg",
    "get_indent_space", "variable_to_code", "op_to_code",
    "block_to_code", "program_to_code",
]


def delete_ops(block, ops):
    """Remove ``ops`` from ``block`` (ref program_utils.py:23)."""
    drop = {id(op) for op in ops}
    block.ops = [op for op in block.ops if id(op) not in drop]
    if hasattr(block, "program") and hasattr(block.program,
                                             "_bump_version"):
        block.program._bump_version()


def find_op_by_input_arg(block, arg_name):
    """Index of the first op consuming ``arg_name`` (ref :32)."""
    for index, op in enumerate(block.ops):
        if arg_name in op.input_arg_names:
            return index
    return -1


def find_op_by_output_arg(block, arg_name, reverse=False):
    """Index of the op producing ``arg_name`` (ref :39)."""
    ops = list(enumerate(block.ops))
    if reverse:
        ops = reversed(ops)
    for index, op in ops:
        if arg_name in op.output_arg_names:
            return index
    return -1


def get_indent_space(indent, space_num=4):
    return " " * indent * space_num


def variable_to_code(var):
    """One-line var summary (ref :62)."""
    if getattr(var, "persistable", False):
        prefix = "persist "
    else:
        prefix = ""
    return "%svar %s : shape(%s) dtype(%s)%s" % (
        prefix, var.name,
        ", ".join(str(s) for s in (var.shape or ())),
        var.dtype,
        " stop_gradient" if getattr(var, "stop_gradient", False) else "",
    )


def op_to_code(op, skip_op_callstack=True):
    """One-line op summary (ref :93)."""
    outs = ", ".join(
        "%s=[%s]" % (slot, ", ".join(names))
        for slot, names in sorted(op.outputs.items())
    )
    ins = ", ".join(
        "%s=[%s]" % (slot, ", ".join(names))
        for slot, names in sorted(op.inputs.items())
    )
    attrs = ", ".join(
        "%s=%r" % (k, v) for k, v in sorted(op.attrs.items())
        if k != "op_callstack"
    )
    text = "{%s} = %s(inputs={%s}%s)" % (
        outs, op.type, ins, (", " + attrs) if attrs else "")
    if not skip_op_callstack and getattr(op, "callstack", None):
        stack = "".join(
            "\n    %s:%s %s" % (f.filename, f.lineno, f.line)
            for f in op.callstack)
        text += stack
    return text


def block_to_code(block, block_idx, fout=None, skip_op_callstack=False):
    fout = fout or sys.stdout
    indent = 0
    print("%s{ // block %d" % (get_indent_space(indent), block_idx),
          file=fout)
    indent += 1
    for var in block.vars.values():
        print(get_indent_space(indent) + variable_to_code(var), file=fout)
    for op in block.ops:
        print(get_indent_space(indent)
              + op_to_code(op, skip_op_callstack), file=fout)
    indent -= 1
    print("%s}" % get_indent_space(indent), file=fout)


def program_to_code(prog, fout=None, skip_op_callstack=True):
    """Dump a whole Program as pseudo-code (ref :190)."""
    for block_idx, block in enumerate(prog.blocks):
        block_to_code(block, block_idx, fout, skip_op_callstack)
