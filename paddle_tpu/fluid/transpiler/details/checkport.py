"""wait_server_ready (ref: fluid/transpiler/details/checkport.py:22).

Generic TCP readiness wait — on TPU there are no pservers, but the same
helper is useful for multi-host coordinator startup (jax.distributed
coordinator address), so it is implemented for real rather than
stubbed."""
import socket
import sys
import time

__all__ = ["wait_server_ready"]


def wait_server_ready(endpoints):
    """Block until every "ip:port" endpoint accepts a TCP connection."""
    assert not isinstance(endpoints, str)
    while True:
        all_ok = True
        not_ready = []
        for ep in endpoints:
            ip_port = ep.split(":")
            with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as sock:
                sock.settimeout(2)
                result = sock.connect_ex((ip_port[0], int(ip_port[1])))
                if result != 0:
                    all_ok = False
                    not_ready.append(ep)
        if not all_ok:
            sys.stderr.write("server not ready, wait 3 sec to retry...\n")
            sys.stderr.write("not ready endpoints:" + str(not_ready) + "\n")
            sys.stderr.flush()
            time.sleep(3)
        else:
            break
