"""transpiler.details (ref: fluid/transpiler/details/__init__.py) —
program introspection helpers fluid-era transpiler users import."""
from .program_utils import (  # noqa: F401
    delete_ops,
    find_op_by_input_arg,
    find_op_by_output_arg,
    program_to_code,
    block_to_code,
    op_to_code,
    variable_to_code,
)
from .ufind import UnionFind  # noqa: F401
from .checkport import wait_server_ready  # noqa: F401
from .vars_distributed import (  # noqa: F401
    VarStruct,
    VarDistributed,
    VarsDistributed,
)
