"""ref import path fluid/transpiler/geo_sgd_transpiler.py — GeoSgd runs
as the synchronous special case (see package __init__: ICI beats delta
staging)."""
from . import GeoSgdTranspiler  # noqa: F401

__all__ = ["GeoSgdTranspiler"]
