"""ref import path fluid/transpiler/distribute_transpiler.py — the
implementation lives in the package __init__ (pserver->sharded-
embedding mapping documented there)."""
from . import (  # noqa: F401
    DistributeTranspiler,
    DistributeTranspilerConfig,
)
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin  # noqa: F401

__all__ = ["DistributeTranspiler", "DistributeTranspilerConfig",
           "HashName", "RoundRobin"]
