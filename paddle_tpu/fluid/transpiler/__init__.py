"""Distribute/memory transpilers
(ref: python/paddle/fluid/transpiler/distribute_transpiler.py,
memory_optimization_transpiler.py, collective.py).

API-compatible surface with TPU-native semantics:

- DistributeTranspiler(pserver mode): there are no parameter servers on a
  TPU pod — the role the pserver shards played (holding slices of big
  embeddings + applying async updates) maps to vocab-sharded parameters
  over the mesh with synchronous ICI all-reduce. transpile() therefore
  annotates the program with sharding rules instead of splitting it into
  trainer/pserver programs; get_trainer_program() returns the annotated
  program, get_pserver_program() raises with this explanation.
- memory_optimize/release_memory: XLA's buffer assignment + donated
  arguments already reuse buffers aggressively; these are no-ops kept for
  script compatibility (they print a note once).
"""
import warnings

from .. import framework
from . import ps_dispatcher
from . import details  # noqa: F401
from .ps_dispatcher import HashName, PSDispatcher, RoundRobin  # noqa: F401
from . import distribute_lookup_table
from .distribute_lookup_table import (  # noqa: F401
    find_distributed_lookup_table,
)

__all__ = [
    "DistributeTranspiler",
    "DistributeTranspilerConfig",
    "GeoSgdTranspiler",
    "memory_optimize",
    "release_memory",
    "HashName",
    "RoundRobin",
]


class DistributeTranspilerConfig:
    slice_var_up = True
    split_method = None
    min_block_size = 8192
    enable_dc_asgd = False
    mode = "collective"
    print_log = False
    wait_port = True
    sync_mode = True


class DistributeTranspiler:
    """ref transpiler/distribute_transpiler.py DistributeTranspiler."""

    def __init__(self, config=None):
        self.config = config or DistributeTranspilerConfig()
        self._program = None
        self._trainer_id = 0
        self._trainers = 1

    def transpile(
        self,
        trainer_id,
        program=None,
        pservers="127.0.0.1:6174",
        trainers=1,
        sync_mode=True,
        startup_program=None,
        current_endpoint="127.0.0.1:6174",
    ):
        self._trainer_id = trainer_id
        self._trainers = trainers
        self._program = program or framework.default_main_program()
        # annotate: data-parallel over 'dp', embeddings vocab-sharded over
        # 'tp' if a tp axis exists (DistributedProgram applies the rules)
        from jax.sharding import PartitionSpec as P

        rules = []
        for p in self._program.all_parameters():
            if getattr(p, "is_distributed", False) or (
                p.shape and len(p.shape) == 2 and p.shape[0] >= 8192
            ):
                rules.append((p.name, P("tp", None)))
        self._program._sharding_spec = rules
        return self

    def get_trainer_program(self, wait_port=True):
        if self._program is not None and self._program._sharding_spec:
            # hand back a runnable mesh-sharded program so the annotation
            # is actually consumed (Executor dispatches through it)
            import jax

            from ..parallel.mesh import build_mesh
            from ..parallel.sharding import DistributedProgram

            try:
                ndev = len(jax.devices())
            except RuntimeError:
                ndev = 1
            tp = 2 if ndev % 2 == 0 and ndev > 1 else 1
            mesh = build_mesh({"dp": ndev // tp, "tp": tp})
            return DistributedProgram(self._program, mesh)
        return self._program

    def get_pserver_program(self, endpoint):
        raise NotImplementedError(
            "TPU pods have no parameter servers: the pserver shard role is "
            "replaced by vocab-sharded parameters over the ICI mesh "
            "(rules annotated on the program; run it through "
            "parallel.sharding.DistributedProgram or fleet)"
        )

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint, pserver_program=None,
                            startup_program=None):
        return framework.default_startup_program()


class GeoSgdTranspiler(DistributeTranspiler):
    """ref transpiler/geo_sgd_transpiler.py GeoSgdTranspiler.

    Geo-SGD runs trainers asynchronously for ``sync_steps`` local updates,
    then ships parameter DELTAS to pservers — a bandwidth optimization for
    slow commodity links. On a TPU mesh the premise inverts: ICI makes the
    per-step synchronous all-reduce (inserted by XLA inside the one
    compiled module) faster than any delta-staging scheme, and there are
    no pservers to stage through. This transpiler therefore keeps the
    geo-SGD API (construction args, transpile, trainer program, the
    sparse/dense update split) but executes as synchronous data-parallel:
    the mathematically stronger special case (deltas exchanged every
    step). The dist lookup-table path maps to vocab-sharded embeddings
    over 'tp' exactly like DistributeTranspiler, whose transpile/
    get_trainer_program this class inherits unchanged (sync_mode is
    already immaterial there)."""


_mem_note = [False]


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0, skip_grads=True):
    if not _mem_note[0]:
        _mem_note[0] = True
        warnings.warn(
            "memory_optimize is a no-op: XLA buffer assignment + donated "
            "arguments already provide in-place reuse; use "
            "fluid.optimizer.RecomputeOptimizer for rematerialisation",
            stacklevel=2,
        )
    return input_program


def release_memory(input_program, skip_opt_set=None):
    return input_program
