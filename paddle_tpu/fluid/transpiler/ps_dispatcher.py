"""Parameter-server variable dispatchers
(ref: python/paddle/fluid/transpiler/ps_dispatcher.py).

On TPU the pserver role maps to mesh-sharded parameters (see the package
docstring), but the dispatch POLICY objects stay useful: the transpiler
uses them to assign vars to logical shards, and reference scripts
construct them directly. Semantics match the reference: HashName is a
stable content hash (every process must agree), RoundRobin cycles.
"""
import zlib

__all__ = ["PSDispatcher", "HashName", "RoundRobin"]


class PSDispatcher:
    """ref ps_dispatcher.py:18."""

    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError("use HashName or RoundRobin")


class HashName(PSDispatcher):
    """Stable digest placement — NOT builtin hash(): trainers and
    restarts must agree on var -> endpoint (ref ps_dispatcher.py:49)."""

    def _hash_block(self, block_str, total):
        return zlib.crc32(str(block_str).encode()) % total

    def dispatch(self, varlist):
        return [
            self._eps[self._hash_block(v.name, len(self._eps))]
            for v in varlist
        ]


class RoundRobin(PSDispatcher):
    """Cycle endpoints in order (ref ps_dispatcher.py:89)."""

    def dispatch(self, varlist):
        out = []
        for _ in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out
