"""Learning-rate schedulers (ref: python/paddle/fluid/layers/
learning_rate_scheduler.py). Each returns a Variable computed by ops from
the global step counter — the schedule math is traced into the jitted step
(branchless formulations instead of control-flow ops: TPU-friendlier)."""
import math

from .. import unique_name
from ..framework import Variable, default_main_program
from ..initializer import Constant
from ..layer_helper import LayerHelper
from . import nn
from . import ops
from . import tensor

__all__ = [
    "exponential_decay",
    "natural_exp_decay",
    "inverse_time_decay",
    "polynomial_decay",
    "piecewise_decay",
    "noam_decay",
    "cosine_decay",
    "linear_lr_warmup",
]


def _decay_step_counter(begin=0):
    global_step = nn.autoincreased_step_counter(
        counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1
    )
    global_step = tensor.cast(global_step, "float32")
    return global_step


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step*warmup^-1.5) (ref)."""
    global_step = _decay_step_counter(1)
    a = nn.elementwise_pow(
        global_step, tensor.fill_constant([1], "float32", -0.5)
    )
    b = nn.scale(global_step, scale=warmup_steps ** -1.5)
    lr_value = nn.scale(
        nn.elementwise_min(a, b), scale=d_model ** -0.5
    )
    return lr_value


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _decay_step_counter()
    div_res = nn.scale(global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    return nn.scale(
        nn.elementwise_pow(
            tensor.fill_constant([1], "float32", decay_rate), div_res
        ),
        scale=float(learning_rate),
    )


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _decay_step_counter()
    div_res = nn.scale(global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    return nn.scale(
        ops.exp(nn.scale(div_res, scale=-decay_rate)),
        scale=float(learning_rate),
    )


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    global_step = _decay_step_counter()
    div_res = nn.scale(global_step, scale=1.0 / decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    denom = nn.scale(div_res, scale=decay_rate, bias=1.0)
    return nn.elementwise_div(
        tensor.fill_constant([1], "float32", float(learning_rate)), denom
    )


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    global_step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(nn.scale(global_step, scale=1.0 / decay_steps))
        # if step == 0 -> div = 1 (branchless: max(div, 1))
        div_res = nn.elementwise_max(
            div_res, tensor.fill_constant([1], "float32", 1.0)
        )
        decay_steps_var = nn.scale(div_res, scale=float(decay_steps))
        ratio = nn.elementwise_div(global_step, decay_steps_var)
    else:
        capped = nn.elementwise_min(
            global_step,
            tensor.fill_constant([1], "float32", float(decay_steps)),
        )
        ratio = nn.scale(capped, scale=1.0 / decay_steps)
    base = nn.scale(ratio, scale=-1.0, bias=1.0)
    powed = nn.elementwise_pow(
        base, tensor.fill_constant([1], "float32", power)
    )
    return nn.scale(
        powed,
        scale=float(learning_rate) - float(end_learning_rate),
        bias=float(end_learning_rate),
        bias_after_scale=True,
    )


def piecewise_decay(boundaries, values):
    """Branchless piecewise-constant schedule: lr = Σ v_i · 1[b_{i-1} ≤ s < b_i]."""
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    global_step = _decay_step_counter()
    lr = tensor.fill_constant([1], "float32", 0.0)
    prev = None
    for i, v in enumerate(values):
        seg = tensor.fill_constant([1], "float32", float(v))
        if i == 0:
            cond = tensor.cast(
                nn.logical_not(
                    _ge(global_step, boundaries[0])
                ),
                "float32",
            )
        elif i == len(values) - 1:
            cond = tensor.cast(_ge(global_step, boundaries[i - 1]), "float32")
        else:
            cond = tensor.cast(
                nn.logical_and(
                    _ge(global_step, boundaries[i - 1]),
                    nn.logical_not(_ge(global_step, boundaries[i])),
                ),
                "float32",
            )
        lr = nn.elementwise_add(lr, nn.elementwise_mul(seg, cond))
    return lr


def _ge(step_var, bound):
    from .nn import _layer

    b = tensor.fill_constant([1], "float32", float(bound))
    return _layer(
        "greater_equal", {"X": step_var, "Y": b}, out_dtype="bool",
        out_shape=(1,),
    )


def cosine_decay(learning_rate, step_each_epoch, epochs):
    global_step = _decay_step_counter()
    epoch = ops.floor(nn.scale(global_step, scale=1.0 / step_each_epoch))
    frac = nn.scale(epoch, scale=math.pi / epochs)
    cosv = ops.cos(frac)
    return nn.scale(
        nn.scale(cosv, scale=0.5, bias=0.5), scale=float(learning_rate)
    )


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Branchless: lr = warmup ? start + (end-start)*s/W : learning_rate."""
    global_step = _decay_step_counter()
    in_warm = tensor.cast(
        nn.logical_not(_ge(global_step, warmup_steps)), "float32"
    )
    ramp = nn.scale(
        global_step,
        scale=(float(end_lr) - float(start_lr)) / float(warmup_steps),
        bias=float(start_lr),
    )
    if isinstance(learning_rate, (float, int)):
        learning_rate = tensor.fill_constant(
            [1], "float32", float(learning_rate)
        )
    return nn.elementwise_add(
        nn.elementwise_mul(ramp, in_warm),
        nn.elementwise_mul(
            learning_rate, nn.scale(in_warm, scale=-1.0, bias=1.0)
        ),
    )
