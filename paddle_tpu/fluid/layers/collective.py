"""Collective layers (ref: python/paddle/fluid/layers/collective.py)."""
from ..layer_helper import LayerHelper

__all__ = ["_c_allreduce", "_c_allgather", "_c_broadcast",
           "_c_reducescatter", "_c_sync_calc_stream", "_c_sync_comm_stream"]


def _op(op_type, x, attrs=None, out_shape=None):
    helper = LayerHelper(op_type, x=x)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = out_shape if out_shape is not None else x.shape
    helper.append_op(
        type=op_type,
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs=attrs or {},
    )
    return out


def _c_allreduce(x, out=None, reduce_type="sum", ring_id=0,
                 use_calc_stream=False):
    return _op("c_allreduce_" + reduce_type, x, {"ring_id": ring_id})


def _c_allgather(x, nranks, ring_id=0, use_calc_stream=False):
    shape = None
    if x.shape is not None:
        shape = (x.shape[0] * nranks if x.shape[0] not in (None, -1) else -1,)\
            + tuple(x.shape[1:])
    return _op("c_allgather", x, {"ring_id": ring_id, "nranks": nranks},
               out_shape=shape)


def _c_broadcast(x, root=0, ring_id=0, use_calc_stream=False):
    return _op("c_broadcast", x, {"root": root, "ring_id": ring_id})


def _c_reducescatter(x, nranks, ring_id=0, use_calc_stream=False):
    shape = None
    if x.shape is not None:
        shape = (x.shape[0] // nranks if x.shape[0] not in (None, -1) else -1,)\
            + tuple(x.shape[1:])
    return _op("c_reducescatter", x, {"ring_id": ring_id, "nranks": nranks},
               out_shape=shape)


def _c_sync_calc_stream(x):
    return _op("c_sync_calc_stream", x)


def _c_sync_comm_stream(x, ring_id=0):
    return _op("c_sync_comm_stream", x, {"ring_id": ring_id})
