"""Tensor creation/manipulation layers (ref: python/paddle/fluid/layers/tensor.py)."""
import numpy as np

from .. import core
from .. import unique_name
from ..framework import Variable, default_main_program, in_dygraph_mode
from ..initializer import Constant, NumpyArrayInitializer
from ..layer_helper import LayerHelper

__all__ = [
    "create_tensor",
    "create_parameter",
    "create_global_var",
    "cast",
    "concat",
    "tensor_array_to_tensor",
    "sums",
    "assign",
    "fill_constant_batch_size_like",
    "fill_constant",
    "argmin",
    "argmax",
    "argsort",
    "ones",
    "zeros",
    "reverse",
    "has_inf",
    "has_nan",
    "isfinite",
    "range",
    "linspace",
    "zeros_like",
    "ones_like",
    "diag",
    "eye",
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(
        name=helper.name, dtype=dtype, persistable=persistable
    )


def create_parameter(
    shape,
    dtype,
    name=None,
    attr=None,
    is_bias=False,
    default_initializer=None,
):
    from ..param_attr import ParamAttr

    helper = LayerHelper("create_parameter", **locals())
    if attr is None:
        attr = ParamAttr(name=name)
    return helper.create_parameter(
        attr, shape, dtype, is_bias, default_initializer
    )


def create_global_var(
    shape, value, dtype, persistable=False, force_cpu=False, name=None
):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        dtype=dtype,
        shape=shape,
        persistable=persistable,
        name=name or unique_name.generate("global_var"),
    )
    helper.set_variable_initializer(var, Constant(value))
    if not persistable:
        # non-persistable global var: also materialize in main program
        helper.append_op(
            type="fill_constant",
            outputs={"Out": [var]},
            attrs={"shape": list(shape), "dtype": var.dtype, "value": float(value)},
        )
    return var


def cast(x, dtype):
    helper = LayerHelper("cast", **locals())
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = x.shape
    helper.append_op(
        type="cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"in_dtype": x.dtype, "out_dtype": core.convert_dtype(dtype)},
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", **locals())
    out = helper.create_variable_for_type_inference(
        dtype=helper.input_dtype()
    )
    shapes = [v.shape for v in input]
    if all(s is not None for s in shapes):
        ref = list(shapes[0])
        ax = axis if axis >= 0 else axis + len(ref)
        total = 0
        for s in shapes:
            total += s[ax] if s[ax] is not None else 0
        ref[ax] = total if all(s[ax] not in (None, -1) for s in shapes) else -1
        out.shape = tuple(ref)
    helper.append_op(
        type="concat",
        inputs={"X": input},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """Concat/stack a LoDTensorArray into one tensor (ref tensor.py
    tensor_array_to_tensor). Arrays here are build-time entry lists (see
    control_flow.create_array), so this composes concat/stack directly;
    also returns the per-entry sizes along axis like the reference."""
    import numpy as np

    from .nn import stack as _stack

    entries = [v for v in getattr(input, "vars", input) if v is not None]
    if not entries:
        raise ValueError("tensor_array_to_tensor: the array is empty")
    if use_stack:
        out = _stack(entries, axis=axis)
        sizes = [1] * len(entries)
    else:
        out = concat(entries, axis=axis)
        sizes = [
            (v.shape[axis] if v.shape is not None else -1) for v in entries
        ]
    out_index = assign(np.asarray(sizes, dtype="int32"))
    return out, out_index


def sums(input, out=None):
    helper = LayerHelper("sum", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=helper.input_dtype()
        )
        out.shape = input[0].shape
    helper.append_op(
        type="sum", inputs={"X": input}, outputs={"Out": [out]}
    )
    return out


def assign(input, output=None):
    helper = LayerHelper("assign", **locals())
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=input.dtype
            )
            output.shape = input.shape
        helper.append_op(
            type="assign", inputs={"X": [input]}, outputs={"Out": [output]}
        )
    elif isinstance(input, (np.ndarray, list, tuple, float, int)):
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=core.convert_dtype(arr.dtype)
            )
            output.shape = arr.shape
        helper.append_op(
            type="assign_value",
            outputs={"Out": [output]},
            attrs={
                "dtype": core.convert_dtype(arr.dtype),
                "shape": list(arr.shape),
                "values": arr.reshape(-1).tolist(),
            },
        )
    else:
        raise TypeError("assign: unsupported input %r" % (input,))
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = tuple(shape)
    helper.append_op(
        type="fill_constant",
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": core.convert_dtype(dtype),
            "value": float(value),
            "force_cpu": force_cpu,
        },
    )
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(
    input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0,
    force_cpu=False
):
    helper = LayerHelper("fill_constant_batch_size_like", **locals())
    out = helper.create_variable_for_type_inference(dtype=dtype)
    out.shape = tuple(shape[:output_dim_idx] + [-1] + shape[output_dim_idx + 1:]) \
        if input.shape is None else tuple(shape)
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": core.convert_dtype(dtype),
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    out.stop_gradient = True
    return out


def _arg_min_max(op_type, x, axis=0):
    helper = LayerHelper(op_type, x=x, axis=axis)
    out = helper.create_variable_for_type_inference("int64")
    if x.shape is not None:
        s = list(x.shape)
        ax = axis if axis >= 0 else axis + len(s)
        s.pop(ax)
        out.shape = tuple(s)
    helper.append_op(
        type=op_type,
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def argmin(x, axis=0):
    return _arg_min_max("arg_min", x, axis)


def argmax(x, axis=0):
    return _arg_min_max("arg_max", x, axis)


def argsort(input, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference("int64")
    out.shape = input.shape
    ids.shape = input.shape
    helper.append_op(
        type="argsort",
        inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [ids]},
        attrs={"axis": axis, "descending": descending},
    )
    return out, ids


def ones(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=1.0)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(shape=shape, dtype=dtype, value=0.0)


def reverse(x, axis):
    if isinstance(axis, int):
        axis = [axis]
    helper = LayerHelper("reverse", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(
        type="reverse",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return out


def _unary_bool(op_type, x, reduce_to_scalar=True):
    helper = LayerHelper(op_type, x=x)
    out = helper.create_variable_for_type_inference("bool")
    out.shape = ()
    helper.append_op(
        type=op_type, inputs={"X": [x]}, outputs={"Out": [out]}
    )
    return out


def has_inf(x):
    helper = LayerHelper("isinf", x=x)
    out = helper.create_variable_for_type_inference("bool")
    out.shape = ()
    helper.append_op(type="isinf_any", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def has_nan(x):
    helper = LayerHelper("isnan", x=x)
    out = helper.create_variable_for_type_inference("bool")
    out.shape = ()
    helper.append_op(type="isnan_any", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def isfinite(x):
    return _unary_bool("isfinite", x)


def range(start, end, step, dtype):
    helper = LayerHelper("range", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    try:
        n = int(np.ceil((float(end) - float(start)) / float(step)))
        out.shape = (n,)
    except (TypeError, ValueError):
        out.shape = (-1,)
    inputs = {}
    attrs = {"dtype": core.convert_dtype(dtype)}
    for key, val in (("Start", start), ("End", end), ("Step", step)):
        if isinstance(val, Variable):
            inputs[key] = [val]
        else:
            attrs[key.lower()] = float(val)
    helper.append_op(
        type="range", inputs=inputs, outputs={"Out": [out]}, attrs=attrs
    )
    return out


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    out.shape = (int(num),) if not isinstance(num, Variable) else (-1,)
    inputs = {}
    attrs = {"dtype": core.convert_dtype(dtype)}
    for key, val in (("Start", start), ("Stop", stop), ("Num", num)):
        if isinstance(val, Variable):
            inputs[key] = [val]
        else:
            attrs[key.lower()] = val
    helper.append_op(
        type="linspace", inputs=inputs, outputs={"Out": [out]}, attrs=attrs
    )
    return out


def zeros_like(x, out=None):
    helper = LayerHelper("zeros_like", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(
        type="fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]}
    )
    return out


def ones_like(x, out=None):
    helper = LayerHelper("ones_like", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    helper.append_op(
        type="fill_constant_batch_size_like",
        inputs={"Input": [x]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(x.shape if x.shape else (1,)),
            "dtype": x.dtype,
            "value": 1.0,
        },
    )
    return out


def diag(diagonal):
    helper = LayerHelper("diag", **locals())
    out = helper.create_variable_for_type_inference(diagonal.dtype)
    if diagonal.shape:
        out.shape = (diagonal.shape[0], diagonal.shape[0])
    helper.append_op(
        type="diag", inputs={"Diagonal": [diagonal]}, outputs={"Out": [out]}
    )
    return out


def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32"):
    helper = LayerHelper("eye", **locals())
    out = helper.create_variable_for_type_inference(dtype)
    nc = num_columns or num_rows
    out.shape = (num_rows, nc)
    helper.append_op(
        type="eye",
        outputs={"Out": [out]},
        attrs={
            "num_rows": num_rows,
            "num_columns": nc,
            "dtype": core.convert_dtype(dtype),
        },
    )
    if batch_shape:
        from . import nn

        for b in reversed(batch_shape):
            out = nn.expand(nn.unsqueeze(out, [0]), [b] + [1] * (len(out.shape)))
    return out
