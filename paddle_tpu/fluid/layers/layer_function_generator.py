"""layers.layer_function_generator (ref: fluid/layers/
layer_function_generator.py — generates layer functions + docs from
the C++ op protos).

Here ops have no protobuf protos; the generator builds layer functions
over the jax lowering registry instead: ``generate_layer_fn(op_type)``
returns a function appending that op with the conventional X/Y->Out
slots (exactly what the reference's generated activations do), and the
doc decorators are functional (they format the docstring templates the
reference's layers use).
"""
import re

from ..framework import Variable
from ..layer_helper import LayerHelper

__all__ = [
    "generate_layer_fn", "generate_activation_fn", "autodoc",
    "templatedoc", "add_sample_code",
]


def _check_registered(op_type):
    from ...ops.registry import LOWERINGS

    if op_type not in LOWERINGS:
        raise ValueError(
            "op %r has no registered lowering; cannot generate a layer "
            "function for it" % op_type)


def generate_layer_fn(op_type):
    """A layer function for a conventional (X[, Y]) -> Out op
    (ref layer_function_generator.py:87)."""
    _check_registered(op_type)

    def func(*args, **kwargs):
        helper = LayerHelper(op_type, **kwargs)
        inputs = {}
        vars_in = list(args) + [
            kwargs[k] for k in ("x", "y", "input") if k in kwargs
        ]
        slots = ["X", "Y", "Z"]
        for slot, v in zip(slots, vars_in):
            inputs[slot] = [v]
        out = helper.create_variable_for_type_inference(
            vars_in[0].dtype if vars_in else "float32")
        if vars_in and isinstance(vars_in[0], Variable) and \
                vars_in[0].shape is not None:
            out.shape = vars_in[0].shape
        attrs = {k: v for k, v in kwargs.items()
                 if k not in ("x", "y", "input", "name")
                 and not isinstance(v, Variable)}
        helper.append_op(type=op_type, inputs=inputs,
                         outputs={"Out": [out]}, attrs=attrs)
        return out

    func.__name__ = op_type
    func.__doc__ = "Generated layer for the %r lowering." % op_type
    return func


def generate_activation_fn(op_type):
    """A unary activation layer (ref :190)."""
    _check_registered(op_type)

    def func(x, name=None):
        helper = LayerHelper(op_type, **locals())
        out = helper.create_variable_for_type_inference(x.dtype)
        if x.shape is not None:
            out.shape = x.shape
        helper.append_op(type=op_type, inputs={"X": [x]},
                         outputs={"Out": [out]})
        return out

    func.__name__ = op_type
    func.__doc__ = "%s activation (generated)." % op_type
    return func


def autodoc(comment=""):
    """Docstring decorator (ref :250): prepends ``comment``."""

    def __impl__(func):
        func.__doc__ = comment + (func.__doc__ or "")
        return func

    return __impl__


def templatedoc(op_type=None):
    """Fill ``${comment}``-style slots in a docstring (ref :264). The
    per-op C++ comments do not exist here; slots resolve to the op
    type name so the docs stay readable."""

    def __impl__(func):
        doc = func.__doc__ or ""
        name = op_type or func.__name__
        doc = re.sub(r"\$\{comment\}", "the %s op" % name, doc)
        doc = re.sub(r"\$\{(\w+)_comment\}", r"\1", doc)
        doc = re.sub(r"\$\{(\w+)_type\}", r"\1", doc)
        func.__doc__ = doc
        return func

    return __impl__


def add_sample_code(func, sample_code):
    """Append an Examples section (ref :330)."""
    func.__doc__ = (func.__doc__ or "") + sample_code
