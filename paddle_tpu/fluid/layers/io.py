"""Data input layers (ref: python/paddle/fluid/layers/io.py data())."""
from .. import core
from ..framework import default_main_program, default_startup_program

__all__ = ["data"]


def data(
    name,
    shape,
    append_batch_size=True,
    dtype="float32",
    lod_level=0,
    type=core.VarType.LOD_TENSOR,
    stop_gradient=True,
):
    """Declare a feed variable (ref layers/io.py:data). With
    append_batch_size=True a leading -1 batch dim is added."""
    helper_shape = list(shape)
    if append_batch_size:
        helper_shape = [-1] + helper_shape
    block = default_main_program().current_block()
    main = block.create_var(
        name=name,
        shape=helper_shape,
        dtype=dtype,
        type=type,
        stop_gradient=stop_gradient,
        lod_level=lod_level,
        is_data=True,
        need_check_feed=True,
    )
    if lod_level and lod_level > 0:
        # TPU-native LoD: sequences are fed dense-padded with a companion
        # per-row length vector (see fluid/lod.py); sequence_* layers wire
        # this var into their SeqLen slot.
        block.create_var(
            name=name + "@SEQ_LEN",
            shape=[-1],
            dtype="int32",
            stop_gradient=True,
            is_data=True,
        )
    return main
